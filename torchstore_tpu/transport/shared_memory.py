"""Shared-memory transport: the same-host fast path.

TPU-native equivalent of /root/reference/torchstore/transport/shared_memory.py
(:41-523). The storage volume owns tensor storage living in POSIX shared
memory (``/dev/shm`` files + mmap — same substrate as ``shm_open``, and the
ABI the native C++ backend accelerates); clients copy directly into/out of
those segments, so a put is exactly one memcpy client-side and zero copies
server-side (the volume's stored array IS a view of the segment).

PUT:  handshake returns existing descriptors for reuse -> client allocates or
      attaches + copies -> volume attaches and stores the view.
GET:  volume returns a descriptor — zero-copy when the entry already lives in
      one of its segments, else a staged copy whose ownership transfers to
      the client (client unlinks after landing it).

Caches: ``ShmServerCache`` (volume side: key -> owned segment),
``ShmClientCache`` (client side: segment name -> attachment), both invalidated
per-key on delete (reference cache semantics, shared_memory.py:56-131).
"""

from __future__ import annotations

import mmap
import os
import uuid
from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

from torchstore_tpu.config import StoreConfig
from torchstore_tpu.logging import get_logger
from torchstore_tpu.native import fast_copy
from torchstore_tpu.transport.buffers import (
    TransportBuffer,
    TransportCache,
    TransportContext,
)
from torchstore_tpu.transport.types import Request, TensorMeta

logger = get_logger("torchstore_tpu.transport.shm")

SHM_DIR = "/dev/shm"


def is_available() -> bool:
    return os.path.isdir(SHM_DIR) and os.access(SHM_DIR, os.W_OK)


def reap_orphaned_segments() -> int:
    """Unlink ts_shm_* segments whose creating process is gone (crashed
    volumes/clients leave them behind; nothing else ever cleans /dev/shm).
    Safe: segment names embed the creator pid, and a dead pid's segments
    can have no owner left. Called at volume startup."""
    reaped = 0
    try:
        names = os.listdir(SHM_DIR)
    except OSError:
        return 0
    for name in names:
        if not name.startswith("ts_shm_"):
            continue
        parts = name.split("_")
        try:
            pid = int(parts[2])
        except (IndexError, ValueError):
            continue
        if not _pid_alive(pid):
            try:
                os.unlink(os.path.join(SHM_DIR, name))
                reaped += 1
            except OSError:
                pass
    if reaped:
        logger.info("reaped %d orphaned shm segments", reaped)
    return reaped


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # exists, owned by someone else — leave it alone


# --------------------------------------------------------------------------
# segments
# --------------------------------------------------------------------------


class ShmSegment:
    """A named shared-memory segment (file in /dev/shm + mmap)."""

    def __init__(self, name: str, size: int, mm: mmap.mmap, owner: bool):
        self.name = name
        self.size = size
        self.mmap = mm
        self.owner = owner
        self._closed = False

    @staticmethod
    def _path(name: str) -> str:
        return os.path.join(SHM_DIR, name)

    @classmethod
    def create(cls, size: int, name: Optional[str] = None) -> "ShmSegment":
        name = name or f"ts_shm_{os.getpid()}_{uuid.uuid4().hex[:12]}"
        fd = os.open(cls._path(name), os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
        try:
            os.ftruncate(fd, size)
            mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        return cls(name, size, mm, owner=True)

    @classmethod
    def attach(cls, name: str, size: int) -> "ShmSegment":
        fd = os.open(cls._path(name), os.O_RDWR)
        try:
            mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        return cls(name, size, mm, owner=False)

    def view(self, meta: TensorMeta, offset: int = 0) -> np.ndarray:
        return np.frombuffer(
            self.mmap, dtype=meta.np_dtype, count=int(np.prod(meta.shape) or 1), offset=offset
        ).reshape(meta.shape)

    def rename_to_owner(self) -> None:
        """Rename the segment so its name embeds THIS process's pid. Volumes
        call this when adopting a client-created segment: the pid in a
        segment name must always be its current owner, or the orphan reaper
        could unlink live volume storage after the creating client exits."""
        new_name = f"ts_shm_{os.getpid()}_{uuid.uuid4().hex[:12]}"
        os.rename(self._path(self.name), self._path(new_name))
        self.name = new_name

    def unlink(self) -> None:
        try:
            os.unlink(self._path(self.name))
        except FileNotFoundError:
            pass

    def close(self) -> None:
        # The mmap stays open while numpy views reference it; python frees the
        # mapping at GC. Unlink only removes the name.
        self._closed = True


@dataclass
class ShmDescriptor:
    """Picklable handle to a tensor inside a segment."""

    segment_name: str
    segment_size: int
    meta: TensorMeta
    offset: int = 0
    # 'volume' -> long-lived, volume owns; 'client' -> staged for one get,
    # the client unlinks after landing the data.
    owner: str = "volume"


# --------------------------------------------------------------------------
# caches
# --------------------------------------------------------------------------


STAGED_TTL_S = 120.0


class ShmServerCache(TransportCache):
    """Volume-side: (key, shard coords|None) -> (segment, meta) for segments
    that back stored tensors/shards, plus staged-get segments awaiting client
    pickup (normally unlinked by the client; reaped here after a TTL so a
    crashed client cannot fill /dev/shm)."""

    def __init__(self) -> None:
        self.by_key: dict[str, dict[Optional[tuple], tuple[ShmSegment, TensorMeta]]] = {}
        self.staged: dict[str, tuple[ShmSegment, float]] = {}

    def track_staged(self, seg: ShmSegment) -> None:
        import time

        now = time.monotonic()
        self.staged[seg.name] = (seg, now)
        for name, (old, ts) in list(self.staged.items()):
            if now - ts > STAGED_TTL_S:
                old.unlink()  # no-op if the client already unlinked it
                del self.staged[name]

    def lookup(self, key: str, coords: Optional[tuple]):
        return self.by_key.get(key, {}).get(coords)

    def put(
        self, key: str, coords: Optional[tuple], seg: ShmSegment, meta: TensorMeta
    ) -> None:
        entries = self.by_key.setdefault(key, {})
        prev = entries.get(coords)
        if prev is not None and prev[0].name != seg.name:
            prev[0].unlink()
        entries[coords] = (seg, meta)

    def segments_for(self, key: str):
        return [seg for seg, _ in self.by_key.get(key, {}).values()]

    def delete_key(self, key: str) -> None:
        for seg, _ in self.by_key.pop(key, {}).values():
            seg.unlink()

    def clear(self) -> None:
        for entries in self.by_key.values():
            for seg, _ in entries.values():
                seg.unlink()
        self.by_key.clear()
        for seg, _ in self.staged.values():
            seg.unlink()
        self.staged.clear()


class ShmClientCache(TransportCache):
    """Client-side: segment name -> attachment, so repeat transfers skip the
    open+mmap syscalls. Keyed back to store keys for invalidation."""

    def __init__(self) -> None:
        self.segments: dict[str, ShmSegment] = {}
        self.key_to_segments: dict[str, set[str]] = {}

    def attach(self, desc: ShmDescriptor, key: str) -> ShmSegment:
        seg = self.segments.get(desc.segment_name)
        if seg is None:
            seg = ShmSegment.attach(desc.segment_name, desc.segment_size)
            self.segments[desc.segment_name] = seg
        self.key_to_segments.setdefault(key, set()).add(desc.segment_name)
        return seg

    def delete_key(self, key: str) -> None:
        for name in self.key_to_segments.pop(key, ()):  # drop attachments
            seg = self.segments.pop(name, None)
            if seg is not None:
                seg.close()

    def clear(self) -> None:
        for seg in self.segments.values():
            seg.close()
        self.segments.clear()
        self.key_to_segments.clear()


# --------------------------------------------------------------------------
# the transport buffer
# --------------------------------------------------------------------------


class SharedMemoryTransportBuffer(TransportBuffer):
    requires_handshake = True
    supports_inplace = True
    requires_contiguous_inplace = False
    supports_batch_puts = True
    supports_batch_gets = True

    def __init__(self, config: Optional[StoreConfig] = None):
        self.config = config
        self.descriptors: dict[int, ShmDescriptor] = {}
        self.objects: dict[int, Any] = {}
        # Client-only staging state (never pickled).
        self._client_segments: dict[int, ShmSegment] = {}
        self._reuse: dict[int, ShmDescriptor] = {}

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_client_segments"] = {}
        state["_reuse"] = {}
        state["config"] = None
        return state

    # ---- client: put -----------------------------------------------------

    def _post_handshake(self, volume, requests, reply, op) -> None:
        if op != "put":
            return
        cache: ShmClientCache = volume.transport_context.get_cache(ShmClientCache)
        offered: dict[int, ShmDescriptor] = reply or {}
        for idx, req in enumerate(requests):
            if req.is_object:
                self.objects[idx] = req.objects
                continue
            arr = np.ascontiguousarray(req.tensor_val)
            meta = TensorMeta.of(arr)
            desc = offered.get(idx)
            if desc is not None and desc.meta == meta:
                seg = cache.attach(desc, req.key)
            else:
                seg = ShmSegment.create(max(arr.nbytes, 1))
                desc = ShmDescriptor(seg.name, seg.size, meta)
                cache.segments[seg.name] = seg
                cache.key_to_segments.setdefault(req.key, set()).add(seg.name)
            # THE hot memcpy: client array -> shared segment (native
            # multi-threaded path on multi-core hosts).
            fast_copy(seg.view(meta, desc.offset), arr)
            self.descriptors[idx] = desc
            self._client_segments[idx] = seg

    # ---- server: put -----------------------------------------------------

    def recv_handshake(
        self, ctx: TransportContext, metas: list[Request], existing: dict, op: str
    ) -> Any:
        if op != "put":
            return None
        cache: ShmServerCache = ctx.get_cache(ShmServerCache)
        offered: dict[int, ShmDescriptor] = {}
        for idx, meta in enumerate(metas):
            if meta.tensor_meta is None:
                continue
            coords = meta.tensor_slice.coordinates if meta.tensor_slice else None
            entry = cache.lookup(meta.key, coords)
            if entry is None:
                continue
            seg, stored_meta = entry
            if stored_meta == meta.tensor_meta:
                # Same shape/dtype: offer the existing segment for in-place
                # reuse (descriptor-reuse handshake, reference
                # shared_memory.py:340-360).
                offered[idx] = ShmDescriptor(seg.name, seg.size, stored_meta)
        return offered

    def handle_put_request(
        self, ctx: TransportContext, metas: list[Request], existing: dict
    ) -> dict[int, Any]:
        cache: ShmServerCache = ctx.get_cache(ShmServerCache)
        out: dict[int, Any] = {}
        for idx, obj in self.objects.items():
            out[idx] = obj
        for idx, desc in self.descriptors.items():
            meta = metas[idx]
            coords = meta.tensor_slice.coordinates if meta.tensor_slice else None
            current = cache.lookup(meta.key, coords)
            if current is not None and current[0].name == desc.segment_name:
                seg = current[0]
            else:
                seg = ShmSegment.attach(desc.segment_name, desc.segment_size)
                seg.owner = True  # volume takes ownership of the lifetime
                # The name's pid must track ownership (see rename_to_owner);
                # future handshakes/gets serve the new name from the cache.
                seg.rename_to_owner()
            cache.put(meta.key, coords, seg, desc.meta)
            out[idx] = seg.view(desc.meta, desc.offset)
        return out

    # ---- server: get -----------------------------------------------------

    def handle_get_request(
        self, ctx: TransportContext, metas: list[Request], entries: list[Any]
    ) -> None:
        cache: ShmServerCache = ctx.get_cache(ShmServerCache)
        for idx, (meta, entry) in enumerate(zip(metas, entries)):
            if meta.is_object:
                self.objects[idx] = entry
                continue
            entry = np.asarray(entry)
            served = next(
                (
                    seg
                    for seg in cache.segments_for(meta.key)
                    if _aliases_whole(entry, seg)
                ),
                None,
            )
            if served is not None:
                self.descriptors[idx] = ShmDescriptor(
                    served.name, served.size, TensorMeta.of(entry)
                )
                continue
            contig = np.ascontiguousarray(entry)
            seg = ShmSegment.create(max(contig.nbytes, 1))
            tmeta = TensorMeta.of(contig)
            fast_copy(seg.view(tmeta), contig)
            # Ownership transfers to the client, which unlinks after landing;
            # the server reaps it after a TTL if the client never does.
            cache.track_staged(seg)
            self.descriptors[idx] = ShmDescriptor(
                seg.name, seg.size, tmeta, owner="client"
            )

    # ---- client: get -----------------------------------------------------

    def _handle_storage_volume_response(
        self, volume, remote: "SharedMemoryTransportBuffer", requests
    ) -> list[Any]:
        cache: ShmClientCache = volume.transport_context.get_cache(ShmClientCache)
        mutable = bool(self.config and self.config.mutable_shm)
        results: list[Any] = []
        for idx, req in enumerate(requests):
            if req.is_object or idx in remote.objects:
                results.append(remote.objects[idx])
                continue
            desc = remote.descriptors[idx]
            if desc.owner == "client":
                seg = ShmSegment.attach(desc.segment_name, desc.segment_size)
                src = seg.view(desc.meta, desc.offset)
                landed = self._land(req, src)
                seg.unlink()
                results.append(landed)
            else:
                seg = cache.attach(desc, req.key)
                src = seg.view(desc.meta, desc.offset)
                if mutable and req.destination_view is None:
                    # Zero-copy read: caller sees the live segment. Mutations
                    # by later puts become visible — opt-in via config.
                    results.append(src)
                else:
                    results.append(self._land(req, src))
        return results

    @staticmethod
    def _land(req: Request, src: np.ndarray) -> np.ndarray:
        if req.destination_view is not None:
            fast_copy(req.destination_view, src)
            return req.destination_view
        return src.copy()

    def drop(self) -> None:
        self.descriptors = {}
        self.objects = {}
        self._client_segments = {}
        self._reuse = {}


def _aliases_whole(entry: np.ndarray, seg: ShmSegment) -> bool:
    """True when ``entry`` is exactly the array stored over ``seg``'s buffer
    start (whole-tensor fetch of a SHM-backed entry -> zero-copy get)."""
    if not entry.flags["C_CONTIGUOUS"]:
        return False
    try:
        seg_start = np.frombuffer(seg.mmap, dtype=np.uint8, count=1).__array_interface__[
            "data"
        ][0]
    except ValueError:
        return False
    start = entry.__array_interface__["data"][0]
    return start == seg_start and entry.nbytes <= seg.size
