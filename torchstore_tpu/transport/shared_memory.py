"""Shared-memory transport: the same-host fast path.

TPU-native equivalent of /root/reference/torchstore/transport/shared_memory.py
(:41-523). The storage volume owns tensor storage living in POSIX shared
memory (``/dev/shm`` files + mmap — same substrate as ``shm_open``, and the
ABI the native C++ backend accelerates); clients copy directly into/out of
those segments, so a put is exactly one memcpy client-side and zero copies
server-side (the volume's stored array IS a view of the segment).

PUT:  handshake returns existing/pooled descriptors for reuse -> client
      allocates or attaches + copies -> volume attaches and stores the view.
GET:  the volume serves an (offset, strides) descriptor into its own segment
      whenever the requested data is segment-backed — including arbitrary
      sub-slices of stored shards (the reference's descriptor-view serve,
      shared_memory.py:133-198) — so the server side is always zero-copy.
      A client with an in-place destination copies once; a client without one
      KEEPS the view: gets are zero-copy by default.

Safety of zero-copy reads (replaces an earlier opt-in ``mutable_shm`` flag):
the volume lease-counts every descriptor it serves, and a put NEVER writes
into a live entry segment — each put lands in a pooled (or fresh) segment
and the previous one is *retired* until every lease is released, then
recycled. Data a reader views — or is mid-copy out of — is therefore
immutable for the life of the read. Clients track served views with
weakrefs and piggyback release notices on their next RPC; released segments
return to a volume-side free pool, so the steady state of a put/get loop
recycles warm segments instead of allocating (double-buffer rotation).

Caches: ``ShmServerCache`` (volume side: entries, leases, retired/free
pools, staged-get TTLs), ``ShmClientCache`` (client side: attachments +
view weakrefs), both invalidated per-key on delete (reference cache
semantics, shared_memory.py:56-131).

One-sided warm gets (the "RPC Considered Harmful" data plane): every entry
carries a slot in a per-volume **stamp table** — a shared-memory array of
uint64 seqlock words (even = stable, odd = write-in-flight), bumped by the
volume around every landing that can change what the entry's bytes mean
(replace, in-place overwrite, delete, repair pull). Get descriptors are
annotated with (stamp segment, slot, generation); the client caches them as
one-sided plans and serves warm repeat gets by ``stamped_read_batch``:
check the stamp, memcpy straight out of the pre-attached segment through
the landing pool, re-check the stamp — ZERO RPCs. Any mismatch (replaced
entry, writer in flight, torn copy, unlinked segment) invalidates the plan
and falls back loudly to the RPC path (``ts_one_sided_fallbacks_total``);
a post-copy stamp change additionally counts ``ts_one_sided_torn_total``
and discards the copy, so mixed-generation bytes are never served. The
protocol leans on two existing invariants: puts never write a live entry
segment (so an even, matching stamp means the mapped bytes are the exact
generation the plan was built against), and a retired segment can only be
re-offered to a writer AFTER the replacing put bumped the entry stamp (so
a reader that raced the recycling always sees the bump on its re-check).
Staleness is bounded exactly like the location cache: a detached replica
serves its last committed generation until the reclaim deletes it (stamp
tombstone), never torn bytes.
"""

from __future__ import annotations

import math
import mmap
import os
import time
import uuid
import weakref
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

from torchstore_tpu import faults
from torchstore_tpu.config import StoreConfig, default_config
from torchstore_tpu.logging import get_logger
from torchstore_tpu.native import copy_into, fast_copy
from torchstore_tpu.observability import ledger as obs_ledger
from torchstore_tpu.observability import metrics as obs_metrics
from torchstore_tpu.observability import profile as obs_profile
from torchstore_tpu.observability import timeline as obs_timeline
from torchstore_tpu.utils import spawn_logged
from torchstore_tpu.transport import landing
from torchstore_tpu.transport.buffers import (
    TransportBuffer,
    TransportCache,
    TransportContext,
)
from torchstore_tpu.transport.types import Request, TensorMeta

logger = get_logger("torchstore_tpu.transport.shm")

# Segment-pool economics. Offer hits/misses are counted where the decision
# is made: the server's handshake (volume process) and the client's
# post-handshake landing (client process) each see their own side.
_POOL_OFFERS = obs_metrics.counter(
    "ts_shm_pool_offers_total",
    "Put-handshake segment offers by outcome (spare/pooled/miss)",
)
_SEGMENTS_CREATED = obs_metrics.counter(
    "ts_shm_segments_created_total", "Fresh /dev/shm segments created"
)
_SEGMENTS_RECYCLED = obs_metrics.counter(
    "ts_shm_segments_recycled_total", "Segments drawn from the warm free pool"
)
_SEGMENTS_REAPED = obs_metrics.counter(
    "ts_shm_segments_reaped_total", "Segments unlinked by TTL sweep, by kind"
)
_CLIENT_ATTACH = obs_metrics.counter(
    "ts_shm_client_attach_total",
    "Client-side segment handling on put (offer_hit / cold_create)",
)
_POOL_BYTES = obs_metrics.gauge(
    "ts_shm_pool_bytes", "Bytes held in the volume's warm free pool"
)
_RETIRED_SEGMENTS = obs_metrics.gauge(
    "ts_shm_retired_segments", "Viewed-then-replaced segments awaiting release"
)
_RESERVED_SEGMENTS = obs_metrics.gauge(
    "ts_shm_reserved_segments", "Handshake-offered segments awaiting their put"
)

# One-sided data-plane instruments (client side). Shared by the SHM stamped
# read and the bulk doorbell (transport label distinguishes them).
ONE_SIDED_READS = obs_metrics.counter(
    "ts_one_sided_reads_total",
    "Warm gets served one-sided (zero RPCs), by transport",
)
ONE_SIDED_FALLBACKS = obs_metrics.counter(
    "ts_one_sided_fallbacks_total",
    "One-sided attempts that fell back to the RPC path, by reason",
)
ONE_SIDED_TORN = obs_metrics.counter(
    "ts_one_sided_torn_total",
    "One-sided reads discarded because the stamp moved mid-copy, by transport",
)

SHM_DIR = "/dev/shm"

STAGED_TTL_S = 120.0  # staged-get segments a crashed client never unlinked
RETIRED_TTL_S = 600.0  # viewed-then-replaced segments never released
RESERVED_TTL_S = 60.0  # handshake offers whose put never arrived

# Puts at or under this ride INLINE in the put RPC (pickle-5 out-of-band
# frames) instead of negotiating a segment handshake first: one RPC instead
# of two — the small-op fast path. The volume still lands them in (pooled)
# segments, so zero-copy gets work identically.
SMALL_INLINE_BYTES = 64 * 1024

# Handshake-reply key for the batch's shared arena segment offer; request
# indices are always >= 0 so -1 can never collide.
ARENA_OFFER_KEY = -1

# Stamp-table capacity: one uint64 seqlock word per live (key, coords)
# entry. 64K slots = a 512 KB segment; entries beyond capacity simply are
# not one-sided-servable (their gets stay on the RPC path).
STAMP_SLOTS = 1 << 16

# A one-sided get WITHOUT a destination must copy (a zero-copy view of a
# recyclable segment cannot be stamp-re-checked after it is handed out), so
# above this size the RPC path's zero-copy snapshot view wins and the
# one-sided path stands down. In-place gets copy on both paths, so they go
# one-sided at any size.
ONE_SIDED_COPY_MAX = 4 << 20

# The OneSidedMiss reasons that invalidate the cached plan (the bytes or
# stamps the plan points at moved/vanished): the fallback RPC serve
# re-records a fresh plan. Other reasons (e.g. shape policy) keep it.
PLAN_DROPPING_MISSES = frozenset(
    {"stale_stamp", "torn", "segment_gone", "stamp_table_gone"}
)


def covered_plan(
    one_sided: dict, key: str, slice_key, has_dest: bool
) -> Optional[dict]:
    """The cached one-sided plan for ``(key, slice_key)`` IF the one-sided
    path may serve it — the single coverage predicate every client-side
    coverage check shares. A destination-less get above
    ``ONE_SIDED_COPY_MAX`` stands down to the RPC zero-copy path (standing
    policy, not a fallback), so it reports uncovered."""
    plan = one_sided.get((key, slice_key))
    if plan is None or (
        not has_dest and plan["nbytes"] > ONE_SIDED_COPY_MAX
    ):
        return None
    return plan


def is_available() -> bool:
    return os.path.isdir(SHM_DIR) and os.access(SHM_DIR, os.W_OK)


def shm_available_bytes() -> int:
    """Free bytes in /dev/shm right now (0 when unreadable). Provisioners
    clamp against this: tmpfs pages are allocated by WRITES, and a write
    past tmpfs-full raises SIGBUS — not an exception any try/except can
    catch — so pre-faulting must never be allowed to run past it."""
    try:
        st = os.statvfs(SHM_DIR)
        return int(st.f_frsize * st.f_bavail)
    except OSError:
        return 0


def reap_orphaned_segments() -> int:
    """Unlink ts_shm_* segments whose creating process is gone (crashed
    volumes/clients leave them behind; nothing else ever cleans /dev/shm).
    Safe: segment names embed the creator pid, and a dead pid's segments
    can have no owner left. Called at volume startup."""
    reaped = 0
    try:
        names = os.listdir(SHM_DIR)
    except OSError:
        return 0
    for name in names:
        if not name.startswith("ts_shm_"):
            continue
        parts = name.split("_")
        try:
            pid = int(parts[2])
        except (IndexError, ValueError):
            continue
        if not _pid_alive(pid):
            try:
                os.unlink(os.path.join(SHM_DIR, name))
                reaped += 1
            except OSError:
                pass
    if reaped:
        logger.info("reaped %d orphaned shm segments", reaped)
    return reaped


def _copy_obj(obj: Any) -> Any:
    """Value-semantics copy for object payloads on in-process dispatch."""
    import copy

    return copy.deepcopy(obj)


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # exists, owned by someone else — leave it alone


# --------------------------------------------------------------------------
# segments
# --------------------------------------------------------------------------


class ShmSegment:
    """A named shared-memory segment (file in /dev/shm + mmap)."""

    def __init__(self, name: str, size: int, mm: mmap.mmap, owner: bool):
        self.name = name
        self.size = size
        self.mmap = mm
        self.owner = owner
        self._closed = False
        self._base_addr: Optional[int] = None

    @staticmethod
    def _path(name: str) -> str:
        return os.path.join(SHM_DIR, name)

    # MAP_POPULATE batches page allocation + zeroing into the mmap call
    # instead of a trap per 4K page on first touch: measured 4.5x on the
    # COLD create+copy path (1.30s -> 0.29s per 256 MB on this host) — the
    # exact cost behind the bench's warm-path collapse (an RL loop's first
    # two syncs allocate fresh segment sets while the consumer still holds
    # snapshot leases on the old ones).
    _POPULATE = getattr(mmap, "MAP_POPULATE", 0)

    @classmethod
    def create(
        cls,
        size: int,
        name: Optional[str] = None,
        populate: bool = True,
        count: bool = True,
    ) -> "ShmSegment":
        """``populate=False`` skips MAP_POPULATE's eager page zeroing — for
        the volume's inline-put residual path, where actor dispatch must not
        stall on population (tiny segments fault their few pages during the
        landing copy instead). ``count=False`` keeps protocol-metadata
        segments (the stamp table) out of the pool-economics counter."""
        name = name or f"ts_shm_{os.getpid()}_{uuid.uuid4().hex[:12]}"
        fd = os.open(cls._path(name), os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
        try:
            os.ftruncate(fd, size)
            flags = mmap.MAP_SHARED | (cls._POPULATE if populate else 0)
            mm = mmap.mmap(fd, size, flags=flags)
        finally:
            os.close(fd)
        if count:
            _SEGMENTS_CREATED.inc()
        return cls(name, size, mm, owner=True)

    @classmethod
    def create_provisioned(
        cls, size: int, hugepages: bool = True, nthreads: int = 0
    ) -> "ShmSegment":
        """Cold-start provisioning variant of ``create``: map WITHOUT
        MAP_POPULATE, advise transparent huge pages while the range is still
        untouched (the advice must precede the faults to influence them),
        then prefault every page with the native multi-threaded entry
        (``tsnative.cc ts_prefault``; single-thread touch fallback). Used by
        the prewarm path to build the volume's warm pool off the first
        sync's critical path."""
        name = f"ts_shm_{os.getpid()}_{uuid.uuid4().hex[:12]}"
        fd = os.open(cls._path(name), os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
        try:
            os.ftruncate(fd, size)
            mm = mmap.mmap(fd, size, flags=mmap.MAP_SHARED)
        finally:
            os.close(fd)
        _SEGMENTS_CREATED.inc()
        seg = cls(name, size, mm, owner=True)
        if hugepages:
            seg.madvise_hugepage()
        seg.prefault(nthreads)
        return seg

    def madvise_hugepage(self) -> None:
        """Advise the kernel to back this mapping with transparent huge
        pages (fewer TLB misses on the hot memcpy). Fail-open: kernels
        without THP-on-shmem, or mmap modules without MADV_HUGEPAGE, leave
        the mapping on plain pages."""
        advice = getattr(mmap, "MADV_HUGEPAGE", None)
        if advice is None or self.size == 0:
            return
        try:
            self.mmap.madvise(advice)
        except (OSError, ValueError):
            pass

    def prefault(self, nthreads: int = 0) -> None:
        """Touch every page so later copies into this segment never
        soft-fault. Native multi-threaded path when the v2 library is
        present; single-thread stride touch otherwise."""
        if self.size == 0:
            return
        from torchstore_tpu import native as native_mod

        addr = self.base_addr()
        if addr is not None and native_mod.prefault(addr, self.size, nthreads):
            return
        view = np.frombuffer(self.mmap, dtype=np.uint8)
        view[::4096] = 0  # page starts are 4096-multiples: every page hit

    @classmethod
    def attach(cls, name: str, size: int, populate: bool = False) -> "ShmSegment":
        """``populate=True`` pre-wires the mapping's page tables (pages
        already exist — the creator populated them) so a big copy into the
        attachment skips per-page soft faults. Leave False for attachments
        that never touch the bytes (the volume's zero-copy descriptor
        serving) — wiring there is pure put-RPC overhead."""
        fd = os.open(cls._path(name), os.O_RDWR)
        try:
            flags = mmap.MAP_SHARED | (cls._POPULATE if populate else 0)
            mm = mmap.mmap(fd, size, flags=flags)
        finally:
            os.close(fd)
        return cls(name, size, mm, owner=False)

    def base_addr(self) -> Optional[int]:
        """Address of the mapping's first byte in THIS process (used to test
        whether a stored array aliases this segment)."""
        if self._base_addr is None:
            if self.size == 0:
                return None
            self._base_addr = np.frombuffer(
                self.mmap, dtype=np.uint8, count=1
            ).__array_interface__["data"][0]
        return self._base_addr

    def view(self, meta: TensorMeta, offset: int = 0) -> np.ndarray:
        # math.prod, not np.prod: this runs once per member on the warm
        # one-sided batch path, and the ufunc reduction is ~30x the cost
        # of the builtin on the small shape tuples that dominate there.
        count = math.prod(meta.shape)
        if count == 0:
            # Zero-size tensors carry no bytes; an empty array of the right
            # shape/dtype IS the value (np.frombuffer(count=0) would also
            # work but the reshape from the `or 1` minimum-map hack can't).
            return np.empty(meta.shape, meta.np_dtype)
        return np.frombuffer(
            self.mmap, dtype=meta.np_dtype, count=count, offset=offset
        ).reshape(meta.shape)

    def strided_view(
        self, meta: TensorMeta, offset: int, strides: Optional[tuple[int, ...]]
    ) -> np.ndarray:
        """View with explicit byte strides — serves sub-slices of stored
        shards without staging (descriptor-view serve)."""
        if strides is None:
            return self.view(meta, offset)
        return np.ndarray(
            meta.shape,
            dtype=meta.np_dtype,
            buffer=self.mmap,
            offset=offset,
            strides=strides,
        )

    def rename_to_owner(self) -> None:
        """Rename the segment so its name embeds THIS process's pid. Volumes
        call this when adopting a client-created segment: the pid in a
        segment name must always be its current owner, or the orphan reaper
        could unlink live volume storage after the creating client exits."""
        new_name = f"ts_shm_{os.getpid()}_{uuid.uuid4().hex[:12]}"
        os.rename(self._path(self.name), self._path(new_name))
        self.name = new_name

    def unlink(self) -> None:
        try:
            os.unlink(self._path(self.name))
        except FileNotFoundError:
            pass

    def close(self) -> None:
        # The mmap stays open while numpy views reference it; python frees the
        # mapping at GC. Unlink only removes the name.
        self._closed = True


class StampTable:
    """Per-volume shared array of per-entry seqlock words.

    Word semantics: even = entry stable at that generation; odd = a write
    that can change the entry's bytes/placement is in flight. Values only
    ever increase (slots are reused across entries without reset), so a
    reader comparing against the generation its plan recorded can never be
    fooled by wrap-behind. Aligned 8-byte loads/stores of the numpy view
    are single instructions on the platforms this runs on; the protocol
    additionally re-checks after the copy, so even a torn stamp read only
    costs a spurious fallback, never wrong data."""

    def __init__(self, seg: ShmSegment) -> None:
        self.seg = seg
        self.words = np.frombuffer(seg.mmap, dtype=np.uint64)

    @classmethod
    def create(cls) -> "StampTable":
        # populate=True zeroes every word: slot generation starts at 0.
        # count=False: the table is protocol metadata, not pool economics —
        # its lazy creation must not move ts_shm_segments_created_total
        # across a prewarmed first put.
        return cls(ShmSegment.create(STAMP_SLOTS * 8, count=False))

    @classmethod
    def attach(cls, name: str, size: int) -> "StampTable":
        return cls(ShmSegment.attach(name, size, populate=True))

    def read(self, slot: int) -> int:
        return int(self.words[slot])

    def write(self, slot: int, value: int) -> None:
        self.words[slot] = value


@dataclass
class ShmDescriptor:
    """Picklable handle to a tensor inside a segment."""

    segment_name: str
    segment_size: int
    meta: TensorMeta
    offset: int = 0
    # Byte strides for non-contiguous views (sub-slices of stored shards);
    # None means C-contiguous at ``offset``.
    strides: Optional[tuple[int, ...]] = None
    # 'volume' -> long-lived, volume owns; 'client' -> staged for one get,
    # the client unlinks after landing the data.
    owner: str = "volume"
    # One-sided annotation: (stamp segment name, stamp segment size, slot,
    # generation at serve time). Present only for volume-owned serves whose
    # entry stamp was stable (even) — the client caches it as a one-sided
    # plan and serves warm repeats without the RPC.
    stamp: Optional[tuple] = None


@dataclass
class _Entry:
    """One stored (key, coords) tensor backed by a volume-owned segment."""

    seg: ShmSegment
    meta: TensorMeta
    # Stamp-table slot carried across replacements (the entry identity owns
    # the slot; the segment rotates underneath it). None = table full or
    # stamping unavailable — the entry is just not one-sided-servable.
    slot: Optional[int] = None


def slice_sig(ts) -> Optional[tuple]:
    """Hashable identity of a sub-request's wanted slice — the one-sided
    plan index key component (None for whole-tensor requests)."""
    if ts is None:
        return None
    return (ts.offsets, ts.local_shape, ts.coordinates)


# --------------------------------------------------------------------------
# caches
# --------------------------------------------------------------------------


class ShmServerCache(TransportCache):
    """Volume-side segment bookkeeping: live entries, view leases, retired
    (viewed-then-replaced) segments awaiting release, a free pool of
    recyclable segments, handshake reservations, and staged-get TTLs."""

    def __init__(self) -> None:
        self.by_key: dict[str, dict[Optional[tuple], _Entry]] = {}
        # name -> number of live (key, coords) entries backed by the
        # segment. 1 for ordinary segments; >1 for arena segments shared by
        # a whole batch of small keys — the segment retires/frees/unlinks
        # only when the LAST referencing entry is replaced or deleted.
        self.seg_refs: dict[str, int] = {}
        self.staged: dict[str, tuple[ShmSegment, float]] = {}
        # name -> outstanding read leases across all clients (zero-copy
        # views AND in-flight destination copies)
        self.grants: dict[str, int] = {}
        # client_id -> highest applied release-batch seq (exactly-once
        # application of retransmitted release batches)
        self.last_applied: dict[str, int] = {}
        # name -> (seg, ts): replaced while leased; released -> free pool
        self.retired: dict[str, tuple[ShmSegment, float]] = {}
        # exact-size free pool of volume-owned, still-linked segments
        self.free_by_size: dict[int, list[ShmSegment]] = {}
        self.free_order: list[tuple[str, float]] = []  # (name, ts) oldest-first
        self.free_bytes = 0
        # Env-seeded default; overridden per-request from the StoreConfig the
        # client buffer carries (see adopt_config) so programmatic
        # initialize(config=...) settings reach the volume side.
        self.pool_cap = default_config().shm_pool_max_bytes
        # pooled segments offered in a put handshake, awaiting the put RPC
        self.reserved: dict[str, tuple[ShmSegment, float]] = {}
        # size -> [reserved names] pre-announced to a client in a put reply
        # (the client pre-attaches them in the background); the next
        # handshake offers these first so the second working-set rotation
        # pays neither allocation nor attach on its critical path.
        self.spare_by_size: dict[int, list[str]] = {}
        # size -> number of background warm-up tasks in flight
        self._warming: dict[int, int] = {}
        # segments being prefaulted (not yet pooled): clear() must unlink
        # these too, or an interrupted warm-up leaks the file for the
        # process lifetime (colocated volumes never exit to be reaped)
        self._warm_inflight: set[ShmSegment] = set()
        # strong refs to in-flight warm-up tasks (asyncio holds tasks
        # weakly; an unretained warmer can be GC'd mid-prefault)
        self._warm_tasks: set = set()
        self._closed = False
        # last time a client RPC touched this cache (warm-up tasks only
        # burn CPU in idle windows, never against live traffic)
        self.last_activity = 0.0
        # Per-entry seqlock stamps (one-sided reads). Lazily created on the
        # first entry; creation failure disables stamping (entries are then
        # simply not one-sided-servable — fail open, never fail the put).
        self.stamps: Optional[StampTable] = None
        self._stamps_failed = False
        self._stamp_next = 0
        self._stamp_free: list[int] = []
        # Open write brackets per (key, coords): endpoints dispatch as
        # independent tasks, so two puts of the same key can overlap at
        # awaits — the stamp may only settle EVEN when the LAST of them
        # closes, else a reader validates against bytes the other put is
        # still writing.
        self._write_nesting: dict[tuple, int] = {}

    def adopt_config(self, config: Optional[StoreConfig]) -> None:
        if config is not None:
            self.pool_cap = config.shm_pool_max_bytes

    # ---- sweeping --------------------------------------------------------

    def sweep(self) -> None:
        now = time.monotonic()
        for name, (seg, ts) in list(self.staged.items()):
            if now - ts > STAGED_TTL_S:
                seg.unlink()  # no-op if the client already unlinked it
                del self.staged[name]
                _SEGMENTS_REAPED.inc(kind="staged")
        for name, (seg, ts) in list(self.retired.items()):
            if now - ts > RETIRED_TTL_S:
                # Client never released (likely crashed). Live readers keep
                # their mapping after the unlink; the name is done either way.
                seg.unlink()
                del self.retired[name]
                self.grants.pop(name, None)
                _SEGMENTS_REAPED.inc(kind="retired")
        for name, (seg, ts) in list(self.reserved.items()):
            if now - ts > RESERVED_TTL_S:
                # The reserving put never arrived (client crashed or is
                # extremely slow). Unlink rather than re-pool: re-pooling
                # could hand the segment to a second writer while the
                # original put is still copying into it — a very late put
                # then fails cleanly on attach instead of corrupting data.
                del self.reserved[name]
                seg.unlink()
                _SEGMENTS_REAPED.inc(kind="reserved")
                # A reaped spare's name must leave spare_by_size too: the
                # stale name was only discarded lazily when a handshake for
                # that exact size popped it, so under many distinct sizes
                # the lists grew without bound (ADVICE r4).
                names = self.spare_by_size.get(seg.size)
                if names is not None:
                    try:
                        names.remove(name)
                    except ValueError:
                        pass
                    if not names:
                        del self.spare_by_size[seg.size]
        _POOL_BYTES.set(self.free_bytes)
        _RETIRED_SEGMENTS.set(len(self.retired))
        _RESERVED_SEGMENTS.set(len(self.reserved))

    # ---- leases ----------------------------------------------------------

    def grant(self, name: str) -> None:
        self.grants[name] = self.grants.get(name, 0) + 1

    def apply_releases(self, payload: Optional[dict]) -> None:
        """Apply a client's release batches. Batches are (seq, counts) pairs
        retransmitted until acked; ``last_applied`` makes application
        exactly-once, so neither a lost response nor a retransmission can
        over- or under-decrement a lease (an over-decrement would recycle a
        segment under a still-live reader)."""
        if not payload:
            return
        client_id = payload["client"]
        last = self.last_applied.get(client_id, 0)
        for seq, counts in sorted(payload["batches"]):
            if seq <= last:
                continue
            last = seq
            for name, n in counts.items():
                have = self.grants.get(name)
                if have is None:
                    continue
                have -= n
                if have > 0:
                    self.grants[name] = have
                    continue
                del self.grants[name]
                entry = self.retired.pop(name, None)
                if entry is not None:
                    self._add_free(entry[0])
        self.last_applied[client_id] = last

    # ---- free pool -------------------------------------------------------

    def _add_free(self, seg: ShmSegment) -> None:
        self.free_by_size.setdefault(seg.size, []).append(seg)
        self.free_order.append((seg.name, time.monotonic()))
        self.free_bytes += seg.size
        while self.free_bytes > self.pool_cap and self.free_order:
            old_name, _ = self.free_order.pop(0)
            for size, segs in self.free_by_size.items():
                victim = next((s for s in segs if s.name == old_name), None)
                if victim is not None:
                    segs.remove(victim)
                    self.free_bytes -= victim.size
                    victim.unlink()
                    break

    def schedule_warm(self, sizes: list[int]) -> None:
        """A put just allocated COLD segments (pool miss): pre-create and
        prefault same-sized spares in the background, so the NEXT push of
        this working set draws warm segments from the pool instead of
        paying first-touch page faults (the cold-start cost an RL loop's
        first weight sync pays; VERDICT r1 item 10)."""
        import asyncio

        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return
        wanted: dict[int, int] = {}
        for size in sizes:
            wanted[size] = wanted.get(size, 0) + 1
        # Segments already earmarked for rotation count against the want:
        # reserved ones (handshake offers + announced spares) re-enter the
        # cycle when their put lands. Without this, every handshake miss of
        # a rotating working set warms ANOTHER full spare set — unbounded
        # page-zeroing that starves the very copies it was meant to speed
        # up (worst on few-core hosts).
        reserved_by_size: dict[int, int] = {}
        for seg, _ in self.reserved.values():
            reserved_by_size[seg.size] = reserved_by_size.get(seg.size, 0) + 1
        budget = self.pool_cap - self.free_bytes
        for size, count in wanted.items():
            have = (
                len(self.free_by_size.get(size, ()))
                + self._warming.get(size, 0)
                + reserved_by_size.get(size, 0)
            )
            for _ in range(max(0, count - have)):
                if budget < size:
                    break
                budget -= size
                self._warming[size] = self._warming.get(size, 0) + 1
                spawn_logged(
                    self._warm_one(size),
                    name="shm.pool_warm",
                    tasks=self._warm_tasks,
                    log=logger,
                )

    async def _warm_one(self, size: int) -> None:
        import asyncio

        seg = None
        try:
            if ShmSegment._POPULATE:
                # MAP_POPULATE prefaults the whole segment inside the mmap
                # call — run it on an executor thread so the (0.1-0.2s/GB)
                # kernel work never stalls the volume's event loop. No
                # idle-gating needed: one batched kernel pass is far
                # cheaper than trap-per-page faulting, and the segment is
                # fully warm the moment create returns.
                loop = asyncio.get_running_loop()
                seg = await loop.run_in_executor(None, ShmSegment.create, size)
                self._warm_inflight.add(seg)
                if self._closed:
                    seg.unlink()
                else:
                    self._add_free(seg)
                return
            seg = ShmSegment.create(size)
            self._warm_inflight.add(seg)
            view = np.frombuffer(seg.mmap, dtype=np.uint8) if size else None
            step = 1 << 20
            off = 0
            while off < size:
                if self._closed:
                    seg.unlink()
                    return
                # No MAP_POPULATE on this platform: prefault by touching,
                # only in LONG idle windows (>=1s since the last RPC) —
                # page-zeroing steals CPU from in-flight transfers (brutal
                # on few-core hosts), and a volume-side gate cannot see the
                # client's own copy work between RPCs. An RL loop's
                # multi-second training step provides exactly these gaps.
                if time.monotonic() - self.last_activity < 1.0:
                    await asyncio.sleep(0.25)
                    continue
                view[off : min(off + step, size) : 4096] = 0
                off += step
                # ~10% duty cycle: a trickle keeps warm-up invisible to
                # concurrent transfers; RL gaps are seconds long, so
                # spares still arrive in time.
                await asyncio.sleep(0.005)
            if self._closed:
                seg.unlink()
            else:
                self._add_free(seg)
        except OSError:
            pass
        finally:
            if seg is not None:
                self._warm_inflight.discard(seg)
            left = self._warming.get(size, 1) - 1
            if left > 0:
                self._warming[size] = left
            else:
                self._warming.pop(size, None)

    async def provision(
        self,
        sizes: dict[int, int],
        hugepages: bool = True,
        nthreads: int = 0,
    ) -> dict:
        """Manifest-driven pool pre-sizing (the prewarm executor's SHM leg):
        for each requested ``{size: count}``, create-and-prefault enough
        segments that the pool can serve that many put-handshake offers —
        counting segments already pooled, warming, or reserved against the
        want. Creation + prefault run on executor threads (the native
        prefault releases the GIL, so multi-segment provisioning
        parallelizes); pool bookkeeping happens back on the event loop.
        Largest sizes first and clamped to the pool cap's remaining budget:
        when everything can't fit, prewarm covers the allocations that hurt
        the cold path most."""
        import asyncio

        loop = asyncio.get_running_loop()
        reserved_by_size: dict[int, int] = {}
        for seg, _ in self.reserved.values():
            reserved_by_size[seg.size] = reserved_by_size.get(seg.size, 0) + 1
        # Clamped at zero: adopt_config may have SHRUNK pool_cap below what
        # the pool already holds — a negative budget would let the floor
        # division below go negative and corrupt the accounting. ALSO
        # clamped to actual tmpfs availability (minus a safety margin for
        # concurrent tenants): the prefault WRITES every page, and a write
        # past tmpfs-full is SIGBUS — fatal to the volume process — not a
        # catchable exception. The controller's reservation normally
        # prevents this, but the volume must protect itself when the
        # reserve step failed and the plan arrived unclamped.
        budget = max(0, self.pool_cap - self.free_bytes)
        budget = min(budget, max(0, shm_available_bytes() - (256 << 20)))
        created = 0
        created_bytes = 0
        already = 0
        clamped_bytes = 0
        plan: list[int] = []
        for size in sorted(sizes, reverse=True):
            count = int(sizes[size])
            if size <= 0 or count <= 0:
                continue
            have = (
                len(self.free_by_size.get(size, ()))
                + self._warming.get(size, 0)
                + reserved_by_size.get(size, 0)
            )
            want = max(0, count - have)
            already += count - want
            fits = min(want, budget // size) if want else 0
            budget -= fits * size
            clamped_bytes += (want - fits) * size
            plan.extend([size] * fits)
        segs = await asyncio.gather(
            *(
                loop.run_in_executor(
                    None, ShmSegment.create_provisioned, size, hugepages, nthreads
                )
                for size in plan
            ),
            return_exceptions=True,
        )
        errors = 0
        names: list[tuple[str, int]] = []
        for seg in segs:
            if isinstance(seg, BaseException):
                errors += 1
                continue
            if self._closed:
                seg.unlink()  # clear() ran mid-provision: don't leak the file
                continue
            self._add_free(seg)
            created += 1
            created_bytes += seg.size
            names.append((seg.name, seg.size))
        _POOL_BYTES.set(self.free_bytes)
        return {
            "created": created,
            "bytes": created_bytes,
            "already_pooled": already,
            "clamped_bytes": clamped_bytes,
            "errors": errors,
            # Created segment names: the prewarming CLIENT pre-attaches these
            # (populate=True page-table wiring off the critical path) so the
            # first put's handshake offers hit its attachment cache and only
            # the copy remains on the hot path.
            "names": names,
        }

    def take_free(self, size: int) -> Optional[ShmSegment]:
        segs = self.free_by_size.get(size)
        if not segs:
            return None
        seg = segs.pop()
        self.free_bytes -= seg.size
        self.free_order = [(n, t) for n, t in self.free_order if n != seg.name]
        _SEGMENTS_RECYCLED.inc()
        return seg

    # ---- entry stamps (one-sided read seqlocks) --------------------------

    def _stamp_table(self) -> Optional[StampTable]:
        if self.stamps is None and not self._stamps_failed:
            try:
                self.stamps = StampTable.create()
            except OSError:
                self._stamps_failed = True
        return self.stamps

    def _alloc_slot(self) -> Optional[int]:
        if self._stamp_table() is None:
            return None
        if self._stamp_free:
            return self._stamp_free.pop()
        if self._stamp_next < STAMP_SLOTS:
            slot = self._stamp_next
            self._stamp_next += 1
            return slot
        return None

    def _tombstone(self, entry: "_Entry") -> None:
        """Entry is going away: leave its stamp ODD forever (until the slot
        is reused, when the word keeps counting up) so one-sided readers of
        any plan built against it fall back from the first check."""
        if entry.slot is None or self.stamps is None:
            return
        w = self.stamps.read(entry.slot)
        if w % 2 == 0:
            self.stamps.write(entry.slot, w + 1)
        self._stamp_free.append(entry.slot)
        entry.slot = None

    def begin_writes(self, pairs: list[tuple[str, Optional[tuple]]]) -> None:
        """Mark every existing entry about to be (re)written as
        write-in-flight (stamp odd). Called by the volume at put/pull entry
        — BEFORE any transport lands bytes that could alias entry memory
        (the bulk/rpc in-place overwrite paths) and before the entry is
        repointed. The volume fires the ``shm.landing_stamp`` faultpoint
        (async, so a delay/wedge holds entries visibly write-in-flight
        without freezing the event loop's RPC fallback path) right after
        this returns."""
        for key, coords in pairs:
            pair = (key, coords)
            nesting = self._write_nesting.get(pair, 0)
            self._write_nesting[pair] = nesting + 1
            if nesting or self.stamps is None:
                continue  # already held odd by an overlapping writer
            entry = self.by_key.get(key, {}).get(coords)
            if entry is not None and entry.slot is not None:
                w = self.stamps.read(entry.slot)
                if w % 2 == 0:
                    self.stamps.write(entry.slot, w + 1)

    def end_writes(self, pairs: list[tuple[str, Optional[tuple]]]) -> None:
        """Settle every written entry at its next EVEN generation (allocate
        slots for fresh entries). Runs after the store adopted the new
        values and strictly before the old segments could be re-offered to
        another writer (both happen inside the same RPC dispatch), which is
        what makes the reader's post-copy re-check sound. An entry another
        put still holds open (overlapping writes of one key) stays ODD —
        only the last closing bracket settles it."""
        for key, coords in pairs:
            pair = (key, coords)
            nesting = self._write_nesting.get(pair, 1) - 1
            if nesting > 0:
                self._write_nesting[pair] = nesting
                continue
            self._write_nesting.pop(pair, None)
            entry = self.by_key.get(key, {}).get(coords)
            if entry is None:
                continue
            if entry.slot is None:
                entry.slot = self._alloc_slot()
                if entry.slot is None:
                    continue
            w = self.stamps.read(entry.slot)
            self.stamps.write(entry.slot, w + 1 if w % 2 else w + 2)

    # ---- entries ---------------------------------------------------------

    def track_staged(self, seg: ShmSegment) -> None:
        self.staged[seg.name] = (seg, time.monotonic())

    def lookup(self, key: str, coords: Optional[tuple]) -> Optional[_Entry]:
        return self.by_key.get(key, {}).get(coords)

    def put(
        self, key: str, coords: Optional[tuple], seg: ShmSegment, meta: TensorMeta
    ) -> None:
        entries = self.by_key.setdefault(key, {})
        prev = entries.get(coords)
        # The stamp slot rides the ENTRY identity across segment rotations
        # (end_writes settles it even once the new bytes are adopted).
        entries[coords] = _Entry(
            seg, meta, slot=prev.slot if prev is not None else None
        )
        if prev is not None and prev.seg.name == seg.name:
            return  # in-place overwrite: refcount unchanged
        self.seg_refs[seg.name] = self.seg_refs.get(seg.name, 0) + 1
        if prev is not None and self._release_entry_ref(prev.seg):
            self._retire_or_free(prev.seg)

    def _release_entry_ref(self, seg: ShmSegment) -> bool:
        """One entry stopped referencing ``seg``. Returns True when it was
        the last reference (the segment left the entry set)."""
        left = self.seg_refs.get(seg.name, 1) - 1
        if left > 0:
            self.seg_refs[seg.name] = left
            return False
        self.seg_refs.pop(seg.name, None)
        return True

    def _retire_or_free(self, seg: ShmSegment) -> None:
        if self.grants.get(seg.name):
            self.retired[seg.name] = (seg, time.monotonic())
        else:
            self._add_free(seg)

    def segments_for(self, key: str) -> list[ShmSegment]:
        return [e.seg for e in self.by_key.get(key, {}).values()]

    def locate(self, key: str, arr: np.ndarray) -> Optional[tuple[_Entry, int]]:
        """Find the entry whose segment ``arr``'s memory lives in (anywhere
        within it — sub-slice views included), or None. Returns the entry
        (its segment AND its stamp slot) plus the byte offset."""
        if arr.nbytes == 0:
            return None
        ptr = arr.__array_interface__["data"][0]
        for entry in self.by_key.get(key, {}).values():
            base = entry.seg.base_addr()
            if base is not None and base <= ptr < base + entry.seg.size:
                return entry, ptr - base
        return None

    def delete_key(self, key: str) -> None:
        for entry in self.by_key.pop(key, {}).values():
            self._tombstone(entry)
            if not self._release_entry_ref(entry.seg):
                # Arena segment still backing other live keys: its bytes
                # stay until the last referencing entry goes.
                continue
            entry.seg.unlink()
            self.grants.pop(entry.seg.name, None)

    def clear(self) -> None:
        for entries in self.by_key.values():
            for entry in entries.values():
                # Readers keep their stamp-table mapping after the unlink
                # below; the tombstone makes every cached plan fall back.
                self._tombstone(entry)
                entry.seg.unlink()
        self.by_key.clear()
        if self.stamps is not None:
            self.stamps.seg.unlink()
            self.stamps = None
        self._stamp_next = 0
        self._stamp_free.clear()
        for seg, _ in self.staged.values():
            seg.unlink()
        self.staged.clear()
        for seg, _ in self.retired.values():
            seg.unlink()
        self.retired.clear()
        for segs in self.free_by_size.values():
            for seg in segs:
                seg.unlink()
        self.free_by_size.clear()
        self.free_order.clear()
        self.free_bytes = 0
        for seg, _ in self.reserved.values():
            seg.unlink()
        self.reserved.clear()
        self.spare_by_size.clear()
        self._closed = True  # interrupt in-flight warm-ups
        for seg in list(self._warm_inflight):
            seg.unlink()
        self._warm_inflight.clear()
        self.grants.clear()
        self.seg_refs.clear()


class ShmClientCache(TransportCache):
    """Client-side: segment name -> attachment, so repeat transfers skip the
    open+mmap syscalls; plus weakref tracking of zero-copy views handed to
    the caller. Releases are routed per VOLUME (one client talks to many
    volumes) as sequence-numbered batches retransmitted until acked, so a
    failed RPC can neither lose a release (leaking the server lease) nor
    double-apply one (recycling a segment under a live reader)."""

    def __init__(self) -> None:
        self.client_id = uuid.uuid4().hex
        self.segments: dict[str, ShmSegment] = {}
        self.key_to_segments: dict[str, set[str]] = {}
        self.seg_volume: dict[str, str] = {}  # name -> volume_id
        self.view_refs: dict[str, list] = {}  # name -> [weakref.ref, ...]
        # volume_id -> {name: count} not yet assigned to a batch
        self.pending: dict[str, dict[str, int]] = {}
        # volume_id -> {seq: counts} sent but not yet acked
        self.unacked: dict[str, dict[int, dict[str, int]]] = {}
        self.seq: dict[str, int] = {}
        # Strong refs to in-flight background pre-attaches (see pre_attach).
        self._pre_attach_tasks: set = set()
        # name -> attach time for pre-attached spares not yet offered —
        # evicted after the server's reserved TTL (the server has unlinked
        # an unused spare by then; keeping the populated mapping would pin
        # its tmpfs pages for the client's lifetime).
        self._pre_attached: dict[str, float] = {}
        # One-sided plans: (key, slice_sig) -> plan dict recorded from
        # stamp-annotated get descriptors (the serving volume rides INSIDE
        # the plan). Bounded; cleared wholesale on overflow and on
        # placement-epoch bumps (the client owns that).
        self.one_sided: dict[tuple, dict] = {}
        # Attached volume stamp tables: name -> (segment, uint64 word view).
        self.stamp_tables: dict[str, tuple[ShmSegment, np.ndarray]] = {}

    ONE_SIDED_MAX = 65536

    def record_one_sided(self, volume_id: str, req, desc: ShmDescriptor) -> None:
        """Cache a stamp-annotated descriptor as a one-sided plan for the
        exact (key, wanted-slice) request it answered. Keyed WITHOUT the
        volume id (a warm get must find the plan before it knows which
        replica it would route to); the serving volume rides inside the
        plan so replica re-routing replaces rather than duplicates."""
        if desc.stamp is None or desc.owner != "volume":
            return
        if len(self.one_sided) >= self.ONE_SIDED_MAX:
            self.one_sided.clear()
        meta = desc.meta
        self.one_sided[(req.key, slice_sig(req.tensor_slice))] = {
            # The store key rides the plan so zero-RPC serves can feed the
            # hot-key profiler and the traffic ledger — without it the
            # warmest keys would be invisible to placement telemetry
            # (the PR-7 blind spot: stamped reads never reach any volume's
            # stats()["hot_keys"]).
            "key": req.key,
            "volume_id": volume_id,
            "segment": desc.segment_name,
            "segment_size": desc.segment_size,
            "offset": desc.offset,
            "strides": desc.strides,
            "meta": meta,
            # Pre-resolved meta scalars: the warm loops read these per
            # member per iteration, and the TensorMeta property walks
            # (math.prod, dtype parse) cost more than the stamp checks.
            "nbytes": meta.nbytes,
            "shape": tuple(meta.shape),
            "npdtype": meta.np_dtype,
            "stamp_name": desc.stamp[0],
            "stamp_size": desc.stamp[1],
            "slot": desc.stamp[2],
            "gen": desc.stamp[3],
        }

    def drop_one_sided(self) -> int:
        """Drop every cached one-sided plan (placement-epoch bump /
        quarantine transition: the placement the plans describe changed).
        LIVE attached stamp tables are kept — they re-validate instantly
        and a reinstated volume's table is still the one in use — but a
        table whose backing file is gone (volume reset unlinked it and
        made a fresh one) is closed here, or each reset would pin another
        512KB of unlinked tmpfs pages for this client's lifetime."""
        n = len(self.one_sided)
        self.one_sided.clear()
        for name in list(self.stamp_tables):
            if not os.path.exists(os.path.join(SHM_DIR, name)):
                seg, _ = self.stamp_tables.pop(name)
                seg.close()
        return n

    def stamp_words(self, plan: dict) -> Optional[np.ndarray]:
        """The uint64 word view of the plan's stamp table (attached and
        cached on first use); None when the table is gone (volume reset)."""
        name = plan["stamp_name"]
        cached = self.stamp_tables.get(name)
        if cached is None:
            try:
                seg = ShmSegment.attach(name, plan["stamp_size"], populate=True)
            except (OSError, ValueError):
                return None
            cached = (seg, np.frombuffer(seg.mmap, dtype=np.uint64))
            self.stamp_tables[name] = cached
        return cached[1]

    def attach(self, desc: ShmDescriptor, key: str, volume_id: str) -> ShmSegment:
        seg = self.segments.get(desc.segment_name)
        if seg is None:
            # Client copies/reads touch every byte — pre-wire the mapping.
            seg = ShmSegment.attach(
                desc.segment_name, desc.segment_size, populate=True
            )
            self.segments[desc.segment_name] = seg
        self._pre_attached.pop(desc.segment_name, None)  # offered: in use now
        self.key_to_segments.setdefault(key, set()).add(desc.segment_name)
        self.seg_volume[desc.segment_name] = volume_id
        return seg

    def evict_stale_pre_attached(self) -> None:
        """Evict pre-attached spares that were never offered within the
        server's reserved TTL: the server has unlinked them by now, and only
        this mapping keeps their tmpfs pages alive. Called from EVERY cache
        entry point that observes traffic (pre_attach AND the per-RPC
        collect_released), not just pre_attach — a client whose puts stop
        missing the pool stops receiving spare announcements, and its stale
        mappings would otherwise pin tmpfs pages for the process lifetime
        (ADVICE carried fix)."""
        cutoff = time.monotonic() - RESERVED_TTL_S
        for name, ts in list(self._pre_attached.items()):
            if ts < cutoff:
                del self._pre_attached[name]
                seg = self.segments.pop(name, None)
                if seg is not None:
                    seg.close()

    def pre_attach(self, spares: list[tuple[str, int]]) -> None:
        """Background-attach server-announced warm spares so the NEXT
        handshake's offers of these names hit the attachment cache — the
        second working-set rotation then pays only its copy. Best-effort:
        off the event loop, races with a synchronous attach resolved in
        its favor, reaped names ignored."""
        import asyncio

        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return

        self.evict_stale_pre_attached()

        async def one(name: str, size: int) -> None:
            if name in self.segments:
                return
            try:
                seg = await loop.run_in_executor(
                    None, ShmSegment.attach, name, size, True
                )
            except OSError:
                return  # reserved-TTL reaped (or volume reset) meanwhile
            if name in self.segments:
                seg.close()  # a synchronous attach won the race
            else:
                self.segments[name] = seg
                self._pre_attached[name] = time.monotonic()

        for name, size in spares:
            # spawn_logged keeps a strong ref until done (a pending
            # pre-attach can otherwise be garbage-collected mid-flight) and
            # surfaces unexpected failures instead of dropping them.
            spawn_logged(
                one(name, size),
                name="shm.pre_attach",
                tasks=self._pre_attach_tasks,
                log=logger,
            )

    def rekey(self, old_name: str, new_name: str) -> None:
        """The volume adopted + renamed a segment this client created: track
        the attachment under the new name (the mapping itself is unchanged —
        rename does not invalidate mmaps), so later handshake offers of the
        renamed segment hit the cache instead of leaking a stale entry."""
        seg = self.segments.pop(old_name, None)
        if seg is not None:
            seg.name = new_name
            self.segments[new_name] = seg
        for names in self.key_to_segments.values():
            if old_name in names:
                names.discard(old_name)
                names.add(new_name)
        vid = self.seg_volume.pop(old_name, None)
        if vid is not None:
            self.seg_volume[new_name] = vid

    def track_view(self, name: str, arr: np.ndarray) -> None:
        self.view_refs.setdefault(name, []).append(weakref.ref(arr))

    def count_release(self, name: str, n: int = 1) -> None:
        vid = self.seg_volume.get(name)
        if vid is None:
            return
        counts = self.pending.setdefault(vid, {})
        counts[name] = counts.get(name, 0) + n

    def collect_released(self, volume_id: str) -> Optional[dict]:
        """Release payload for ``volume_id``: all unacked batches (including
        a fresh one from views dropped since the last RPC), or None."""
        self.evict_stale_pre_attached()
        for name, refs in list(self.view_refs.items()):
            live = [r for r in refs if r() is not None]
            dead = len(refs) - len(live)
            if dead:
                self.count_release(name, dead)
            if live:
                self.view_refs[name] = live
            else:
                del self.view_refs[name]
        fresh = self.pending.pop(volume_id, None)
        if fresh:
            s = self.seq[volume_id] = self.seq.get(volume_id, 0) + 1
            self.unacked.setdefault(volume_id, {})[s] = fresh
        batches = self.unacked.get(volume_id)
        if not batches:
            return None
        return {"client": self.client_id, "batches": sorted(batches.items())}

    def ack_released(self, volume_id: str, payload: Optional[dict]) -> None:
        if not payload:
            return
        batches = self.unacked.get(volume_id)
        if batches:
            for seq, _ in payload["batches"]:
                batches.pop(seq, None)

    def delete_key(self, key: str) -> None:
        for name in self.key_to_segments.pop(key, ()):  # drop attachments
            seg = self.segments.pop(name, None)
            if seg is not None:
                seg.close()
            # seg_volume is kept: views handed out for this key may still
            # be alive, and their eventual release must still route to the
            # owning volume (or its retired segment waits out the full TTL).
        for pk in [pk for pk in self.one_sided if pk[0] == key]:
            del self.one_sided[pk]

    def clear(self) -> None:
        for seg in self.segments.values():
            seg.close()
        self.segments.clear()
        self.key_to_segments.clear()
        self.seg_volume.clear()
        self.view_refs.clear()
        self.pending.clear()
        self.unacked.clear()
        self.seq.clear()
        self.one_sided.clear()
        for seg, _ in self.stamp_tables.values():
            seg.close()
        self.stamp_tables.clear()


async def pre_attach_segments(volume, names: list[tuple[str, int]]) -> int:
    """Prewarm helper: synchronously attach volume-provisioned segments into
    this client's attachment cache (populate=True — the page-table wiring a
    put would otherwise pay on its critical path). Unlike the background
    ``ShmClientCache.pre_attach`` (best-effort, races the next handshake),
    this AWAITS completion: prewarm returns only when the first put's offers
    will hit the cache. Attachments are tracked as pre-attached spares, so
    the standard staleness eviction applies — a prewarm more than the
    reserved TTL ahead of the first put keeps the volume-side pool benefit
    but re-attaches lazily. Returns the number of fresh attachments."""
    import asyncio

    cache: ShmClientCache = volume.transport_context.get_cache(ShmClientCache)
    loop = asyncio.get_running_loop()

    async def one(name: str, size: int) -> int:
        if name in cache.segments:
            return 0
        try:
            seg = await loop.run_in_executor(
                None, ShmSegment.attach, name, size, True
            )
        except OSError:
            return 0  # pool-cap evicted (or volume reset) meanwhile
        if name in cache.segments:
            seg.close()  # a concurrent attach won the race
            return 0
        cache.segments[name] = seg
        cache._pre_attached[name] = time.monotonic()
        return 1

    results = await asyncio.gather(*(one(n, s) for n, s in names))
    return sum(results)


# --------------------------------------------------------------------------
# one-sided stamped reads (client side)
# --------------------------------------------------------------------------


class OneSidedMiss(Exception):
    """A one-sided attempt cannot (or must not) serve this request — the
    caller falls back to the RPC path and counts the reason. Carrying the
    reason in the exception keeps every fallback LOUD in metrics while the
    data path stays correct by construction (the fallback re-fetches)."""

    def __init__(self, reason: str):
        self.reason = reason
        super().__init__(reason)


def segment_read_view(
    seg: ShmSegment,
    meta: TensorMeta,
    offset: int = 0,
    strides: Optional[tuple[int, ...]] = None,
) -> np.ndarray:
    """THE blessed raw-segment read accessor for client/direct modules (the
    ``one-sided-discipline`` tslint rule routes every attached-segment read
    here): callers MUST pair it with a seqlock/generation validation around
    the consuming copy — ``stamped_read`` does that internally; the direct
    sync path validates its source generations over the control socket
    before and after consuming the view."""
    return seg.strided_view(meta, offset, strides)


# One-sided accounting sample policy: batches above _ACCOUNT_EXACT_MAX
# plans record 1-in-_ACCOUNT_SAMPLE at weight _ACCOUNT_SAMPLE (the warm
# many-keys leg is the store's hottest per-key path — full per-key
# accounting there costs ~10x the <=2% telemetry budget, and a steady
# consumer repeats the same batch so the weighted sample converges to the
# exact totals). Small batches (p50 1KB gets, layer serves) stay exact.
_ACCOUNT_SAMPLE = 8
_ACCOUNT_EXACT_MAX = 64
_account_tick = 0


def _account_one_sided(plans: list[dict]) -> None:
    """Decision telemetry for zero-RPC serves (the PR-7 blind spot fix):
    stamped reads never touch a volume, so without this the warmest keys of
    a warm working set are invisible to every ``hot_keys`` view and the
    traffic ledger under-counts exactly the path placement decisions care
    about most. One batched tally (single lock) per accounted batch; keys
    ride the plan dicts (plans recorded before the field existed are
    skipped). Large batches are weight-scaled samples — see
    ``_ACCOUNT_SAMPLE`` above."""
    global _account_tick
    weight = 1
    if len(plans) > _ACCOUNT_EXACT_MAX:
        _account_tick += 1
        if _account_tick % _ACCOUNT_SAMPLE:
            return
        weight = _ACCOUNT_SAMPLE
    ledger = obs_ledger.ledger()
    if not ledger.enabled:
        return
    items: list[tuple] = []
    by_volume: dict[str, list] = {}
    for plan in plans:
        key = plan.get("key")
        if key is None:
            continue
        item = (key, plan["nbytes"])
        items.append(item)
        by_volume.setdefault(str(plan.get("volume_id", "")), []).append(item)
    if not items:
        return
    obs_profile.hot_key_tracker("one_sided").record_many(
        items, weight=weight
    )
    host = obs_ledger.local_host()
    for vid, vitems in by_volume.items():
        ledger.record(
            "one_sided",
            obs_ledger.INGRESS,
            sum(n for _, n in vitems) * weight,
            peer_host=host,  # same-host by construction
            volume=vid,
            items=vitems,
            ops=weight,
            weight=weight,
        )


def stamped_read(
    cache: "ShmClientCache",
    plan: dict,
    dest: Optional[np.ndarray] = None,
    borrow: bool = False,
) -> tuple[np.ndarray, Optional[Any]]:
    """Serve one warm get straight out of a pre-attached volume segment
    under the plan's per-entry seqlock stamp — ZERO RPCs.

    Protocol: check the stamp word equals the plan's recorded (even)
    generation, copy the bytes out (into ``dest`` when given), re-check the
    stamp. Any pre-copy mismatch means the entry was replaced/deleted/is
    mid-write (stale plan); a post-copy mismatch means the copy may be torn
    — both raise :class:`OneSidedMiss` so the caller falls back to the RPC
    path, which fully overwrites any partial landing. Soundness leans on
    the volume-side ordering: a recycled segment is only re-offered to a
    writer after the replacing put went through begin_writes (stamp odd)
    — so a reader racing the recycle always sees the stamp move.

    ``borrow=True`` (destination-less device uploads) returns a READ-ONLY
    view of the segment plus a ``recheck`` callable instead of copying;
    the consumer must finish reading (e.g. jax.block_until_ready after
    device_put) and then call ``recheck()`` — False means the upload may
    hold mixed-generation bytes and must be discarded
    (``device_transfer.finalize_stamped`` wraps that)."""
    src, words, slot, gen = _stamped_source(cache, plan)

    def recheck() -> bool:
        return int(words[slot]) == gen

    if borrow and dest is None:
        view = src.view()
        view.flags.writeable = False
        ONE_SIDED_READS.inc(transport="shm")
        _account_one_sided([plan])
        return view, recheck
    if dest is None:
        if plan["nbytes"] > ONE_SIDED_COPY_MAX:
            # Destination-less big get: the RPC path's zero-copy snapshot
            # view wins (a one-sided serve would have to copy).
            raise OneSidedMiss("too_large")
        dest = np.empty(plan["shape"], plan["npdtype"])
    elif dest.shape != plan["shape"] or dest.dtype != plan["npdtype"]:
        # Stale-metadata target (dtype-converting get / re-published shape):
        # the RPC path owns the conversion story.
        raise OneSidedMiss("shape")
    copy_into(dest, src)
    if not recheck():
        # Copy raced a replacement landing: the bytes in ``dest`` may mix
        # generations — discard (the RPC fallback fully overwrites).
        ONE_SIDED_TORN.inc(transport="shm")
        raise OneSidedMiss("torn")
    ONE_SIDED_READS.inc(transport="shm")
    _account_one_sided([plan])
    return dest, None


def _stamped_source(
    cache: "ShmClientCache", plan: dict
) -> tuple[np.ndarray, np.ndarray, int, int]:
    """Resolve a plan's source view after the pre-copy stamp check; returns
    (src_view, stamp_words, slot, gen) or raises :class:`OneSidedMiss`.

    The stamp-word array, the constructed source view, and its base address
    are memoized ON the plan dict: a warm iteration repeats the same plans,
    and per-member view construction was a measurable slice of the
    many-keys get leg. Safe because plans are dropped wholesale whenever
    the underlying placement can change (stale/torn miss, epoch bump,
    delete), and a segment's mapping outlives ``close()`` as long as any
    view references it (close never unmaps; GC does)."""
    words = plan.get("words")
    if words is None:
        words = cache.stamp_words(plan)
        if words is None:
            raise OneSidedMiss("stamp_table_gone")
        plan["words"] = words
    slot, gen = plan["slot"], plan["gen"]
    if int(words[slot]) != gen:
        raise OneSidedMiss("stale_stamp")
    src = plan.get("view")
    if src is None:
        name = plan["segment"]
        seg = cache.segments.get(name)
        if seg is None:
            try:
                seg = ShmSegment.attach(
                    name, plan["segment_size"], populate=True
                )
            except (OSError, ValueError):
                raise OneSidedMiss("segment_gone") from None
            cache.segments[name] = seg
        src = segment_read_view(
            seg, plan["meta"], plan["offset"], plan["strides"]
        )
        plan["view"] = src
        # Base address for the native scatter-copy batch; None marks the
        # member ineligible (strided source — memcpy would read stray
        # bytes), which stands the whole batch down to the grouped path.
        plan["src_addr"] = (
            src.__array_interface__["data"][0]
            if plan["strides"] is None and src.size
            else None
        )
    return src, words, slot, gen


async def stamped_read_batch(
    cache: "ShmClientCache",
    plans: list[dict],
    dests: list[Optional[np.ndarray]],
    config: Optional[StoreConfig] = None,
) -> list[np.ndarray]:
    """The many-keys warm get leg: serve a whole batch of one-sided plans as
    ONE stamped memcpy loop on the shared landing pool — check every stamp,
    fan all copies out to :func:`landing.land_async` together (they overlap
    each other and the event loop), then re-check every stamp.

    All-or-nothing: any pre-copy mismatch, shape drift, or post-copy tear
    raises :class:`OneSidedMiss` for the WHOLE batch — the caller falls back
    to the RPC path, which fully overwrites any partial in-place landings,
    so mixed-generation bytes are never observable. Destination-less members
    above ONE_SIDED_COPY_MAX stand down (the RPC path's zero-copy snapshot
    view wins there)."""
    results: list[np.ndarray] = []
    # Native scatter-copy batch (landing.land_batch_async): one GIL-free
    # call replaces the per-pair grouped pool path. Any ineligible member
    # (strided source, non-contiguous destination) stands the whole batch
    # down to land_async — correctness is identical, only dispatch differs.
    dst_addrs: list[int] = []
    src_addrs: list[int] = []
    lens: list[int] = []
    batch_ok = True
    t_verify = time.perf_counter()
    for plan, dest in zip(plans, dests):
        src, words, slot, gen = _stamped_source(cache, plan)
        nbytes = plan["nbytes"]
        if dest is None:
            if nbytes > ONE_SIDED_COPY_MAX:
                raise OneSidedMiss("too_large")
            dest = np.empty(plan["shape"], plan["npdtype"])
        elif dest.shape != plan["shape"] or dest.dtype != plan["npdtype"]:
            # Stale-metadata target (dtype-converting get / re-published
            # shape): the RPC path owns the conversion story.
            raise OneSidedMiss("shape")
        results.append(dest)
        if batch_ok and nbytes:
            src_addr = plan.get("src_addr")
            if src_addr is None or not dest.flags["C_CONTIGUOUS"]:
                batch_ok = False
            else:
                dst_addrs.append(dest.__array_interface__["data"][0])
                src_addrs.append(src_addr)
                lens.append(nbytes)
    t_copy = time.perf_counter()
    verify_s = t_copy - t_verify
    # ``shm.landing_stamp`` fires inside the landing-copy window of the
    # one-sided read too (client scope) — a delay/wedge here lands squarely
    # in the get's "landing" stage, exactly how a slow landing pool under
    # overload presents, which is what the stage-attribution tests (and
    # fleet-scale chaos legs) lean on.
    await faults.afire("shm.landing_stamp")
    copied = batch_ok and await landing.land_batch_async(
        dst_addrs, src_addrs, lens, stage="one_sided", config=config
    )
    if not copied:
        # Grouped-pool fallback (pre-v3 library / ineligible member): the
        # (dest, src) pairs are rebuilt off the hot path from the plans'
        # memoized views.
        await landing.land_async(
            [(dest, plan["view"]) for plan, dest in zip(plans, results)],
            stage="one_sided",
            config=config,
        )
    t_recheck = time.perf_counter()
    obs_timeline.observe_stage("get", "landing", t_recheck - t_copy)
    # Post-copy recheck, vectorized per stamp table: one fancy-indexed
    # gather + compare replaces a per-member int() round trip.
    by_table: dict[int, tuple[np.ndarray, list, list]] = {}
    for plan in plans:
        words = plan["words"]
        entry = by_table.get(id(words))
        if entry is None:
            entry = by_table[id(words)] = (words, [], [])
        entry[1].append(plan["slot"])
        entry[2].append(plan["gen"])
    try:
        for words, slots, gens in by_table.values():
            if not np.array_equal(
                words[np.asarray(slots)], np.asarray(gens, dtype=np.uint64)
            ):
                ONE_SIDED_TORN.inc(transport="shm")
                raise OneSidedMiss("torn")
    finally:
        # Stage attribution: pre-copy stamp matching + post-copy re-gather
        # are the seqlock-verify cost of the zero-RPC path (torn included —
        # a discarded read still paid its verify).
        obs_timeline.observe_stage(
            "get",
            "stamp_verify",
            verify_s + (time.perf_counter() - t_recheck),
        )
    ONE_SIDED_READS.inc(len(results), transport="shm")
    _account_one_sided(plans)
    return results


# --------------------------------------------------------------------------
# the transport buffer
# --------------------------------------------------------------------------


class SharedMemoryTransportBuffer(TransportBuffer):
    transport_name = "shm"
    requires_handshake = True
    # Gets are self-describing (descriptors ride the get response) — no
    # handshake round trip on the read path.
    handshake_ops = ("put",)
    supports_inplace = True
    requires_contiguous_inplace = False
    supports_batch_puts = True
    supports_batch_gets = True

    def __init__(
        self, config: Optional[StoreConfig] = None, inproc_copy: bool = False
    ):
        # config TRAVELS with the buffer (like the bulk transport's) so the
        # volume side honors programmatic initialize(config=...) overrides.
        self.config = config
        # Colocated volumes dispatch endpoints without serialization:
        # OBJECT payloads would be stored/served by reference (tensors are
        # safe — they always live in segments). Deep-copy restores the
        # value semantics pickling provides.
        self.inproc_copy = inproc_copy
        self.descriptors: dict[int, ShmDescriptor] = {}
        self.objects: dict[int, Any] = {}
        # Small-put fast path: payload arrays riding the put RPC itself
        # (zero-copy pickle-5 frames), landed server-side into segments.
        self.inline: dict[int, np.ndarray] = {}
        # Small-key arena: {"offsets": {req_idx: byte offset}, "total": n,
        # "segment": name, "segment_size": n} — computed client-side before
        # the handshake, ridden to the server on BOTH RPCs (handshake offers
        # one pooled segment for the whole batch; the put indexes every
        # member out of it in one pass).
        self.arena_plan: Optional[dict] = None
        # client -> server piggyback: sequenced view-release batches
        self.released: Optional[dict] = None
        # server -> client (via put_reply): adopted-segment renames
        self.renames: dict[str, str] = {}
        # server -> client (via put_reply): pre-announced warm spares
        # [(name, size)] the client should background-attach.
        self.spares: list[tuple[str, int]] = []
        # Client-only staging state (never pickled).
        self._client_segments: dict[int, ShmSegment] = {}

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_client_segments"] = {}
        return state

    # ---- client: put -----------------------------------------------------

    async def put_to_storage_volume(self, volume, requests) -> None:
        total = sum(r.nbytes for r in requests)
        if 0 < total <= SMALL_INLINE_BYTES:
            # One-RPC small put: skip the segment handshake entirely.
            self.handshake_ops = ()
        else:
            self.arena_plan = self._compute_arena_plan(requests)
        return await super().put_to_storage_volume(volume, requests)

    def _compute_arena_plan(self, requests) -> Optional[dict]:
        """Pack every tensor at or below the arena threshold into one shared
        segment: one handshake entry, one segment rotation, and one
        volume-side index pass for the whole small-key tail of a batch —
        instead of a pooled segment per key. A valid ``plan_hint`` from the
        iteration-stable plan cache (or a prewarm seed) is adopted verbatim
        so repeat iterations skip even the layout arithmetic."""
        config = self.config or default_config()
        limit = config.arena_max_bytes
        if limit <= 0:
            return None
        members = [
            idx
            for idx, req in enumerate(requests)
            if not req.is_object
            and req.tensor_val is not None
            and req.nbytes <= limit
        ]
        if len(members) < 2:
            return None  # nothing to amortize
        sizes = tuple(requests[idx].nbytes for idx in members)
        hint = (self.plan_hint or {}).get("arena")
        if (
            hint is not None
            and hint.get("sizes") == sizes
            and len(hint.get("offsets", ())) == len(members)
        ):
            offsets = hint["offsets"]
            total = hint["total"]
        else:
            offsets, total = landing.compute_arena_layout(list(sizes))
        return {
            "offsets": dict(zip(members, offsets)),
            "sizes": sizes,
            "total": total,
        }

    async def _pre_put_hook(self, volume, requests) -> None:
        if self.handshake_ops:
            return  # handshake path already staged into segments
        cache: ShmClientCache = volume.transport_context.get_cache(ShmClientCache)
        self.released = cache.collect_released(volume.volume_id)
        for idx, req in enumerate(requests):
            if req.is_object:
                self.objects[idx] = req.objects
            else:
                self.inline[idx] = np.ascontiguousarray(req.tensor_val)

    def _pre_handshake(self, volume, requests, op) -> None:
        if op != "put":
            return
        cache: ShmClientCache = volume.transport_context.get_cache(ShmClientCache)
        self.released = cache.collect_released(volume.volume_id)

    async def _post_handshake(self, volume, requests, reply, op) -> None:
        if op != "put":
            return
        cache: ShmClientCache = volume.transport_context.get_cache(ShmClientCache)
        # The handshake RPC delivered the release batches; ack them (a failed
        # RPC leaves them unacked for retransmission instead).
        cache.ack_released(volume.volume_id, self.released)
        self.released = None
        offered: dict[int, ShmDescriptor] = reply or {}
        arena = self.arena_plan
        arena_seg: Optional[ShmSegment] = None
        if arena:
            arena_seg = self._attach_arena(volume, cache, offered, requests)
        # Landing copies for the whole batch are collected first, then fanned
        # out to the shared overlap pool: copies run concurrently with each
        # other (and, chunked, within one huge tensor) while the event loop
        # stays free for sibling volumes' RPCs.
        pairs: list[tuple[np.ndarray, np.ndarray]] = []
        for idx, req in enumerate(requests):
            if req.is_object:
                self.objects[idx] = req.objects
                continue
            arr = np.ascontiguousarray(req.tensor_val)
            meta = req.meta_only().tensor_meta
            if arena_seg is not None and idx in arena["offsets"]:
                # Arena member: no per-key descriptor rides the RPC — the
                # server rebuilds every member view from the (already
                # carried) arena plan plus the request metas.
                off = arena["offsets"][idx]
                cache.key_to_segments.setdefault(req.key, set()).add(
                    arena_seg.name
                )
                if arr.nbytes:
                    pairs.append((arena_seg.view(meta, off), arr))
                self._client_segments[idx] = arena_seg
                continue
            desc = offered.get(idx)
            if desc is not None and desc.meta == meta:
                seg = cache.attach(desc, req.key, volume.volume_id)
                _CLIENT_ATTACH.inc(outcome="offer_hit")
            else:
                _CLIENT_ATTACH.inc(outcome="cold_create")
                seg = ShmSegment.create(max(arr.nbytes, 1))
                desc = ShmDescriptor(seg.name, seg.size, meta)
                cache.segments[seg.name] = seg
                cache.key_to_segments.setdefault(req.key, set()).add(seg.name)
                cache.seg_volume[seg.name] = volume.volume_id
            # THE hot memcpy: client array -> shared segment (native
            # multi-threaded path; overlapped below).
            pairs.append((seg.view(meta, desc.offset), arr))
            self.descriptors[idx] = desc
            self._client_segments[idx] = seg
        await landing.land_async(
            pairs, stage="put", copy=fast_copy, config=self.config
        )

    def _attach_arena(
        self, volume, cache: "ShmClientCache", offered: dict, requests
    ) -> ShmSegment:
        """Resolve the batch's shared arena segment: the handshake's pooled
        offer when one arrived, a cold create otherwise."""
        arena = self.arena_plan
        size = max(int(arena["total"]), 1)
        desc = offered.get(ARENA_OFFER_KEY)
        if desc is not None and desc.segment_size >= size:
            first_key = requests[next(iter(arena["offsets"]))].key
            seg = cache.attach(desc, first_key, volume.volume_id)
            _CLIENT_ATTACH.inc(outcome="offer_hit")
        else:
            _CLIENT_ATTACH.inc(outcome="cold_create")
            seg = ShmSegment.create(size)
            cache.segments[seg.name] = seg
            cache.seg_volume[seg.name] = volume.volume_id
        arena["segment"] = seg.name
        arena["segment_size"] = seg.size
        landing.ARENA_KEYS.inc(len(arena["offsets"]), transport="shm")
        landing.ARENA_BYTES.inc(sum(arena["sizes"]), transport="shm")
        return seg

    def _handle_put_reply(self, volume, reply, requests) -> None:
        cache: ShmClientCache = volume.transport_context.get_cache(ShmClientCache)
        if self.released:
            # Inline (no-handshake) puts deliver releases with the put RPC
            # itself; the RPC succeeded, so ack the batches now.
            cache.ack_released(volume.volume_id, self.released)
            self.released = None
        if not reply:
            return
        for old_name, new_name in reply.get("renames", {}).items():
            cache.rekey(old_name, new_name)
        spares = reply.get("spares")
        if spares:
            cache.pre_attach(spares)

    # ---- server: put -----------------------------------------------------

    def recv_handshake(
        self, ctx: TransportContext, metas: list[Request], existing: dict, op: str
    ) -> Any:
        # Sync faultpoint: a "wedge" here blocks the volume's event loop —
        # the WHOLE process (pings included) looks dead to the supervisor,
        # the deterministic stand-in for a volume stuck in a native copy.
        from torchstore_tpu import faults

        faults.fire("shm.handshake")
        if op != "put":
            return None
        cache: ShmServerCache = ctx.get_cache(ShmServerCache)
        cache.adopt_config(self.config)
        cache.last_activity = time.monotonic()
        cache.apply_releases(self.released)
        cache.sweep()
        offered: dict[int, ShmDescriptor] = {}
        misses: list[int] = []
        arena = self.arena_plan
        arena_members = set(arena["offsets"]) if arena else set()
        if arena:
            # ONE offer serves the whole small-key tail of the batch: the
            # arena segment rotates through the pool exactly like a
            # per-key segment, just shared by every member entry.
            size = max(int(arena["total"]), 1)
            seg = self._offer_from_pool(cache, size)
            if seg is not None:
                offered[ARENA_OFFER_KEY] = ShmDescriptor(
                    seg.name,
                    seg.size,
                    TensorMeta(shape=(size,), dtype="uint8"),
                )
            else:
                misses.append(size)
        for idx, meta in enumerate(metas):
            if meta.tensor_meta is None or idx in arena_members:
                continue
            # Puts NEVER overwrite a live entry segment — between this
            # handshake and the put RPC a concurrent get could be serving
            # (or staging a copy of) the current content, and a cross-
            # process writer would tear it. Instead, offer a warm segment
            # from the free pool (retired segments return there once every
            # view lease is released), so steady-state put/get loops rotate
            # buffers instead of allocating cold ones; the old segment is
            # retired or pooled when the put lands (descriptor-reuse
            # handshake role, reference shared_memory.py:340-360, with
            # rotation instead of in-place overwrite).
            size = max(meta.tensor_meta.nbytes, 1)
            seg = self._offer_from_pool(cache, size)
            if seg is not None:
                offered[idx] = ShmDescriptor(
                    seg.name, seg.size, meta.tensor_meta
                )
            else:
                misses.append(size)
        if misses:
            # Warm spares for the sizes this handshake could NOT serve,
            # starting NOW: the client spends the next stretch copying its
            # working set, which is exactly the window the (executor-side,
            # MAP_POPULATE) warming can fill so the NEXT rotation of this
            # set draws warm segments.
            cache.schedule_warm(misses)
        return offered

    @staticmethod
    def _offer_from_pool(
        cache: "ShmServerCache", size: int
    ) -> Optional[ShmSegment]:
        """One handshake offer: pre-announced spares first (the client may
        have background-attached them already), then the warm free pool.
        The returned segment is reserved for the put now in flight."""
        names = cache.spare_by_size.get(size)
        while names:
            name = names.pop()
            entry = cache.reserved.get(name)
            if entry is not None:
                # Membership in `reserved` IS liveness: reserved segments
                # are only unlinked by sweep(), which removes them from
                # `reserved` in the same step. Refresh the reservation
                # timestamp for the put now in flight.
                cache.reserved[name] = (entry[0], time.monotonic())
                _POOL_OFFERS.inc(outcome="spare")
                return entry[0]
        pooled = cache.take_free(size)
        if pooled is not None:
            _POOL_OFFERS.inc(outcome="pooled")
            cache.reserved[pooled.name] = (pooled, time.monotonic())
            return pooled
        _POOL_OFFERS.inc(outcome="miss")
        return None

    def handle_put_request(
        self, ctx: TransportContext, metas: list[Request], existing: dict
    ) -> dict[int, Any]:
        cache: ShmServerCache = ctx.get_cache(ShmServerCache)
        cache.adopt_config(self.config)
        cache.last_activity = time.monotonic()
        cache.apply_releases(self.released)
        out: dict[int, Any] = {}
        for idx, obj in self.objects.items():
            out[idx] = _copy_obj(obj) if self.inproc_copy else obj
        cold_sizes: list[int] = []
        cold_inline: list[int] = []
        for idx, arr in self.inline.items():
            # Small inline put: the VOLUME lands the payload into a pooled
            # segment, so these entries get the same zero-copy get serving
            # as handshake puts. Volume-created segments already carry the
            # volume's pid — no rename round trip needed.
            meta = metas[idx]
            coords = meta.tensor_slice.coordinates if meta.tensor_slice else None
            tmeta = TensorMeta.of(arr)
            seg = cache.take_free(max(arr.nbytes, 1))
            if seg is None:
                # Residual cold path (the arena makes this rare): dispatch
                # must not stall on segment population, so the create skips
                # MAP_POPULATE (an inline payload is <= 64 KB — its few
                # pages fault during the landing copy) and the warm pool is
                # scheduled to absorb the NEXT inline put of this size.
                seg = ShmSegment.create(max(arr.nbytes, 1), populate=False)
                cold_inline.append(max(arr.nbytes, 1))
            view = seg.view(tmeta)
            copy_into(view, arr)
            cache.put(meta.key, coords, seg, tmeta)
            out[idx] = view
        if cold_inline:
            cache.schedule_warm(cold_inline)
        arena = self.arena_plan
        arena_seg: Optional[ShmSegment] = None
        arena_name = arena.get("segment") if arena else None
        if arena_name:
            # Resolve the batch's shared arena segment ONCE; every member
            # below is a pure view+index step against it.
            reserved = cache.reserved.pop(arena_name, None)
            if reserved is not None:
                arena_seg = reserved[0]
            else:
                arena_seg = ShmSegment.attach(
                    arena_name, arena["segment_size"]
                )
                arena_seg.owner = True
                old_name = arena_seg.name
                arena_seg.rename_to_owner()
                self.renames[old_name] = arena_seg.name
                cold_sizes.append(arena_seg.size)
            # One volume-side index pass: each arena member becomes a view
            # at its packed offset (meta from the request list — members
            # carry no per-key descriptors); the segment's entry refcount
            # keeps it alive until the last member is replaced/deleted.
            for idx, off in arena["offsets"].items():
                meta = metas[idx]
                coords = (
                    meta.tensor_slice.coordinates if meta.tensor_slice else None
                )
                tmeta = meta.tensor_meta
                cache.put(meta.key, coords, arena_seg, tmeta)
                out[idx] = arena_seg.view(tmeta, off)
        for idx, desc in self.descriptors.items():
            meta = metas[idx]
            coords = meta.tensor_slice.coordinates if meta.tensor_slice else None
            current = cache.lookup(meta.key, coords)
            reserved = cache.reserved.pop(desc.segment_name, None)
            if current is not None and current.seg.name == desc.segment_name:
                seg = current.seg  # in-place overwrite of the live segment
            elif reserved is not None:
                seg = reserved[0]  # pooled segment, already volume-owned
            else:
                seg = ShmSegment.attach(desc.segment_name, desc.segment_size)
                seg.owner = True  # volume takes ownership of the lifetime
                # The name's pid must track ownership (see rename_to_owner);
                # future handshakes/gets serve the new name from the cache —
                # and the client is told via put_reply so its attachment
                # cache follows the rename instead of leaking.
                old_name = seg.name
                seg.rename_to_owner()
                self.renames[old_name] = seg.name
                cold_sizes.append(seg.size)
            cache.put(meta.key, coords, seg, desc.meta)
            out[idx] = seg.view(desc.meta, desc.offset)
        if cold_sizes:
            # Pool misses: warm same-sized spares in the background so the
            # next push of this working set starts warm.
            cache.schedule_warm(cold_sizes)
            # Spares already warm (handshake-time warming ran during the
            # client's copy): reserve them NOW and announce them in the put
            # reply — the client pre-attaches off the critical path and the
            # next handshake offers exactly these names, so the second
            # rotation of a working set pays neither allocation nor attach.
            for size in cold_sizes:
                seg = cache.take_free(size)
                if seg is None:
                    continue
                cache.reserved[seg.name] = (seg, time.monotonic())
                cache.spare_by_size.setdefault(size, []).append(seg.name)
                self.spares.append((seg.name, size))
        return out

    def put_reply(self):
        reply = {}
        if self.renames:
            reply["renames"] = self.renames
        if self.spares:
            reply["spares"] = self.spares
        return reply or None

    # ---- server: get -----------------------------------------------------

    def handle_get_request(
        self, ctx: TransportContext, metas: list[Request], entries: list[Any]
    ) -> None:
        cache: ShmServerCache = ctx.get_cache(ShmServerCache)
        cache.adopt_config(self.config)
        cache.last_activity = time.monotonic()
        cache.apply_releases(self.released)
        cache.sweep()
        for idx, (meta, entry) in enumerate(zip(metas, entries)):
            if meta.is_object:
                self.objects[idx] = _copy_obj(entry) if self.inproc_copy else entry
                continue
            entry = np.asarray(entry)
            desc = self._serve_descriptor(cache, meta, entry)
            if desc is not None:
                self.descriptors[idx] = desc
                continue
            # Not segment-backed (or write-pending): stage a copy whose
            # ownership transfers to the client (client unlinks after
            # landing; the server reaps it after a TTL otherwise).
            tmeta = TensorMeta.of(entry)
            seg = ShmSegment.create(max(tmeta.nbytes, 1))
            fast_copy(seg.view(tmeta), entry)
            cache.track_staged(seg)
            self.descriptors[idx] = ShmDescriptor(
                seg.name, seg.size, tmeta, owner="client"
            )

    def _serve_descriptor(
        self, cache: ShmServerCache, meta: Request, entry: np.ndarray
    ) -> Optional[ShmDescriptor]:
        """Zero-copy descriptor for ``entry`` if it aliases an entry segment
        (whole tensors AND sub-slice views — any non-negative-stride view of
        segment memory is expressible as offset+strides)."""
        loc = cache.locate(meta.key, entry)
        if loc is None:
            return None
        stored, offset = loc
        seg = stored.seg
        strides = entry.strides
        if any(s < 0 for s in strides):
            return None
        extent = entry.itemsize + sum(
            (d - 1) * s for d, s in zip(entry.shape, strides) if d > 0
        )
        if offset + extent > seg.size:
            return None
        # Lease for EVERY volume-owned serve: zero-copy views hold it until
        # GC'd; in-place destination copies hold it only until the client's
        # copy lands (released on its next RPC). Either way a concurrent
        # put can never be offered this segment mid-read.
        cache.grant(seg.name)
        # One-sided annotation: a stable (even) entry stamp rides the
        # descriptor so the client can serve warm repeats of this exact
        # request with zero RPCs (stamped_read_batch).
        stamp = None
        if stored.slot is not None and cache.stamps is not None:
            gen = cache.stamps.read(stored.slot)
            if gen % 2 == 0:
                stamp = (
                    cache.stamps.seg.name,
                    cache.stamps.seg.size,
                    stored.slot,
                    gen,
                )
        return ShmDescriptor(
            seg.name,
            seg.size,
            TensorMeta.of(entry),
            offset=offset,
            strides=None if entry.flags["C_CONTIGUOUS"] else tuple(strides),
            stamp=stamp,
        )

    # ---- client: get -----------------------------------------------------

    async def _pre_get_hook(self, volume, requests) -> None:
        cache: ShmClientCache = volume.transport_context.get_cache(ShmClientCache)
        self.released = cache.collect_released(volume.volume_id)

    async def _handle_storage_volume_response(
        self, volume, remote: "SharedMemoryTransportBuffer", requests
    ) -> list[Any]:
        cache: ShmClientCache = volume.transport_context.get_cache(ShmClientCache)
        # The get RPC (which carried self.released) succeeded: ack batches.
        cache.ack_released(volume.volume_id, self.released)
        self.released = None
        zero_copy = self.config is None or self.config.zero_copy_get
        results: list[Any] = []
        # Landing copies are collected, fanned out to the overlap pool
        # together, and only then do the per-copy completions (lease
        # releases, staged-segment unlinks) run — a failed landing leaves
        # those to the server's TTL sweeps instead of mis-releasing.
        pairs: list[tuple[np.ndarray, np.ndarray]] = []
        done: list = []
        for idx, req in enumerate(requests):
            if req.is_object or idx in remote.objects:
                results.append(remote.objects[idx])
                continue
            desc = remote.descriptors[idx]
            if desc.owner == "client":
                seg = ShmSegment.attach(
                    desc.segment_name, desc.segment_size, populate=True
                )
                src = seg.view(desc.meta, desc.offset)
                if req.destination_view is not None:
                    landed = req.destination_view
                else:
                    landed = np.empty(src.shape, src.dtype)
                pairs.append((landed, src))
                done.append(seg.unlink)
                results.append(landed)
                continue
            seg = cache.attach(desc, req.key, volume.volume_id)
            if self.config is None or self.config.one_sided:
                # Stamp-annotated serve: cache it as a one-sided plan so the
                # client's next repeat of this exact request skips the RPC.
                cache.record_one_sided(volume.volume_id, req, desc)
            src = seg.strided_view(desc.meta, desc.offset, desc.strides)
            if req.destination_view is not None:
                pairs.append((req.destination_view, src))
                # Once the copy lands: release the read lease the volume
                # granted for the duration of this in-place read.
                done.append(
                    lambda name=desc.segment_name: cache.count_release(name)
                )
                results.append(req.destination_view)
            elif zero_copy:
                # Zero-copy read: hand out a read-only snapshot view of the
                # live segment (the volume retires, never overwrites, leased
                # segments). Released automatically when the array is GC'd.
                src.flags.writeable = False
                cache.track_view(desc.segment_name, src)
                results.append(src)
            else:
                # Copying instead of keeping the view: release once landed.
                buf = np.empty(src.shape, src.dtype)
                pairs.append((buf, src))
                done.append(
                    lambda name=desc.segment_name: cache.count_release(name)
                )
                results.append(buf)
        await landing.land_async(pairs, stage="get", config=self.config)
        for fn in done:
            fn()
        return results

    def drop(self) -> None:
        # self.released is NOT re-credited here: unacked batches persist in
        # the client cache and retransmit on the next RPC to that volume.
        self.descriptors = {}
        self.objects = {}
        self.inline = {}
        self.arena_plan = None
        self.released = None
        self.renames = {}
        self._client_segments = {}
