"""Bulk-socket transport: the flagship cross-host data path.

TPU-native analog of the reference's torchcomms/uniflow transport
(/root/reference/torchstore/transport/torchcomms/uniflow_buffer.py:43-580):
tensor bytes move over a dedicated TCP channel between client and volume
(riding DCN across TPU hosts; loopback within one), never through the RPC
codec. It reproduces uniflow's hard-won semantics:

- **Two-phase handshake**: the RPC handshake returns the volume's bulk
  endpoint; the client connects and keeps the connection *handshake-scoped*.
- **Promote-on-success**: the connection is published to the reusable
  per-volume cache only in ``_post_request_success`` — a failed request can
  never poison the cache (reference invariant 5, uniflow_buffer.py:88-116).
- **Abort**: dropped puts send an abort frame so the volume discards any
  partially-landed session bytes (uniflow_buffer.py:224-250).
- **Registration cache**: client arrays register once per (ptr, nbytes)
  with weakref eviction (torchcomms/cache.py:150-186); the native backend
  pins pages here.

IO rides RAW non-blocking sockets via ``loop.sock_sendall`` /
``sock_recv_into`` — payload bytes go kernel<->array with no user-space
staging copies (asyncio streams would add a transport-buffer copy per
direction, which measurably halves loopback throughput). Wire format:
``<session u64><idx u32><nbytes u64>`` + payload. PUT payloads are pushed
before the RPC lands (the volume awaits their arrival); GET payloads are
streamed by a background task after the RPC response so neither side blocks
the other (deadlock-free for arbitrarily large transfers).
"""

from __future__ import annotations

import asyncio
import socket
import struct
import time
import uuid
from typing import Any, Optional

import numpy as np

from torchstore_tpu import faults
from torchstore_tpu.config import StoreConfig, _env_int, default_config
from torchstore_tpu.logging import get_logger
from torchstore_tpu.native import fast_copy
from torchstore_tpu.observability import ledger as obs_ledger
from torchstore_tpu.observability import metrics as obs_metrics
from torchstore_tpu.transport.buffers import (
    TransportBuffer,
    TransportCache,
    TransportContext,
)
from torchstore_tpu.transport.cache import ArrayRegistrationCache
from torchstore_tpu.utils import spawn_logged
from torchstore_tpu.transport.types import Request, TensorMeta

logger = get_logger("torchstore_tpu.transport.bulk")


def _env_emulate_gbps() -> float:
    import os

    try:
        return float(os.environ.get("TORCHSTORE_TPU_BULK_EMULATE_GBPS", "0") or 0)
    except ValueError:
        return 0.0


# Emulated link bandwidth (GB/s) for benches/tests: when > 0, every payload
# frame send adds the wall time a link of that bandwidth would need on top
# of the real (loopback) transfer — so a single-host bench measures the
# cross-host DCN regime this transport actually targets (where the
# quantized/delta wire tier earns its keep). Production: leave unset —
# the pace check is one float compare per frame. Parsed at import and
# re-read after fork (actor children apply their corrected env first);
# same-process benches call set_emulated_gbps().
_EMULATE_GBPS = _env_emulate_gbps()


def set_emulated_gbps(gbps: Optional[float]) -> float:
    """Set (or, with None, re-read from env) the emulated link bandwidth
    for THIS process; returns the previous value so benches can restore."""
    global _EMULATE_GBPS
    prev = _EMULATE_GBPS
    _EMULATE_GBPS = _env_emulate_gbps() if gbps is None else float(gbps)
    return prev


def reinit_after_fork() -> None:
    """Re-read the emulated-bandwidth knob from the child's corrected env
    (the forkserver's module state carries the spawner's value)."""
    set_emulated_gbps(None)


async def _pace(nbytes: int) -> None:
    """Emulated-DCN pacing for one payload frame (no-op when disabled)."""
    if _EMULATE_GBPS > 0 and nbytes > 0:
        await asyncio.sleep(nbytes / (_EMULATE_GBPS * 1e9))


_FRAME = struct.Struct("<QIQ")
IDX_HELLO = 0xFFFFFFFF
IDX_ABORT = 0xFFFFFFFE
# Announces "get payloads for this session go to THIS connection" — one
# client may hold several connections to a volume (concurrent first
# requests), so routing by client id alone would misdeliver. The server acks
# it (same idx back) so the client can order the frame ahead of the get RPC,
# which travels on an independent TCP connection.
IDX_SESSION_OPEN = 0xFFFFFFFD
# Striped payload chunk: the frame body starts with a _STRIPE subheader
# (real_idx, byte offset, total bytes) followed by the chunk. Large
# transfers split into stripes ridden over several connections in parallel
# (the uniflow multi-QP striping role, uniflow_buffer.py:400-497).
IDX_STRIPED = 0xFFFFFFFC
# One frame carrying the PACKED small-key payload of a put batch (offset
# table rides the RPC manifest): 2048 small tensors cost one header +
# one sendall instead of 2048 framed sends — the DCN analog of the SHM
# arena. Not a control index: the server stores it like any payload.
IDX_PACKED = 0xFFFFFFFB
# One-sided warm get: the client rings "plan N ready?" with an 8-byte plan
# id instead of a get RPC; the volume streams every member of the cached
# plan back in a single IDX_PACKED reply (bracketed by its landing stamp),
# or answers with an IDX_DOORBELL miss frame carrying a 1-byte reason —
# the client then falls back loudly to the RPC path.
IDX_DOORBELL = 0xFFFFFFFA
# Push-on-publish subscription: an 8-byte plan id on a HELLO'd connection
# registers a PERSISTENT per-(client, volume) push session for that doorbell
# plan — the volume then streams the plan proactively every time its keys
# are freshly watermarked, instead of waiting for the next ring.
IDX_PUSH_SUB = 0xFFFFFFF9
# Proactive push frame: the session field carries the PLAN id; the payload
# is a u32 member count + per-member u64 write generations (pack-time, in
# plan order) + the packed arena bytes. The client stages it and the next
# acquire validates the generations against the MIRRORED watermark before
# serving — first byte becomes a local memcpy.
IDX_PUSHED = 0xFFFFFFF8
_CONTROL_IDXS = frozenset({IDX_HELLO, IDX_ABORT, IDX_SESSION_OPEN, IDX_STRIPED})

_U64 = struct.Struct("<Q")
_U32 = struct.Struct("<I")

# Doorbell miss reasons (1-byte reply payload -> fallback metric label).
# evicted_plan vs unknown_plan is the silent-eviction fix: a ring that
# misses because DOORBELL_PLANS_MAX cycled the table is attributable
# (ts_doorbell_plans_evicted_total moved), not a mystery cold start.
_DOORBELL_MISS = {
    0: "unknown_plan",
    1: "missing_key",
    2: "meta_drift",
    3: "torn",
    4: "busy",
    5: "evicted_plan",
}

# Server-side cached get plans awaiting doorbells; wholesale clear on
# overflow (a warm working set re-registers in one iteration).
DOORBELL_PLANS_MAX = 512

_STRIPE = struct.Struct("<IQQ")  # real_idx, offset, total_nbytes
# Payloads above this are striped across STRIPE_CONNS connections (puts,
# get replies, and IDX_PACKED doorbell replies). Env-tunable so tests and
# operators can exercise striping at realistic-for-them sizes.
STRIPE_THRESHOLD = _env_int(
    "TORCHSTORE_TPU_BULK_STRIPE_THRESHOLD", 64 * 1024 * 1024
)
STRIPE_CONNS = 4

_DIALS = obs_metrics.counter(
    "ts_bulk_dials_total", "Bulk TCP connections dialed (main + stripe)"
)
_STRIPED = obs_metrics.counter(
    "ts_bulk_striped_transfers_total",
    "Payloads striped across parallel connections, by direction",
)
# Overload signal (ts.slo_report): doorbell plans resident in this server's
# table. Pinned near DOORBELL_PLANS_MAX means wholesale clears are churning
# warm clients back onto the RPC path.
_DOORBELL_PLANS = obs_metrics.gauge(
    "ts_doorbell_plans_resident",
    "One-sided doorbell get plans resident in this bulk server",
)
_DOORBELL_EVICTED = obs_metrics.counter(
    "ts_doorbell_plans_evicted_total",
    "Doorbell plans dropped by DOORBELL_PLANS_MAX table cycling",
)
_PUSH_SUBS = obs_metrics.gauge(
    "ts_push_sessions_resident",
    "Push-on-publish plan subscriptions resident in this bulk server",
)
_PUSH_FRAMES = obs_metrics.counter(
    "ts_push_frames_total",
    "Push-on-publish frames streamed by this bulk server, by outcome",
)
_PUSH_SERVES = obs_metrics.counter(
    "ts_push_serves_total",
    "Warm gets served from push-staged bytes (first byte = local memcpy)",
)
_PUSH_STAGED_BYTES = obs_metrics.gauge(
    "ts_push_staged_bytes",
    "Bytes currently resident in this client's push staging arenas",
)


def push_sessions_enabled() -> bool:
    """Push-on-publish bulk sessions (TORCHSTORE_TPU_PUSH_SESSIONS,
    default on): freshly-watermarked doorbell plans stream to subscribed
    clients proactively; off = pull-on-acquire doorbell rings only."""
    import os

    return os.environ.get(
        "TORCHSTORE_TPU_PUSH_SESSIONS", "1"
    ).strip().lower() not in ("0", "false", "no", "off", "")


def push_staging_max_bytes() -> int:
    """Per-client cap on push-staged bytes
    (TORCHSTORE_TPU_PUSH_STAGING_MAX_BYTES, default 1 GiB): staging past it
    evicts oldest-first — an evicted plan's next acquire falls back to the
    doorbell ring, never OOMs the trainer host."""
    import os

    try:
        return max(
            1 << 20,
            int(
                os.environ.get(
                    "TORCHSTORE_TPU_PUSH_STAGING_MAX_BYTES", 1 << 30
                )
            ),
        )
    except ValueError:
        return 1 << 30

# Volume-side session state (landed put bytes, abort markers) is purged after
# this long without the matching RPC arriving — a crashed client must not
# grow volume memory forever.
SESSION_TTL_S = 600.0


def is_available() -> bool:
    return True


def _new_id() -> int:
    return uuid.uuid4().int & ((1 << 64) - 1)


def _now() -> float:
    return time.monotonic()


# --------------------------------------------------------------------------
# raw-socket IO helpers
# --------------------------------------------------------------------------


async def _recv_exact(sock: socket.socket, view: memoryview) -> None:
    """Fill ``view`` from the socket (kernel -> destination, no staging)."""
    loop = asyncio.get_running_loop()
    pos = 0
    total = view.nbytes
    while pos < total:
        n = await loop.sock_recv_into(sock, view[pos:])
        if n == 0:
            raise ConnectionError("bulk peer closed mid-frame")
        pos += n


async def _discard(sock: socket.socket, nbytes: int) -> None:
    """Consume and drop payload bytes addressed to an unknown session."""
    if nbytes <= 0:
        return
    scratch = memoryview(bytearray(min(nbytes, 1 << 16)))
    loop = asyncio.get_running_loop()
    left = nbytes
    while left:
        n = await loop.sock_recv_into(sock, scratch[: min(left, len(scratch))])
        if n == 0:
            raise ConnectionError("bulk peer closed mid-frame")
        left -= n


async def _send_frame(
    sock: socket.socket,
    lock: asyncio.Lock,
    session: int,
    idx: int,
    payload: Optional[memoryview],
) -> None:
    if await faults.afire("bulk.send_frame") == "drop-frame":
        return  # frame silently lost: the receiver's deadline machinery owns recovery
    loop = asyncio.get_running_loop()
    async with lock:
        nbytes = payload.nbytes if payload is not None else 0
        await loop.sock_sendall(sock, _FRAME.pack(session, idx, nbytes))
        if payload is not None:
            await loop.sock_sendall(sock, payload)
            await _pace(nbytes)


async def _send_frame_raw(
    sock: socket.socket,
    session: int,
    idx: int,
    subheader: bytes,
    payload: memoryview,
) -> None:
    """Frame with a stripe subheader; CALLER holds the write lock."""
    loop = asyncio.get_running_loop()
    await loop.sock_sendall(
        sock, _FRAME.pack(session, idx, len(subheader) + payload.nbytes)
    )
    await loop.sock_sendall(sock, subheader)
    await loop.sock_sendall(sock, payload)
    await _pace(payload.nbytes)


def _shutdown_sock(sock: socket.socket) -> None:
    """Wake the connection's reader with an error; the READER then joins
    in-flight sends and closes the fd (single deterministic owner)."""
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass


def _close_sock(sock: Optional[socket.socket]) -> None:
    """Immediate close — ONLY safe when no loop.sock_* op can be pending on
    this socket (dial failures, teardown without a loop)."""
    if sock is not None:
        try:
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            sock.close()
        except OSError:
            pass


def _family_for(host: str) -> int:
    return socket.AF_INET6 if ":" in host else socket.AF_INET


def _stripe_ranges(total: int, n: int, k: int) -> list[tuple[int, int]]:
    """Byte ranges connection ``k`` of ``n`` carries when striping a
    ``total``-byte payload: contiguous chunks round-robined so every
    connection streams in parallel (shared by the put, get-reply, and
    doorbell-reply striping paths)."""
    chunk = -(-total // n)
    return [
        (off, min(off + chunk, total))
        for off in range(k * chunk, total, chunk * n)
    ]


# --------------------------------------------------------------------------
# server side (storage volume process)
# --------------------------------------------------------------------------


class BulkServer:
    """Per-volume bulk listener: receives put payloads into a session table,
    streams get payloads back over the client's registered connection."""

    def __init__(self) -> None:
        self._listen_sock: Optional[socket.socket] = None
        self._accept_task: Optional[asyncio.Task] = None
        self.port: Optional[int] = None
        self.host: str = "127.0.0.1"
        # (session, idx) -> bytearray of landed payload
        self.incoming: dict[tuple[int, int], bytearray] = {}
        # (session, idx) -> [bytearray(total), bytes_received] while striped
        # chunks are still arriving (possibly over several connections)
        self._stripe_asm: dict[tuple[int, int], list] = {}
        self.aborted: set[int] = set()
        self._session_ts: dict[int, float] = {}  # last activity per session
        self._arrival = asyncio.Condition()
        # client_id -> (sock, write_lock) for outgoing get payloads
        self.client_conns: dict[int, tuple[socket.socket, asyncio.Lock]] = {}
        # session -> [(sock, write_lock), ...]: every connection the client
        # opened for this get session; >1 means striped responses.
        self.session_conns: dict[int, list[tuple[socket.socket, asyncio.Lock]]] = {}
        self._conn_tasks: set[asyncio.Task] = set()
        # sock -> set[Task]: in-flight sends per connection, awaited before
        # that connection's fd is closed (deterministic teardown — no
        # sleep-based grace period).
        self._send_tasks: dict[socket.socket, set[asyncio.Task]] = {}
        # One-sided doorbell state: the StorageVolume of this process (set
        # by the volume at init — doorbell serves read its store directly,
        # no RPC dispatch) and the registered get plans
        # (plan_id -> {"metas": [Request], "serve_metas": [TensorMeta]}).
        self.doorbell_volume: Optional[Any] = None
        self.get_plans: dict[int, dict] = {}
        # Plan ids dropped by DOORBELL_PLANS_MAX cycling (insertion-ordered,
        # bounded): a ring that lands here misses as "evicted_plan", not
        # "unknown_plan" — eviction churn is attributable, never silent.
        self.evicted_plans: dict[int, None] = {}
        # Push-on-publish sessions: plan_id -> subscribed client id, the
        # reverse key index driving dirty marking, pack-time write gens per
        # key, and the pump that streams dirty plans at watermark time.
        self.push_subs: dict[int, int] = {}
        self._push_keys: dict[str, set[int]] = {}
        self._push_key_gens: dict[str, int] = {}
        self._push_dirty: set[int] = set()
        self._push_event = asyncio.Event()
        self._push_task: Optional[asyncio.Task] = None

    async def ensure_started(self, bind_host: str) -> tuple[str, int]:
        if self._listen_sock is None:
            import os

            sock = socket.socket(_family_for(bind_host), socket.SOCK_STREAM)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            sock.bind((bind_host, 0))
            sock.listen(64)
            sock.setblocking(False)
            self._listen_sock = sock
            self.port = sock.getsockname()[1]
            # Advertise a REACHABLE address, not the bind address: a volume
            # bound to 0.0.0.0 (cross-host DCN) must hand clients its real
            # hostname/IP (TORCHSTORE_TPU_ADVERTISE_HOST overrides).
            advertise = os.environ.get("TORCHSTORE_TPU_ADVERTISE_HOST")
            if advertise is None:
                advertise = (
                    socket.gethostname()
                    if bind_host in ("0.0.0.0", "::")
                    else bind_host
                )
            self.host = advertise
            self._accept_task = asyncio.ensure_future(self._accept_loop())
            logger.info(
                "bulk server bound %s:%s (advertised as %s)",
                bind_host,
                self.port,
                self.host,
            )
        return self.host, self.port

    async def _accept_loop(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            try:
                conn, _ = await loop.sock_accept(self._listen_sock)
            except asyncio.CancelledError:
                raise  # cancellation must mark the accept task cancelled
            except OSError as exc:
                # Transient accept failures (EMFILE/ECONNABORTED/...): log,
                # back off, keep accepting — the old asyncio.Server did the
                # same; dying here would strand every future client.
                if self._listen_sock is None or self._listen_sock.fileno() < 0:
                    return  # listener closed: normal shutdown
                logger.warning("bulk accept failed (%s); retrying in 1s", exc)
                # Not a RetryPolicy site: the accept loop must retry FOREVER
                # (a deadline here would strand every future client); this is
                # pacing against EMFILE churn, not a bounded retry.
                await asyncio.sleep(1.0)  # tslint: disable=retry-discipline
                continue
            conn.setblocking(False)
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            spawn_logged(
                self._handle_conn(conn),
                name="bulk.conn",
                tasks=self._conn_tasks,
                log=logger,
            )

    async def _handle_conn(self, sock: socket.socket) -> None:
        from torchstore_tpu.runtime.auth import server_authenticate_sock

        if not await server_authenticate_sock(sock):
            # No sends can be in flight yet and the auth recv just
            # completed — immediate close is safe.
            _close_sock(sock)
            return
        client_id = None
        conn_lock = asyncio.Lock()  # serializes all outgoing writes
        header = bytearray(_FRAME.size)
        header_view = memoryview(header)
        sub = bytearray(_STRIPE.size)
        try:
            while True:
                await _recv_exact(sock, header_view)
                session, idx, nbytes = _FRAME.unpack(header)
                if await faults.afire("bulk.recv_frame") == "drop-frame":
                    # Swallow the frame (payload drained so the stream stays
                    # parseable): the sender sees silence, not an error.
                    await _discard(sock, nbytes)
                    continue
                if idx == IDX_HELLO:
                    client_id = session
                    self.client_conns[client_id] = (sock, conn_lock)
                    continue
                if idx == IDX_SESSION_OPEN:
                    # Route this session's get payloads back on THIS exact
                    # connection (a client may hold several; several for ONE
                    # session means striped responses), then ack so the
                    # client knows routing is in place before it RPCs.
                    conns = self.session_conns.setdefault(session, [])
                    if all(c is not sock for c, _ in conns):
                        conns.append((sock, conn_lock))
                    self._session_ts[session] = _now()
                    await _send_frame(sock, conn_lock, session, IDX_SESSION_OPEN, None)
                    continue
                if idx == IDX_DOORBELL:
                    payload = bytearray(nbytes)
                    await _recv_exact(sock, memoryview(payload))
                    (plan_id,) = _U64.unpack(payload[:8])
                    # Serve off the reader loop (the pack copies must not
                    # block this connection's frame parsing); tracked in
                    # _send_tasks so teardown joins it before closing the fd.
                    spawn_logged(
                        self._serve_doorbell(session, plan_id, sock, conn_lock),
                        name="bulk.doorbell",
                        tasks=self._send_tasks.setdefault(sock, set()),
                        log=logger,
                    )
                    continue
                if idx == IDX_PUSH_SUB:
                    payload = bytearray(nbytes)
                    await _recv_exact(sock, memoryview(payload))
                    (plan_id,) = _U64.unpack(payload[:8])
                    # The session field doubles as the client id so a
                    # subscription can ride a connection whose HELLO raced
                    # this frame; pushes go to client_conns[client_id].
                    self.subscribe_push(
                        plan_id, client_id if client_id is not None else session
                    )
                    continue
                if idx == IDX_ABORT:
                    async with self._arrival:
                        self.aborted.add(session)
                        self._session_ts[session] = _now()
                        for key in [k for k in self.incoming if k[0] == session]:
                            del self.incoming[key]
                        for key in [k for k in self._stripe_asm if k[0] == session]:
                            del self._stripe_asm[key]
                        self._arrival.notify_all()
                    continue
                if idx == IDX_STRIPED:
                    await _recv_exact(sock, memoryview(sub))
                    real_idx, offset, total = _STRIPE.unpack(sub)
                    chunk_len = nbytes - _STRIPE.size
                    key = (session, real_idx)
                    asm = self._stripe_asm.get(key)
                    if asm is None:
                        asm = self._stripe_asm[key] = [bytearray(total), 0]
                    await _recv_exact(
                        sock, memoryview(asm[0])[offset : offset + chunk_len]
                    )
                    asm[1] += chunk_len
                    if asm[1] >= total:
                        async with self._arrival:
                            # pop, not del: an abort on another connection
                            # may have purged this assembly mid-chunk.
                            if self._stripe_asm.pop(key, None) is not None:
                                self.incoming[key] = asm[0]
                            self._session_ts[session] = _now()
                            self._purge_stale()
                            self._arrival.notify_all()
                    else:
                        self._session_ts[session] = _now()
                    continue
                buf = bytearray(nbytes)
                await _recv_exact(sock, memoryview(buf))
                async with self._arrival:
                    self.incoming[(session, idx)] = buf
                    self._session_ts[session] = _now()
                    self._purge_stale()
                    self._arrival.notify_all()
        except (ConnectionError, OSError):
            pass
        finally:
            if (
                client_id is not None
                and self.client_conns.get(client_id, (None,))[0] is sock
            ):
                self.client_conns.pop(client_id, None)
            for sess, conns in list(self.session_conns.items()):
                conns[:] = [(c, l) for c, l in conns if c is not sock]
                if not conns:
                    self.session_conns.pop(sess, None)
            # Deterministic teardown: cancel + await this connection's
            # in-flight sends, then close. The reader's own recv just
            # returned, so after the sends are joined no loop.sock_* op can
            # reference the fd.
            send_tasks = list(self._send_tasks.pop(sock, ()))
            for task in send_tasks:
                task.cancel()
            if send_tasks:
                # Join the cancelled sends without eating OUR OWN
                # cancellation: per-task outcomes land in the result list
                # (return_exceptions), while cancelling this reader during
                # the join cancels the gather future itself and propagates.
                await asyncio.gather(*send_tasks, return_exceptions=True)
            _close_sock(sock)

    def _purge_stale(self) -> None:
        """Drop per-session state older than SESSION_TTL_S (client crashed
        between pushing bytes and the RPC, or aborted a session whose RPC
        never ran). Called under the _arrival lock."""
        now = _now()
        stale = [s for s, ts in self._session_ts.items() if now - ts > SESSION_TTL_S]
        for session in stale:
            del self._session_ts[session]
            self.aborted.discard(session)
            self.session_conns.pop(session, None)
            for key in [k for k in self.incoming if k[0] == session]:
                del self.incoming[key]
            for key in [k for k in self._stripe_asm if k[0] == session]:
                del self._stripe_asm[key]

    async def collect(self, session: int, indices: list[int]) -> dict[int, bytearray]:
        """Await all payloads of a put session (bytes may arrive before or
        after the RPC)."""
        async with self._arrival:
            try:
                while True:
                    if session in self.aborted:
                        self.aborted.discard(session)
                        raise ConnectionError(
                            f"bulk session {session} aborted by client"
                        )
                    if all((session, i) in self.incoming for i in indices):
                        return {
                            i: self.incoming.pop((session, i)) for i in indices
                        }
                    await self._arrival.wait()
            finally:
                self._session_ts.pop(session, None)

    def send_background(
        self, client_id: int, session: int, payloads: dict[int, np.ndarray]
    ) -> None:
        """Stream get payloads without blocking the RPC response (avoiding
        the write-write deadlock for payloads larger than socket buffers).
        With several session connections, large payloads are STRIPED across
        them (one in-flight chunk per connection — parallel TCP streams for
        DCN throughput)."""
        conns = self.session_conns.pop(session, None)
        if not conns:
            fallback = self.client_conns.get(client_id)
            if fallback is None:
                raise ConnectionError(
                    f"no bulk connection registered for client {client_id}"
                )
            conns = [fallback]

        def _track(sock: socket.socket, coro) -> asyncio.Task:
            task = asyncio.ensure_future(coro)
            bucket = self._send_tasks.setdefault(sock, set())
            bucket.add(task)
            task.add_done_callback(bucket.discard)
            return task

        async def _send_plain(sock, lock, frames: list[tuple[int, np.ndarray]]):
            async def _send_all() -> None:
                for idx, arr in frames:
                    view = memoryview(np.ascontiguousarray(arr)).cast("B")
                    await _send_frame(sock, lock, session, idx, view)

            try:
                # asyncio.wait_for, not asyncio.timeout: this image runs
                # Python 3.10 (asyncio.timeout landed in 3.11) and the
                # AttributeError was killing every bulk get send.
                await asyncio.wait_for(_send_all(), timeout=SESSION_TTL_S)
            except (TimeoutError, asyncio.TimeoutError):
                # The cancelled sendall may have left a PARTIAL frame on the
                # wire — the connection's framing is unrecoverable; kill it
                # (the reader task then joins sends and closes).
                logger.warning(
                    "bulk get send timed out (session=%s); closing connection",
                    session,
                )
                _shutdown_sock(sock)
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("bulk get send failed (session=%s)", session)

        async def _send_stripes(sock, lock, idx, view, ranges, total):
            async def _send_all() -> None:
                for off, end in ranges:
                    sub = _STRIPE.pack(idx, off, total)
                    async with lock:
                        await _send_frame_raw(
                            sock, session, IDX_STRIPED, sub, view[off:end]
                        )

            try:
                await asyncio.wait_for(_send_all(), timeout=SESSION_TTL_S)
            except (TimeoutError, asyncio.TimeoutError):
                logger.warning(
                    "bulk striped send timed out (session=%s); closing",
                    session,
                )
                _shutdown_sock(sock)
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("bulk striped send failed (session=%s)", session)

        plain: list[tuple[int, np.ndarray]] = []
        for idx, arr in payloads.items():
            nbytes = arr.nbytes
            if len(conns) > 1 and nbytes > STRIPE_THRESHOLD:
                view = memoryview(np.ascontiguousarray(arr)).cast("B")
                for k, (sock, lock) in enumerate(conns):
                    ranges = _stripe_ranges(nbytes, len(conns), k)
                    if ranges:
                        _track(
                            sock,
                            _send_stripes(sock, lock, idx, view, ranges, nbytes),
                        )
            else:
                plain.append((idx, arr))
        if plain:
            sock, lock = conns[0]
            _track(sock, _send_plain(sock, lock, plain))

    def register_plan(self, metas, serve_metas) -> int:
        """Cache a served get batch as a doorbell plan; returns the plan id
        the client rings to repeat the batch without the get RPC."""
        if len(self.get_plans) >= DOORBELL_PLANS_MAX:
            evicted = list(self.get_plans)
            self.get_plans.clear()
            _DOORBELL_EVICTED.inc(len(evicted))
            for pid in evicted:
                # Remember WHO was cycled out (bounded, oldest dropped
                # first) so the victim's next ring misses attributably;
                # its push session dies with the plan.
                self.evicted_plans[pid] = None
                self._drop_push_sub(pid)
            while len(self.evicted_plans) > 4 * DOORBELL_PLANS_MAX:
                self.evicted_plans.pop(next(iter(self.evicted_plans)))
        plan_id = _new_id()
        self.get_plans[plan_id] = {
            "metas": list(metas),
            "serve_metas": list(serve_metas),
        }
        _DOORBELL_PLANS.set(len(self.get_plans))
        return plan_id

    # ---- push-on-publish sessions ----------------------------------------

    def subscribe_push(self, plan_id: int, client_id: int) -> bool:
        """Register a persistent push session for a registered plan: every
        future watermark landing on the plan's keys streams the whole plan
        to ``client_id``'s HELLO connection proactively. Unknown plans are
        refused silently — the client's acquire just keeps ringing."""
        if not push_sessions_enabled():
            return False
        plan = self.get_plans.get(plan_id)
        if plan is None:
            return False
        self.push_subs[plan_id] = client_id
        for meta in plan["metas"]:
            self._push_keys.setdefault(meta.key, set()).add(plan_id)
        _PUSH_SUBS.set(len(self.push_subs))
        return True

    def _drop_push_sub(self, plan_id: int) -> None:
        if self.push_subs.pop(plan_id, None) is None:
            return
        for key in [k for k, p in self._push_keys.items() if plan_id in p]:
            pids = self._push_keys[key]
            pids.discard(plan_id)
            if not pids:
                del self._push_keys[key]
        self._push_dirty.discard(plan_id)
        _PUSH_SUBS.set(len(self.push_subs))

    def notify_landed(self, gens: dict[str, int]) -> None:
        """The volume just committed a put/pull batch (write gens bumped):
        mark every subscribed plan touching those keys dirty and kick the
        pump. Called synchronously from the volume's endpoint — must stay
        O(touched plans), no IO."""
        if gens:
            for key, gen in gens.items():
                prev = self._push_key_gens.get(key, 0)
                if gen > prev:
                    self._push_key_gens[key] = gen
        if not self.push_subs or not gens:
            return
        dirty = False
        for key in gens:
            for pid in self._push_keys.get(key, ()):
                self._push_dirty.add(pid)
                dirty = True
        if dirty:
            self._push_event.set()
            if self._push_task is None or self._push_task.done():
                self._push_task = spawn_logged(
                    self._push_pump(),
                    name="bulk.push_pump",
                    tasks=self._conn_tasks,
                    log=logger,
                )

    async def _push_pump(self) -> None:
        """Drain dirty plans into IDX_PUSHED frames until the set stays
        empty past an idle window (re-spawned by the next notify). One
        serial pump: pushes for one client never interleave frames, and a
        burst of landings coalesces into one push per plan."""
        idle_s = 5.0
        while True:
            self._push_event.clear()
            dirty = list(self._push_dirty)
            self._push_dirty.clear()
            if not dirty:
                try:
                    await asyncio.wait_for(self._push_event.wait(), idle_s)
                except asyncio.TimeoutError:
                    if not self._push_dirty:
                        return
                continue
            for plan_id in dirty:
                try:
                    await self._serve_push(plan_id)
                except asyncio.CancelledError:
                    raise
                except Exception:  # noqa: BLE001 - push is an optimization;
                    # a failed push must never kill the pump (the client's
                    # doorbell ring still serves)
                    logger.exception("push serve failed (plan=%s)", plan_id)

    async def _serve_push(self, plan_id: int) -> None:
        """Pack one dirty plan (same landing-stamp bracket as the doorbell
        serve) and stream it to the subscribed client with its pack-time
        write generations. Any impossibility DROPS the subscription — the
        client's next acquire falls back loudly to the ring/RPC ladder."""
        from torchstore_tpu.transport import landing

        vol = self.doorbell_volume
        plan = self.get_plans.get(plan_id)
        client_id = self.push_subs.get(plan_id)
        if client_id is None:
            return
        if vol is None or plan is None:
            self._drop_push_sub(plan_id)
            return
        conn = self.client_conns.get(client_id)
        if conn is None:
            # The client's HELLO connection is gone (crashed/reset): a
            # push session without a live lane is dead, loudly.
            self._drop_push_sub(plan_id)
            _PUSH_FRAMES.inc(outcome="dead_conn")
            return
        stamp0 = vol._landing_stamp
        if vol._landing_inflight:
            # Mid-landing: that landing's own notify re-dirties this plan
            # only if it touches our keys, so re-dirty explicitly and let
            # the pump retry after yielding.
            self._push_dirty.add(plan_id)
            self._push_event.set()
            await asyncio.sleep(0.001)
            return
        arrays: list[np.ndarray] = []
        keys: list[str] = []
        try:
            for meta, expect in zip(plan["metas"], plan["serve_metas"]):
                arr = np.ascontiguousarray(vol.store.get_data(meta))
                if TensorMeta.of(arr) != expect:
                    # Shape/dtype drift: the client's staged unpack layout
                    # is wrong now; the doorbell ring re-plans.
                    self._drop_push_sub(plan_id)
                    _PUSH_FRAMES.inc(outcome="meta_drift")
                    return
                arrays.append(arr)
                keys.append(meta.key)
        except KeyError:
            self._drop_push_sub(plan_id)
            _PUSH_FRAMES.inc(outcome="missing_key")
            return
        offsets, total = landing.compute_arena_layout(
            [a.nbytes for a in arrays]
        )
        packed = np.empty(total, np.uint8)
        pairs = [
            (
                packed[off : off + a.nbytes],
                np.frombuffer(a, dtype=np.uint8),
            )
            for a, off in zip(arrays, offsets)
            if a.nbytes
        ]
        await landing.land_async(pairs, stage="push")
        if vol._landing_inflight or vol._landing_stamp != stamp0:
            # A landing raced the pack: the arena may mix generations.
            # Never ship it — re-dirty and let the pump retry clean.
            self._push_dirty.add(plan_id)
            self._push_event.set()
            _PUSH_FRAMES.inc(outcome="torn_retry")
            return
        gens = [self._push_key_gens.get(k, 0) for k in keys]
        sub = _U32.pack(len(keys)) + b"".join(_U64.pack(g) for g in gens)
        view = memoryview(packed).cast("B")
        # Volume-side egress accounting, peer-less like the doorbell serve:
        # the RECEIVER's staging cell carries the attributable host->host
        # edge (count-once rule), this keeps the volume's own totals honest.
        if obs_ledger.ledger().enabled:
            obs_ledger.record(
                "bulk_push",
                obs_ledger.EGRESS,
                view.nbytes,
                volume=str(getattr(vol, "volume_id", "")),
                items=[
                    (k, expect.nbytes)
                    for k, expect in zip(keys, plan["serve_metas"])
                ],
            )
        try:
            async with conn[1]:
                await _send_frame_raw(
                    conn[0], plan_id, IDX_PUSHED, sub, view
                )
            _PUSH_FRAMES.inc(outcome="sent")
        except (ConnectionError, OSError):
            self._drop_push_sub(plan_id)
            _PUSH_FRAMES.inc(outcome="dead_conn")

    async def _serve_doorbell(
        self,
        session: int,
        plan_id: int,
        sock: socket.socket,
        lock: asyncio.Lock,
    ) -> None:
        """Answer one doorbell: re-read every member of the cached plan from
        the volume's store, pack them at the shared arena layout, and stream
        ONE IDX_PACKED frame back — bracketed by the volume's landing stamp
        so a reply that raced ANY landing is declared torn (miss frame) and
        the client falls back to the RPC path, which serves a consistent
        snapshot. Replies ride the session's registered connection(s): a
        packed reply above the striping threshold whose session the client
        carried over several connections is STRIPED across them (the same
        parallel-TCP path multi-GB get replies already ride)."""
        from torchstore_tpu.transport import landing

        conns = self.session_conns.pop(session, None) or [(sock, lock)]

        async def miss(code: int) -> None:
            try:
                await _send_frame(
                    sock, lock, session, IDX_DOORBELL, memoryview(bytes([code]))
                )
            except (ConnectionError, OSError):
                pass  # client gone: its timeout owns the fallback

        vol = self.doorbell_volume
        plan = self.get_plans.get(plan_id)
        if vol is None or plan is None:
            return await miss(
                5 if plan is None and plan_id in self.evicted_plans else 0
            )
        stamp0 = vol._landing_stamp
        if vol._landing_inflight:
            return await miss(4)  # a landing is mid-flight right now
        arrays: list[np.ndarray] = []
        try:
            for meta, expect in zip(plan["metas"], plan["serve_metas"]):
                arr = np.ascontiguousarray(vol.store.get_data(meta))
                if TensorMeta.of(arr) != expect:
                    # Shape/dtype drift since registration: the client's
                    # cached unpack layout no longer matches.
                    del self.get_plans[plan_id]
                    _DOORBELL_PLANS.set(len(self.get_plans))
                    return await miss(2)
                arrays.append(arr)
        except KeyError:
            del self.get_plans[plan_id]
            _DOORBELL_PLANS.set(len(self.get_plans))
            return await miss(1)
        offsets, total = landing.compute_arena_layout(
            [a.nbytes for a in arrays]
        )
        packed = np.empty(total, np.uint8)
        pairs = [
            (
                packed[off : off + a.nbytes],
                np.frombuffer(a, dtype=np.uint8),
            )
            for a, off in zip(arrays, offsets)
            if a.nbytes
        ]
        await landing.land_async(pairs, stage="doorbell")
        if vol._landing_inflight or vol._landing_stamp != stamp0:
            # A put/delete landed (or is still landing) while we packed:
            # the packed bytes may mix generations — never serve them.
            # The stamp bumps at every bracket open, so inflight==0 at
            # both ends plus an unchanged stamp proves no overlap even
            # when landings themselves overlapped each other.
            return await miss(3)
        view = memoryview(packed).cast("B")
        # Volume-side egress accounting: doorbell serves never pass through
        # the volume.get endpoint, so without this line the volume's own
        # ledger would miss its one-sided-served bytes (peer unknown here —
        # the client-side cell carries the attributable edge).
        if obs_ledger.ledger().enabled:
            obs_ledger.record(
                "bulk",
                obs_ledger.EGRESS,
                view.nbytes,
                volume=str(getattr(vol, "volume_id", "")),
                items=[
                    (meta.key, expect.nbytes)
                    for meta, expect in zip(
                        plan["metas"], plan["serve_metas"]
                    )
                ],
            )
        if len(conns) > 1 and view.nbytes > STRIPE_THRESHOLD:
            # Multi-GB packed reply: stripe contiguous chunks round-robin
            # over every connection the client opened for this session
            # (the ROADMAP item-4 "remaining depth" — doorbells no longer
            # fall off the parallel-TCP path above the threshold).
            _STRIPED.inc(direction="doorbell")
            total = view.nbytes

            async def send_on(k: int, s_sock, s_lock) -> None:
                for off, end in _stripe_ranges(total, len(conns), k):
                    async with s_lock:
                        await _send_frame_raw(
                            s_sock,
                            session,
                            IDX_STRIPED,
                            _STRIPE.pack(IDX_PACKED, off, total),
                            view[off:end],
                        )

            try:
                # Same stall guard as the get-reply stripes: a client that
                # stops READING while keeping TCP open would otherwise
                # block sendall forever, wedging this serve task and
                # pinning the packed buffer for the volume's lifetime.
                await asyncio.wait_for(
                    asyncio.gather(
                        *(send_on(k, s, l) for k, (s, l) in enumerate(conns))
                    ),
                    timeout=SESSION_TTL_S,
                )
            except (TimeoutError, asyncio.TimeoutError):
                logger.warning(
                    "bulk doorbell striped send timed out (session=%s); "
                    "closing connections",
                    session,
                )
                for s_sock, _ in conns:
                    _shutdown_sock(s_sock)
            except (ConnectionError, OSError):
                pass  # client gone: its timeout owns the fallback
            return
        try:
            await _send_frame(
                sock, lock, session, IDX_PACKED, view
            )
        except (ConnectionError, OSError):
            pass


class BulkServerCache(TransportCache):
    def __init__(self) -> None:
        self.server = BulkServer()

    def clear(self) -> None:
        self.server.incoming.clear()


# --------------------------------------------------------------------------
# client side
# --------------------------------------------------------------------------


# Queue marker: the payload was received straight into the registered
# destination view (no staging buffer to hand back).
LANDED = object()


class _SessionEntry:
    """Per-get-session client state, SHARED by every connection carrying
    the session (main + stripe connections land into the same
    destinations/assembly buffers)."""

    __slots__ = ("queue", "dests", "stripes")

    def __init__(self) -> None:
        self.queue: asyncio.Queue = asyncio.Queue()
        # idx -> contiguous destination memoryview (recv lands in place —
        # kernel -> destination, zero staging copies; VERDICT r1 item 3)
        self.dests: dict[int, memoryview] = {}
        # idx -> [target_view, received, total] while stripes arrive
        self.stripes: dict[int, list] = {}


class BulkClientConn:
    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.write_lock = asyncio.Lock()
        self.closed = False
        self.sessions: dict[int, _SessionEntry] = {}
        # Push-on-publish sink: set by the cache when a push session rides
        # this connection; receives (plan_id, raw_frame_bytes) for every
        # IDX_PUSHED frame (session field = plan id, not a get session).
        self.push_sink = None
        self._reader_task = asyncio.ensure_future(self._demux())

    async def _demux(self) -> None:
        header = bytearray(_FRAME.size)
        header_view = memoryview(header)
        sub = bytearray(_STRIPE.size)
        try:
            while True:
                await _recv_exact(self.sock, header_view)
                session, idx, nbytes = _FRAME.unpack(header)
                if idx == IDX_PUSHED:
                    buf = bytearray(nbytes)
                    if nbytes:
                        await _recv_exact(self.sock, memoryview(buf))
                    sink = self.push_sink
                    if sink is not None:
                        sink(session, buf)
                    continue
                entry = self.sessions.get(session)
                if idx == IDX_STRIPED:
                    await _recv_exact(self.sock, memoryview(sub))
                    real_idx, offset, total = _STRIPE.unpack(sub)
                    chunk_len = nbytes - _STRIPE.size
                    if entry is None:
                        await _discard(self.sock, chunk_len)
                        continue
                    st = entry.stripes.get(real_idx)
                    if st is None:
                        dest = entry.dests.get(real_idx)
                        if dest is not None and dest.nbytes == total:
                            st = [dest, 0, total, True]
                        else:
                            st = [memoryview(bytearray(total)), 0, total, False]
                        entry.stripes[real_idx] = st
                    await _recv_exact(
                        self.sock, st[0][offset : offset + chunk_len]
                    )
                    st[1] += chunk_len
                    if st[1] >= total:
                        del entry.stripes[real_idx]
                        entry.queue.put_nowait(
                            (real_idx, LANDED if st[3] else st[0].obj)
                        )
                    continue
                if idx in _CONTROL_IDXS:
                    if nbytes:
                        await _discard(self.sock, nbytes)
                    if entry is not None:
                        entry.queue.put_nowait((idx, None))
                    continue
                dest = entry.dests.get(idx) if entry is not None else None
                if dest is not None and dest.nbytes == nbytes:
                    await _recv_exact(self.sock, dest)
                    entry.queue.put_nowait((idx, LANDED))
                    continue
                buf = bytearray(nbytes)
                if nbytes:
                    await _recv_exact(self.sock, memoryview(buf))
                if entry is not None:
                    entry.queue.put_nowait((idx, buf))
        except (ConnectionError, OSError):
            for entry in self.sessions.values():
                entry.queue.put_nowait((None, None))
        finally:
            # The recv op just completed/failed, so the fd is unregistered:
            # safe to close here (and only here) in the reader's own task.
            self.closed = True
            try:
                self.sock.close()
            except OSError:
                pass

    def register_session(self, session: int) -> _SessionEntry:
        entry = _SessionEntry()
        self.sessions[session] = entry
        return entry

    def adopt_session(self, session: int, entry: _SessionEntry) -> None:
        """Carry an existing session on THIS connection too (striping)."""
        self.sessions[session] = entry

    def release_session(self, session: int) -> None:
        self.sessions.pop(session, None)

    def close_now(self) -> None:
        """Mark closed and wake the reader (which owns the actual close).
        Never closes the fd directly — in-flight loop.sock_* ops on a
        closed-and-reused fd corrupt the selector state."""
        self.closed = True
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass


async def _dial(host: str, port: int, timeout: float) -> socket.socket:
    loop = asyncio.get_running_loop()
    # Resolve first so IPv6-only hosts work (AF from the resolved address).
    infos = await loop.getaddrinfo(host, port, type=socket.SOCK_STREAM)
    family, _, _, _, sockaddr = infos[0]
    sock = socket.socket(family, socket.SOCK_STREAM)
    sock.setblocking(False)
    try:
        await asyncio.wait_for(loop.sock_connect(sock, sockaddr), timeout)
    except BaseException:
        _close_sock(sock)
        raise
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    from torchstore_tpu.runtime.auth import client_authenticate_sock

    try:
        await client_authenticate_sock(sock)
    except BaseException:
        _close_sock(sock)
        raise
    _DIALS.inc()
    return sock


class BulkClientCache(TransportCache):
    """Promoted, reusable per-volume connections (uniflow's connected-
    transport bucket), plus extra per-volume connections used to stripe
    large transfers."""

    def __init__(self) -> None:
        self.client_id = _new_id()
        self.connections: dict[str, BulkClientConn] = {}
        self.stripe_conns: dict[str, list[BulkClientConn]] = {}
        self.endpoints: dict[str, tuple[str, int]] = {}
        # One-sided doorbell plans: (volume_id, request signature) ->
        # {"plan_id", "metas": [TensorMeta], "offsets", "total"} recorded
        # from plan-annotated get replies. Dropped wholesale on placement-
        # epoch bumps (the client owns that) and per-plan on any miss.
        self.doorbells: dict[tuple, dict] = {}
        # Push-on-publish staging: plan_id -> {"gens": [u64...], "data":
        # bytearray (packed arena), "volume_id", "hostname"} — the freshest
        # pushed copy of each subscribed plan, insertion-ordered for
        # oldest-first eviction at push_staging_max_bytes(). Serving is
        # gated on the mirrored watermark (stamped_write_gens): staged gens
        # must be at least the committed index's — never a stale serve.
        self.push_staging: dict[int, dict] = {}
        self.push_staged_bytes = 0
        self.push_subscribed: set[int] = set()
        # Wired by the client at volume load: (keys, volume_id) ->
        # {key: committed write gen} off the stamped/mirrored index, or
        # None when unattached/stale (push then misses "unvalidated").
        self.push_validate = None

    DOORBELLS_MAX = 4096

    def stage_push(
        self, plan_id: int, raw: bytearray, volume_id: str, hostname: str
    ) -> None:
        """Adopt one IDX_PUSHED frame: parse the gen table, replace any
        older staged copy, evict oldest-first past the staging cap, and
        record the receiver-side ingress cell (the count-once host->host
        edge — the volume's egress cell is peer-less)."""
        if len(raw) < _U32.size:
            return
        (nk,) = _U32.unpack_from(raw, 0)
        need = _U32.size + _U64.size * nk
        if len(raw) < need:
            return
        gens = list(struct.unpack_from(f"<{nk}Q", raw, _U32.size))
        data = bytes(memoryview(raw)[need:])
        prev = self.push_staging.pop(plan_id, None)
        if prev is not None:
            self.push_staged_bytes -= len(prev["data"])
        cap = push_staging_max_bytes()
        if len(data) > cap:
            _PUSH_STAGED_BYTES.set(self.push_staged_bytes)
            return  # a single over-cap plan never stages
        while self.push_staged_bytes + len(data) > cap and self.push_staging:
            victim = self.push_staging.pop(next(iter(self.push_staging)))
            self.push_staged_bytes -= len(victim["data"])
        self.push_staging[plan_id] = {
            "gens": gens,
            "data": data,
            "volume_id": volume_id,
            "hostname": hostname,
        }
        self.push_staged_bytes += len(data)
        _PUSH_STAGED_BYTES.set(self.push_staged_bytes)
        if obs_ledger.ledger().enabled:
            obs_ledger.record(
                "bulk_push",
                obs_ledger.INGRESS,
                len(data),
                peer_host=hostname or "",
                volume=volume_id,
            )

    def push_sink_for(self, volume):
        vid = volume.volume_id
        hostname = getattr(volume, "hostname", "") or ""

        def _sink(plan_id: int, raw: bytearray) -> None:
            self.stage_push(plan_id, raw, vid, hostname)

        return _sink

    def drop_staged(self, plan_id: int) -> None:
        prev = self.push_staging.pop(plan_id, None)
        if prev is not None:
            self.push_staged_bytes -= len(prev["data"])
            _PUSH_STAGED_BYTES.set(self.push_staged_bytes)
        self.push_subscribed.discard(plan_id)

    def drop_one_sided(self) -> int:
        """Drop every cached doorbell plan AND push-staged arena
        (placement-epoch bump: the placement they describe changed)."""
        n = len(self.doorbells)
        self.doorbells.clear()
        self.push_staging.clear()
        self.push_staged_bytes = 0
        self.push_subscribed.clear()
        _PUSH_STAGED_BYTES.set(0)
        return n

    def get_alive(self, volume_id: str) -> Optional[BulkClientConn]:
        conn = self.connections.get(volume_id)
        if conn is not None and conn.closed:
            del self.connections[volume_id]
            return None
        return conn

    async def get_stripe_conns(
        self, volume_id: str, n: int, timeout: float
    ) -> list[BulkClientConn]:
        """Up to ``n`` extra live connections for striping (dialed lazily,
        reused forever). Returns [] when the endpoint is unknown."""
        endpoint = self.endpoints.get(volume_id)
        if endpoint is None:
            return []
        conns = [
            c for c in self.stripe_conns.get(volume_id, []) if not c.closed
        ]
        self.stripe_conns[volume_id] = conns  # keep even on partial dials
        try:
            while len(conns) < n:
                sock = await _dial(endpoint[0], endpoint[1], timeout)
                conns.append(BulkClientConn(sock))
        except (ConnectionError, OSError, asyncio.TimeoutError):
            # Striping is an optimization: degrade to however many
            # connections dialed (possibly none) instead of failing the
            # transfer that the main connection can still carry.
            pass
        return conns

    def delete_key(self, key: str) -> None:
        for dkey in [d for d in self.doorbells if any(k == key for k, _ in d[1])]:
            self.drop_staged(self.doorbells[dkey].get("plan_id"))
            del self.doorbells[dkey]

    def clear(self) -> None:
        for conn in self.connections.values():
            conn.close_now()
        self.connections.clear()
        for conns in self.stripe_conns.values():
            for conn in conns:
                conn.close_now()
        self.stripe_conns.clear()
        self.endpoints.clear()
        self.doorbells.clear()
        self.push_staging.clear()
        self.push_staged_bytes = 0
        self.push_subscribed.clear()
        _PUSH_STAGED_BYTES.set(0)


async def prewarm_connection(
    volume, config: Optional[StoreConfig] = None, stripes: int = 0
) -> int:
    """Cold-start provisioning for the bulk rung: perform the two-phase
    endpoint handshake, dial + authenticate the main connection, promote it
    to the per-volume cache (a successful dial IS the success the
    promote-on-success invariant gates on), and optionally pre-open the
    stripe set so a large first transfer stripes from byte zero. Returns
    the number of fresh dials made (0 when everything was already warm).
    Raises on dial/handshake failure — the prewarm orchestrator reports and
    degrades to the lazy path."""
    config = config or default_config()
    cache: BulkClientCache = volume.transport_context.get_cache(BulkClientCache)
    dials = 0
    if cache.get_alive(volume.volume_id) is None:
        buffer = BulkTransportBuffer(config)
        await buffer._ensure_conn(volume)
        buffer._post_request_success(volume)
        if not buffer._promoted:
            # Lost a promote race with a concurrent first request; the cache
            # has a live connection either way — close the spare.
            buffer._conn.close_now()
        else:
            dials += 1
    if stripes > 0:
        before = len(
            [c for c in cache.stripe_conns.get(volume.volume_id, []) if not c.closed]
        )
        conns = await cache.get_stripe_conns(
            volume.volume_id, stripes, config.handshake_timeout
        )
        dials += max(0, len(conns) - before)
    return dials


def prewarm_registrations(volume, arrays) -> int:
    """Warm the array-registration cache for ``arrays`` (the buffers a bulk
    put will pin/register): repeat puts of the same working set then skip
    per-(ptr, nbytes) registration on the critical path."""
    regs: ArrayRegistrationCache = volume.transport_context.get_cache(
        ArrayRegistrationCache
    )
    count = 0
    for arr in arrays:
        if isinstance(arr, np.ndarray) and arr.flags["C_CONTIGUOUS"]:
            regs.register(arr)
            count += 1
    return count


class BulkTransportBuffer(TransportBuffer):
    transport_name = "bulk"
    requires_handshake = True  # dynamically skipped when a promoted conn exists
    supports_inplace = True
    requires_contiguous_inplace = False
    supports_batch_puts = True
    supports_batch_gets = True
    # Process-wide retention for in-flight abort/close tasks: drop() returns
    # synchronously and the buffer instance may be GC'd immediately after,
    # so the cleanup task must be anchored somewhere that outlives it.
    _cleanup_tasks: set = set()

    def __init__(
        self, config: Optional[StoreConfig] = None, inproc_copy: bool = False
    ):
        self.config = config or default_config()
        # Colocated dispatch: object payloads ride the buffer by reference;
        # deep-copy on store/serve preserves value semantics (tensor bytes
        # always cross the socket and are safe).
        self.inproc_copy = inproc_copy
        self.session = _new_id()
        self.client_id: Optional[int] = None
        # RPC-carried metadata
        self.manifest: dict[int, TensorMeta] = {}
        # Packed small-key frame: request idx -> (byte offset, TensorMeta)
        # into the single IDX_PACKED payload (the DCN arena).
        self.packed_manifest: dict[int, tuple[int, TensorMeta]] = {}
        self.packed_total = 0
        self.objects: dict[int, Any] = {}
        self.descriptors: dict[int, TensorMeta] = {}
        # Doorbell plan id advertised by the server in the get reply (the
        # client caches it and rings it instead of the next identical get
        # RPC); None when the batch is not one-sided-servable.
        self.doorbell_plan: Optional[int] = None
        # client-only live state
        self._conn: Optional[BulkClientConn] = None
        self._promoted = False
        self._volume_id: Optional[str] = None
        self._entry: Optional[_SessionEntry] = None
        self._session_carriers: list[BulkClientConn] = []
        self._sent_put = False
        self._succeeded = False

    def __getstate__(self):
        # config (a plain dataclass) travels with the buffer: the server-side
        # hooks read timeouts from it.
        state = self.__dict__.copy()
        for field in ("_conn", "_entry", "_session_carriers"):
            state[field] = None if field != "_session_carriers" else []
        return state

    # ---- connection management ------------------------------------------

    async def _ensure_conn(self, volume) -> BulkClientConn:
        cache: BulkClientCache = volume.transport_context.get_cache(BulkClientCache)
        self.client_id = cache.client_id
        self._volume_id = volume.volume_id
        conn = cache.get_alive(volume.volume_id)
        if conn is not None:
            self._conn = conn
            self._promoted = True  # already published
            return conn
        # Two-phase: RPC handshake learns the endpoint, then we dial it.
        endpoint = await volume.actor.handshake.call_one(self, [], "bulk_connect")
        host, port = endpoint
        cache.endpoints[volume.volume_id] = (host, port)  # for stripe dials
        sock = await _dial(host, port, self.config.handshake_timeout)
        conn = BulkClientConn(sock)
        await _send_frame(sock, conn.write_lock, cache.client_id, IDX_HELLO, None)
        self._conn = conn
        self._promoted = False  # handshake-scoped until success
        return conn

    def _post_request_success(self, volume) -> None:
        # Promote-on-success: publish the handshake-scoped connection. Under
        # a concurrent first-request storm only one connection wins the
        # cache slot; the rest stay handshake-scoped and close at drop().
        self._succeeded = True
        if self._conn is not None and not self._promoted:
            cache: BulkClientCache = volume.transport_context.get_cache(
                BulkClientCache
            )
            if cache.get_alive(volume.volume_id) is None:
                cache.connections[volume.volume_id] = self._conn
                self._promoted = True

    # ---- client: put -----------------------------------------------------

    async def put_to_storage_volume(self, volume, requests: list[Request]) -> None:
        await self._ensure_conn(volume)
        return await super().put_to_storage_volume(volume, requests)

    @staticmethod
    def _doorbell_key(volume, requests: list[Request]) -> Optional[tuple]:
        from torchstore_tpu.transport.shared_memory import slice_sig

        if any(r.is_object for r in requests):
            return None
        return (
            volume.volume_id,
            tuple((r.key, slice_sig(r.tensor_slice)) for r in requests),
        )

    async def get_from_storage_volume(self, volume, requests: list[Request]):
        from torchstore_tpu.transport.shared_memory import (
            ONE_SIDED_FALLBACKS,
            ONE_SIDED_TORN,
            OneSidedMiss,
        )

        await self._ensure_conn(volume)
        if self.config is None or self.config.one_sided:
            cache: BulkClientCache = volume.transport_context.get_cache(
                BulkClientCache
            )
            dkey = self._doorbell_key(volume, requests)
            entry = cache.doorbells.get(dkey) if dkey is not None else None
            if entry is not None:
                staged = (
                    cache.push_staging.get(entry["plan_id"])
                    if push_sessions_enabled()
                    else None
                )
                if staged is not None:
                    try:
                        # Push-on-publish fast path: the plan's freshest
                        # bytes were streamed at watermark time — validate
                        # against the mirrored committed gens and serve
                        # with a LOCAL memcpy, no wire wait at all.
                        return await self._get_via_push(
                            volume, requests, entry, staged, cache
                        )
                    except OneSidedMiss as miss:
                        # Stale/unvalidatable staging: drop it and fall
                        # THROUGH to the doorbell ring (same plan), which
                        # serves a fresh consistent snapshot or escalates
                        # to the RPC ladder itself.
                        cache.drop_staged(entry["plan_id"])
                        ONE_SIDED_FALLBACKS.inc(
                            reason=f"push_{miss.reason}"
                        )
                try:
                    return await self._get_via_doorbell(volume, requests, entry)
                except OneSidedMiss as miss:
                    # Loud fallback: drop the plan (the RPC serve below
                    # re-registers a fresh one) and take the RPC path.
                    cache.doorbells.pop(dkey, None)
                    if miss.reason == "torn":
                        ONE_SIDED_TORN.inc(transport="bulk")
                    ONE_SIDED_FALLBACKS.inc(
                        reason=f"doorbell_{miss.reason}"
                    )
                    # Fresh session id for the fallback: a TIMED-OUT
                    # doorbell's reply may still be in flight on this
                    # shared connection, and reusing the id would misroute
                    # that late IDX_PACKED/IDX_DOORBELL frame into the RPC
                    # get (the demux drains unknown-session frames, so
                    # under a new id the stale reply is read and dropped).
                    self.session = _new_id()
                    # The doorbell may have died with the connection; the
                    # RPC path needs a live one.
                    await self._ensure_conn(volume)
        try:
            return await self._get_with_session(volume, requests)
        finally:
            # Release on EVERY exit path — including session-open/ack
            # failures — or pooled connections accumulate dead session
            # entries pinning destination views forever.
            for carrier in self._session_carriers:
                carrier.release_session(self.session)
            self._session_carriers = []
            self._entry = None

    async def _get_with_session(self, volume, requests: list[Request]):
        self._entry = self._conn.register_session(self.session)
        self._session_carriers = [self._conn]
        # In-place destinations land straight from the kernel: register
        # contiguous destination views so the demux loop recv()s into them
        # (no intermediate buffer + copy).
        for idx, req in enumerate(requests):
            dest = req.destination_view
            if dest is None or not dest.flags["C_CONTIGUOUS"]:
                continue
            # Raw bytes land as-is: dtype AND shape must equal what the
            # volume will serve (the slice's local shape for sub-slice
            # requests, the stored shape otherwise) — a mismatch
            # (dtype-converting get, or stale location metadata after a
            # same-size re-publish) must take the copy-landing path, where
            # fast_copy's shape guard raises and triggers the fresh-locate
            # retry.
            if req.tensor_meta is None or req.tensor_meta.np_dtype != dest.dtype:
                continue
            served_shape = (
                req.tensor_slice.local_shape
                if req.tensor_slice is not None
                else req.tensor_meta.shape
            )
            if served_shape == tuple(dest.shape):
                self._entry.dests[idx] = memoryview(dest).cast("B")
        # Striping: when a single expected payload is large, carry this
        # session over extra connections; the server stripes across them.
        expect_large = any(
            m.tensor_meta is not None
            and m.tensor_meta.nbytes > STRIPE_THRESHOLD
            for m in (r.meta_only() for r in requests)
        )
        if expect_large:
            cache: BulkClientCache = volume.transport_context.get_cache(
                BulkClientCache
            )
            for extra in await cache.get_stripe_conns(
                volume.volume_id, STRIPE_CONNS - 1, self.config.handshake_timeout
            ):
                extra.adopt_session(self.session, self._entry)
                self._session_carriers.append(extra)
        acks_needed = len(self._session_carriers)
        for carrier in self._session_carriers:
            await _send_frame(
                carrier.sock,
                carrier.write_lock,
                self.session,
                IDX_SESSION_OPEN,
                None,
            )
        # Await every carrier's ack: the get RPC rides a different TCP
        # stream, so without this the volume could serve the get before
        # routing for this session exists (misdelivered/dropped payloads).
        for _ in range(acks_needed):
            ack_idx, _ = await asyncio.wait_for(
                self._entry.queue.get(), timeout=self.config.handshake_timeout
            )
            if ack_idx != IDX_SESSION_OPEN:
                raise ConnectionError(
                    f"bulk session-open handshake failed (got frame {ack_idx})"
                )
        return await super().get_from_storage_volume(volume, requests)

    async def _get_via_push(
        self, volume, requests: list[Request], entry: dict, staged: dict,
        cache: "BulkClientCache",
    ) -> list[Any]:
        """Serve a warm get from the push-staged arena: the bytes already
        crossed the wire at watermark time, so the reader's first byte is
        a LOCAL memcpy. Correctness gate: the staged pack-time write gens
        must be at least the COMMITTED gens the (possibly mirrored)
        stamped index holds for every member on this volume — a staging
        that missed a newer landing, or an unattached/lagging index, is a
        loud :class:`OneSidedMiss` and the doorbell ring serves instead.
        Never serves unvalidated bytes."""
        from torchstore_tpu.transport import landing
        from torchstore_tpu.transport.shared_memory import (
            ONE_SIDED_READS,
            OneSidedMiss,
        )

        if staged.get("volume_id") != volume.volume_id:
            raise OneSidedMiss("wrong_volume")
        gens = staged["gens"]
        data = staged["data"]
        if len(gens) != len(requests) or len(data) != int(entry["total"]):
            raise OneSidedMiss("layout")
        validate = cache.push_validate
        committed = (
            validate([r.key for r in requests], volume.volume_id)
            if validate is not None
            else None
        )
        if committed is None:
            raise OneSidedMiss("unvalidated")
        for req, gen in zip(requests, gens):
            if gen < committed.get(req.key, 0):
                raise OneSidedMiss("stale")
        results: list[Any] = []
        pairs: list[tuple[np.ndarray, np.ndarray]] = []
        for req, meta, off in zip(requests, entry["metas"], entry["offsets"]):
            count = int(np.prod(meta.shape)) if meta.shape else 1
            arr = np.frombuffer(
                data, dtype=meta.np_dtype, count=count, offset=off
            ).reshape(meta.shape)
            dest = req.destination_view
            if dest is not None:
                if (
                    tuple(dest.shape) != tuple(meta.shape)
                    or dest.dtype != meta.np_dtype
                ):
                    raise OneSidedMiss("shape")
                pairs.append((dest, arr))
                results.append(dest)
            else:
                results.append(arr)
        await landing.land_async(pairs, stage="push", config=self.config)
        ONE_SIDED_READS.inc(len(results), transport="bulk_push")
        _PUSH_SERVES.inc()
        # NO ledger cell here: the wire transfer was recorded at staging
        # time (stage_push's ingress edge) — this serve is a local memcpy
        # and recording it again would double-count the edge.
        return results

    async def _get_via_doorbell(
        self, volume, requests: list[Request], entry: dict
    ) -> list[Any]:
        """One-sided warm get over the bulk socket: ring the cached plan id
        (one tiny frame instead of the get RPC + per-key request frames),
        land the single IDX_PACKED reply straight into a pre-registered
        read buffer, and unpack members at the shared arena layout. A plan
        whose packed reply exceeds the striping threshold carries the
        session over the pre-opened stripe set first (acks awaited), so
        the volume stripes the reply across parallel TCP streams. Any miss
        frame, timeout, or connection loss raises
        :class:`shared_memory.OneSidedMiss` — the caller falls back loudly
        to the RPC path."""
        from torchstore_tpu.transport import landing
        from torchstore_tpu.transport.buffers import transfer_timeout
        from torchstore_tpu.transport.shared_memory import (
            ONE_SIDED_READS,
            OneSidedMiss,
        )

        conn = self._conn
        sess = conn.register_session(self.session)
        carriers = [conn]
        packed = bytearray(max(int(entry["total"]), 1))
        try:
            # Pre-registered read buffer: the demux loop recv()s the packed
            # reply kernel->buffer, no staging copy (striped chunks land at
            # their offsets in the same buffer).
            if entry["total"]:
                sess.dests[IDX_PACKED] = memoryview(packed)
            try:
                if int(entry["total"]) > STRIPE_THRESHOLD:
                    cache: BulkClientCache = (
                        volume.transport_context.get_cache(BulkClientCache)
                    )
                    for extra in await cache.get_stripe_conns(
                        volume.volume_id,
                        STRIPE_CONNS - 1,
                        self.config.handshake_timeout,
                    ):
                        extra.adopt_session(self.session, sess)
                        carriers.append(extra)
                for carrier in carriers:
                    await _send_frame(
                        carrier.sock,
                        carrier.write_lock,
                        self.session,
                        IDX_SESSION_OPEN,
                        None,
                    )
                if len(carriers) > 1:
                    # Stripe carriers ride independent TCP streams: their
                    # routing must be acked BEFORE the doorbell rings, or
                    # the volume could reply before session_conns lists
                    # them (single-connection sessions keep the zero-RTT
                    # same-connection ordering instead).
                    for _ in range(len(carriers)):
                        ack_idx, _ = await asyncio.wait_for(
                            sess.queue.get(),
                            timeout=self.config.handshake_timeout,
                        )
                        if ack_idx != IDX_SESSION_OPEN:
                            raise OneSidedMiss("protocol")
                await _send_frame(
                    conn.sock,
                    conn.write_lock,
                    self.session,
                    IDX_DOORBELL,
                    memoryview(_U64.pack(entry["plan_id"])),
                )
                timeout = transfer_timeout(
                    (self.config or default_config()).handshake_timeout,
                    int(entry["total"]),
                )
                while True:
                    idx, raw = await asyncio.wait_for(
                        sess.queue.get(), timeout=timeout
                    )
                    if idx == IDX_SESSION_OPEN:
                        continue  # the routing ack; the reply follows
                    break
            except (TimeoutError, asyncio.TimeoutError):
                raise OneSidedMiss("timeout") from None
            except (ConnectionError, OSError):
                raise OneSidedMiss("conn") from None
        finally:
            for carrier in carriers:
                carrier.release_session(self.session)
        if idx is None:
            raise OneSidedMiss("conn")
        if idx == IDX_DOORBELL:
            code = raw[0] if raw else 0
            raise OneSidedMiss(_DOORBELL_MISS.get(code, "unknown"))
        if idx != IDX_PACKED:
            raise OneSidedMiss("protocol")
        if raw is not LANDED:
            # Dest registration raced (or zero-size batch): the demux
            # buffered the payload instead.
            packed = raw if isinstance(raw, (bytes, bytearray)) else packed
        results: list[Any] = []
        pairs: list[tuple[np.ndarray, np.ndarray]] = []
        for req, meta, off in zip(requests, entry["metas"], entry["offsets"]):
            count = int(np.prod(meta.shape)) if meta.shape else 1
            arr = np.frombuffer(
                packed, dtype=meta.np_dtype, count=count, offset=off
            ).reshape(meta.shape)
            dest = req.destination_view
            if dest is not None:
                if (
                    tuple(dest.shape) != tuple(meta.shape)
                    or dest.dtype != meta.np_dtype
                ):
                    raise OneSidedMiss("shape")
                pairs.append((dest, arr))
                results.append(dest)
            else:
                results.append(arr)
        await landing.land_async(pairs, stage="doorbell", config=self.config)
        ONE_SIDED_READS.inc(len(results), transport="bulk")
        # Doorbell serves bypass the transport-buffer choke point: account
        # them here (the client knows both endpoints, so this cell feeds
        # the traffic matrix exactly like an RPC get would). Enabled check
        # outside so a disabled ledger skips the items build too.
        if obs_ledger.ledger().enabled:
            obs_ledger.record(
                "bulk",
                obs_ledger.INGRESS,
                int(entry["total"]),
                peer_host=volume.hostname or "",
                volume=volume.volume_id,
                items=[
                    (req.key, meta.nbytes)
                    for req, meta in zip(requests, entry["metas"])
                ],
            )
        return results

    async def _perform_handshake(self, volume, requests, op) -> None:
        # The real handshake (endpoint exchange + dial) happened in
        # _ensure_conn; nothing further to negotiate per-request.
        return None

    async def _pre_put_hook(self, volume, requests: list[Request]) -> None:
        regs: ArrayRegistrationCache = volume.transport_context.get_cache(
            ArrayRegistrationCache
        )
        cache: BulkClientCache = volume.transport_context.get_cache(
            BulkClientCache
        )
        packed_members = await self._pack_small_requests(requests)
        for idx, req in enumerate(requests):
            if req.is_object:
                self.objects[idx] = req.objects
                continue
            if idx in packed_members:
                continue  # rides the single packed frame
            arr = np.ascontiguousarray(req.tensor_val)
            regs.register(arr)
            self.manifest[idx] = TensorMeta.of(arr)
            view = memoryview(arr).cast("B")
            if arr.nbytes > STRIPE_THRESHOLD:
                extras = await cache.get_stripe_conns(
                    volume.volume_id,
                    STRIPE_CONNS - 1,
                    self.config.handshake_timeout,
                )
                if extras:
                    await self._send_striped(
                        idx, view, [self._conn, *extras]
                    )
                    continue
            await _send_frame(
                self._conn.sock,
                self._conn.write_lock,
                self.session,
                idx,
                view,
            )
        self._sent_put = True

    async def _pack_small_requests(self, requests: list[Request]) -> set[int]:
        """Pack every tensor at or below the arena threshold into ONE framed
        payload (offset table rides the RPC manifest): the per-key framing —
        a header, a lock round, and a sendall per tensor — collapses to a
        single frame for the whole small-key tail of the batch."""
        from torchstore_tpu.transport import landing

        limit = getattr(self.config, "arena_max_bytes", 0)
        if limit <= 0:
            return set()
        members = [
            idx
            for idx, req in enumerate(requests)
            if not req.is_object
            and req.tensor_val is not None
            and req.nbytes <= limit
        ]
        if len(members) < 2:
            return set()
        arrs = {
            idx: np.ascontiguousarray(requests[idx].tensor_val)
            for idx in members
        }
        offsets, total = landing.compute_arena_layout(
            [arrs[idx].nbytes for idx in members]
        )
        packed = np.empty(total, np.uint8)
        pairs = []
        for idx, off in zip(members, offsets):
            arr = arrs[idx]
            self.packed_manifest[idx] = (off, requests[idx].meta_only().tensor_meta)
            if arr.nbytes:
                pairs.append(
                    (
                        packed[off : off + arr.nbytes],
                        np.frombuffer(arr, dtype=np.uint8),
                    )
                )
        # land_async, not land_sync: this runs ON the event loop, and a
        # ~100 MB pack must not freeze concurrent replication fan-outs /
        # heartbeats for its full copy duration.
        await landing.land_async(pairs, stage="bulk_pack")
        self.packed_total = total
        landing.ARENA_KEYS.inc(len(members), transport="bulk")
        landing.ARENA_BYTES.inc(sum(a.nbytes for a in arrs.values()), transport="bulk")
        await _send_frame(
            self._conn.sock,
            self._conn.write_lock,
            self.session,
            IDX_PACKED,
            memoryview(packed),
        )
        return set(members)

    async def _send_striped(
        self, idx: int, view: memoryview, conns: list[BulkClientConn]
    ) -> None:
        """Split one payload into contiguous chunks round-robined over the
        connections; each chunk frame carries (idx, offset, total) so the
        volume reassembles order-independently."""
        _STRIPED.inc(direction="put")
        total = view.nbytes

        async def send_on(k: int, conn: BulkClientConn) -> None:
            for off, end in _stripe_ranges(total, len(conns), k):
                async with conn.write_lock:
                    await _send_frame_raw(
                        conn.sock,
                        self.session,
                        IDX_STRIPED,
                        _STRIPE.pack(idx, off, total),
                        view[off:end],
                    )

        await asyncio.gather(
            *(send_on(k, conn) for k, conn in enumerate(conns))
        )

    # ---- server hooks ----------------------------------------------------

    async def recv_handshake(self, ctx: TransportContext, metas, existing, op: str):
        import os

        server: BulkServer = ctx.get_cache(BulkServerCache).server
        bind_host = os.environ.get("TORCHSTORE_TPU_BIND_HOST", "127.0.0.1")
        return await server.ensure_started(bind_host)

    async def handle_put_request(
        self, ctx: TransportContext, metas: list[Request], existing: dict
    ) -> dict[int, Any]:
        server: BulkServer = ctx.get_cache(BulkServerCache).server
        if self.inproc_copy and self.objects:
            import copy

            self.objects = {k: copy.deepcopy(v) for k, v in self.objects.items()}
        out: dict[int, Any] = dict(self.objects)
        from torchstore_tpu.transport.buffers import transfer_timeout

        # Size-scaled: a multi-GB DCN transfer slower than the flat
        # handshake timeout must not spuriously fail the put.
        total = sum(m.nbytes for m in self.manifest.values()) + self.packed_total
        indices = sorted(self.manifest)
        if self.packed_manifest:
            indices.append(IDX_PACKED)
        payloads = await asyncio.wait_for(
            server.collect(self.session, indices),
            timeout=transfer_timeout(self.config.handshake_timeout, total),
        )
        if self.packed_manifest:
            # One unpack pass serves the whole small-key tail: member
            # arrays are zero-copy views into the single packed frame.
            raw = payloads.pop(IDX_PACKED)
            for idx, (off, meta) in self.packed_manifest.items():
                count = int(np.prod(meta.shape)) if meta.shape else 1
                arr = np.frombuffer(
                    raw, dtype=meta.np_dtype, count=count, offset=off
                ).reshape(meta.shape)
                out[idx] = self._land_existing(existing, idx, arr)
        for idx, raw in payloads.items():
            meta = self.manifest[idx]
            arr = np.frombuffer(raw, dtype=meta.np_dtype).reshape(meta.shape)
            out[idx] = self._land_existing(existing, idx, arr)
        return out

    @staticmethod
    def _land_existing(existing: dict, idx: int, arr: np.ndarray):
        prev = existing.get(idx)
        if prev is not None and prev.shape == arr.shape and prev.dtype == arr.dtype:
            fast_copy(prev, arr)  # in-place reuse (invariant 6)
            return prev
        return arr

    def handle_get_request(
        self, ctx: TransportContext, metas: list[Request], entries: list[Any]
    ) -> None:
        server: BulkServer = ctx.get_cache(BulkServerCache).server
        payloads: dict[int, np.ndarray] = {}
        for idx, (meta, entry) in enumerate(zip(metas, entries)):
            if meta.is_object:
                if self.inproc_copy:
                    import copy

                    entry = copy.deepcopy(entry)
                self.objects[idx] = entry
                continue
            arr = np.ascontiguousarray(entry)
            self.descriptors[idx] = TensorMeta.of(arr)
            payloads[idx] = arr
        if (
            (self.config is None or self.config.one_sided)
            and payloads
            and len(payloads) == len(metas)
            and server.doorbell_volume is not None
        ):
            # All-tensor batch with a doorbell-capable volume: register the
            # plan; the id rides this buffer back in the get RPC reply and
            # the client's next identical batch rings it instead.
            self.doorbell_plan = server.register_plan(
                [m for m in metas],
                [self.descriptors[i] for i in range(len(metas))],
            )
        if payloads:
            server.send_background(self.client_id, self.session, payloads)

    # ---- client: get landing --------------------------------------------

    async def _handle_storage_volume_response(
        self, volume, remote: "BulkTransportBuffer", requests: list[Request]
    ) -> list[Any]:
        from torchstore_tpu.transport.buffers import transfer_timeout

        frame_timeout = transfer_timeout(
            self.config.rpc_timeout,
            sum(m.nbytes for m in remote.descriptors.values()),
        )
        expected = set(remote.descriptors)
        received: dict[int, Any] = {}
        while expected - set(received):
            idx, raw = await asyncio.wait_for(
                self._entry.queue.get(), timeout=frame_timeout
            )
            if idx is None:
                raise ConnectionError("bulk connection lost during get")
            received[idx] = raw
        results: list[Any] = []
        for idx, req in enumerate(requests):
            if req.is_object or idx in remote.objects:
                results.append(remote.objects[idx])
                continue
            meta = remote.descriptors[idx]
            raw = received[idx]
            if raw is LANDED:
                # Payload was recv()'d straight into the destination view.
                results.append(req.destination_view)
                continue
            arr = np.frombuffer(raw, dtype=meta.np_dtype).reshape(meta.shape)
            if req.destination_view is not None:
                # Fallback landing (non-contiguous dest or size mismatch).
                fast_copy(req.destination_view, arr)
                results.append(req.destination_view)
            else:
                results.append(arr)
        if remote.doorbell_plan is not None and (
            self.config is None or self.config.one_sided
        ):
            # Cache the server's plan id with the per-member layout so the
            # next identical batch unpacks the IDX_PACKED reply locally.
            from torchstore_tpu.transport import landing

            cache: BulkClientCache = volume.transport_context.get_cache(
                BulkClientCache
            )
            dkey = self._doorbell_key(volume, requests)
            if dkey is not None and len(remote.descriptors) == len(requests):
                member_metas = [
                    remote.descriptors[i] for i in range(len(requests))
                ]
                offsets, total = landing.compute_arena_layout(
                    [m.nbytes for m in member_metas]
                )
                if len(cache.doorbells) >= cache.DOORBELLS_MAX:
                    cache.doorbells.clear()
                cache.doorbells[dkey] = {
                    "plan_id": remote.doorbell_plan,
                    "metas": member_metas,
                    "offsets": offsets,
                    "total": total,
                }
                await self._subscribe_push(volume, cache, remote.doorbell_plan)
        return results

    async def _subscribe_push(
        self, volume, cache: "BulkClientCache", plan_id: int
    ) -> None:
        """Register the persistent push session for a freshly cached plan:
        one IDX_PUSH_SUB frame on the promoted (HELLO'd) connection, whose
        demux then stages every IDX_PUSHED frame the volume streams at
        watermark time. Best-effort — a failed subscription just leaves
        the plan on the doorbell-ring path."""
        if not push_sessions_enabled():
            return
        conn = cache.get_alive(volume.volume_id)
        if conn is None:
            return
        conn.push_sink = cache.push_sink_for(volume)
        try:
            await _send_frame(
                conn.sock,
                conn.write_lock,
                cache.client_id,
                IDX_PUSH_SUB,
                memoryview(_U64.pack(plan_id)),
            )
            cache.push_subscribed.add(plan_id)
        except (ConnectionError, OSError):
            pass

    # ---- cleanup ---------------------------------------------------------

    def drop(self) -> None:
        conn = self._conn
        if conn is not None:
            need_abort = self._sent_put and not self._succeeded and not conn.closed
            promoted = self._promoted
            session = self.session

            async def _cleanup() -> None:
                if need_abort:
                    # Failed put: abort so the volume discards landed bytes.
                    # Sent under the connection's write lock — a raw write
                    # could interleave into another request's payload stream
                    # on a shared promoted connection.
                    try:
                        await _send_frame(
                            conn.sock, conn.write_lock, session, IDX_ABORT, None
                        )
                    except Exception:
                        pass
                if not promoted:
                    # Handshake-scoped connection never gets published after
                    # a failure — close it (never poison the cache).
                    conn.close_now()

            try:
                spawn_logged(
                    _cleanup(),
                    name="bulk.cleanup",
                    tasks=BulkTransportBuffer._cleanup_tasks,
                    log=logger,
                )
            except RuntimeError:  # no running loop (interpreter teardown)
                if not promoted:
                    _close_sock(conn.sock)
        self._conn = None
        self.manifest = {}
        self.packed_manifest = {}
        self.packed_total = 0
        self.objects = {}
        self.descriptors = {}
