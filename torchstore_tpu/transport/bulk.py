"""Bulk-socket transport: the flagship cross-host data path.

TPU-native analog of the reference's torchcomms/uniflow transport
(/root/reference/torchstore/transport/torchcomms/uniflow_buffer.py:43-580):
tensor bytes move over a dedicated TCP channel between client and volume
(riding DCN across TPU hosts; loopback within one), never through the RPC
codec. It reproduces uniflow's hard-won semantics:

- **Two-phase handshake**: the RPC handshake returns the volume's bulk
  endpoint; the client connects and keeps the connection *handshake-scoped*.
- **Promote-on-success**: the connection is published to the reusable
  per-volume cache only in ``_post_request_success`` — a failed request can
  never poison the cache (reference invariant 5, uniflow_buffer.py:88-116).
- **Abort**: dropped puts send an abort frame so the volume discards any
  partially-landed session bytes (uniflow_buffer.py:224-250).
- **Registration cache**: client arrays register once per (ptr, nbytes)
  with weakref eviction (torchcomms/cache.py:150-186); the native backend
  pins pages here.

IO rides RAW non-blocking sockets via ``loop.sock_sendall`` /
``sock_recv_into`` — payload bytes go kernel<->array with no user-space
staging copies (asyncio streams would add a transport-buffer copy per
direction, which measurably halves loopback throughput). Wire format:
``<session u64><idx u32><nbytes u64>`` + payload. PUT payloads are pushed
before the RPC lands (the volume awaits their arrival); GET payloads are
streamed by a background task after the RPC response so neither side blocks
the other (deadlock-free for arbitrarily large transfers).
"""

from __future__ import annotations

import asyncio
import socket
import struct
import time
import uuid
from typing import Any, Optional

import numpy as np

from torchstore_tpu.config import StoreConfig, default_config
from torchstore_tpu.logging import get_logger
from torchstore_tpu.native import fast_copy
from torchstore_tpu.transport.buffers import (
    TransportBuffer,
    TransportCache,
    TransportContext,
)
from torchstore_tpu.transport.cache import ArrayRegistrationCache
from torchstore_tpu.transport.types import Request, TensorMeta

logger = get_logger("torchstore_tpu.transport.bulk")

_FRAME = struct.Struct("<QIQ")
IDX_HELLO = 0xFFFFFFFF
IDX_ABORT = 0xFFFFFFFE
# Announces "get payloads for this session go to THIS connection" — one
# client may hold several connections to a volume (concurrent first
# requests), so routing by client id alone would misdeliver. The server acks
# it (same idx back) so the client can order the frame ahead of the get RPC,
# which travels on an independent TCP connection.
IDX_SESSION_OPEN = 0xFFFFFFFD
_CONTROL_IDXS = frozenset({IDX_HELLO, IDX_ABORT, IDX_SESSION_OPEN})

# Volume-side session state (landed put bytes, abort markers) is purged after
# this long without the matching RPC arriving — a crashed client must not
# grow volume memory forever.
SESSION_TTL_S = 600.0


def is_available() -> bool:
    return True


def _new_id() -> int:
    return uuid.uuid4().int & ((1 << 64) - 1)


def _now() -> float:
    return time.monotonic()


# --------------------------------------------------------------------------
# raw-socket IO helpers
# --------------------------------------------------------------------------


async def _recv_exact(sock: socket.socket, view: memoryview) -> None:
    """Fill ``view`` from the socket (kernel -> destination, no staging)."""
    loop = asyncio.get_running_loop()
    pos = 0
    total = view.nbytes
    while pos < total:
        n = await loop.sock_recv_into(sock, view[pos:])
        if n == 0:
            raise ConnectionError("bulk peer closed mid-frame")
        pos += n


async def _send_frame(
    sock: socket.socket,
    lock: asyncio.Lock,
    session: int,
    idx: int,
    payload: Optional[memoryview],
) -> None:
    loop = asyncio.get_running_loop()
    async with lock:
        nbytes = payload.nbytes if payload is not None else 0
        await loop.sock_sendall(sock, _FRAME.pack(session, idx, nbytes))
        if payload is not None:
            await loop.sock_sendall(sock, payload)


def _close_sock(sock: Optional[socket.socket]) -> None:
    """Immediate close — ONLY safe when no loop.sock_* op can be pending on
    this socket (dial failures, teardown without a loop)."""
    if sock is not None:
        try:
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            sock.close()
        except OSError:
            pass


async def _graceful_close(sock: socket.socket) -> None:
    """Close a socket that may have in-flight loop.sock_* operations:
    shutdown() wakes them with an error (a bare close would strand them —
    epoll drops closed fds), one tick lets their completion callbacks
    unregister the fd, THEN close. Closing first risks the fd being reused
    by a new socket while the loop still holds the old registration
    (observed as selector FileNotFoundError under concurrent churn)."""
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    await asyncio.sleep(0.05)
    try:
        sock.close()
    except OSError:
        pass


def _family_for(host: str) -> int:
    return socket.AF_INET6 if ":" in host else socket.AF_INET


# --------------------------------------------------------------------------
# server side (storage volume process)
# --------------------------------------------------------------------------


class BulkServer:
    """Per-volume bulk listener: receives put payloads into a session table,
    streams get payloads back over the client's registered connection."""

    def __init__(self) -> None:
        self._listen_sock: Optional[socket.socket] = None
        self._accept_task: Optional[asyncio.Task] = None
        self.port: Optional[int] = None
        self.host: str = "127.0.0.1"
        # (session, idx) -> bytearray of landed payload
        self.incoming: dict[tuple[int, int], bytearray] = {}
        self.aborted: set[int] = set()
        self._session_ts: dict[int, float] = {}  # last activity per session
        self._arrival = asyncio.Condition()
        # client_id -> (sock, write_lock) for outgoing get payloads
        self.client_conns: dict[int, tuple[socket.socket, asyncio.Lock]] = {}
        # session -> (sock, write_lock): exact routing for get sessions
        self.session_conns: dict[int, tuple[socket.socket, asyncio.Lock]] = {}
        self._conn_tasks: set[asyncio.Task] = set()
        self._send_tasks: set[asyncio.Task] = set()

    async def ensure_started(self, bind_host: str) -> tuple[str, int]:
        if self._listen_sock is None:
            import os

            sock = socket.socket(_family_for(bind_host), socket.SOCK_STREAM)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            sock.bind((bind_host, 0))
            sock.listen(64)
            sock.setblocking(False)
            self._listen_sock = sock
            self.port = sock.getsockname()[1]
            # Advertise a REACHABLE address, not the bind address: a volume
            # bound to 0.0.0.0 (cross-host DCN) must hand clients its real
            # hostname/IP (TORCHSTORE_TPU_ADVERTISE_HOST overrides).
            advertise = os.environ.get("TORCHSTORE_TPU_ADVERTISE_HOST")
            if advertise is None:
                advertise = (
                    socket.gethostname()
                    if bind_host in ("0.0.0.0", "::")
                    else bind_host
                )
            self.host = advertise
            self._accept_task = asyncio.ensure_future(self._accept_loop())
            logger.info(
                "bulk server bound %s:%s (advertised as %s)",
                bind_host,
                self.port,
                self.host,
            )
        return self.host, self.port

    async def _accept_loop(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            try:
                conn, _ = await loop.sock_accept(self._listen_sock)
            except asyncio.CancelledError:
                return
            except OSError as exc:
                # Transient accept failures (EMFILE/ECONNABORTED/...): log,
                # back off, keep accepting — the old asyncio.Server did the
                # same; dying here would strand every future client.
                if self._listen_sock is None or self._listen_sock.fileno() < 0:
                    return  # listener closed: normal shutdown
                logger.warning("bulk accept failed (%s); retrying in 1s", exc)
                await asyncio.sleep(1.0)
                continue
            conn.setblocking(False)
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            task = asyncio.ensure_future(self._handle_conn(conn))
            self._conn_tasks.add(task)
            task.add_done_callback(self._conn_tasks.discard)

    async def _handle_conn(self, sock: socket.socket) -> None:
        from torchstore_tpu.runtime.auth import server_authenticate_sock

        if not await server_authenticate_sock(sock):
            await _graceful_close(sock)
            return
        client_id = None
        conn_lock = asyncio.Lock()  # serializes all outgoing writes
        header = bytearray(_FRAME.size)
        header_view = memoryview(header)
        try:
            while True:
                await _recv_exact(sock, header_view)
                session, idx, nbytes = _FRAME.unpack(header)
                if idx == IDX_HELLO:
                    client_id = session
                    self.client_conns[client_id] = (sock, conn_lock)
                    continue
                if idx == IDX_SESSION_OPEN:
                    # Route this session's get payloads back on THIS exact
                    # connection (a client may hold several), then ack so the
                    # client knows routing is in place before it RPCs.
                    self.session_conns[session] = (sock, conn_lock)
                    self._session_ts[session] = _now()
                    await _send_frame(sock, conn_lock, session, IDX_SESSION_OPEN, None)
                    continue
                if idx == IDX_ABORT:
                    async with self._arrival:
                        self.aborted.add(session)
                        self._session_ts[session] = _now()
                        for key in [k for k in self.incoming if k[0] == session]:
                            del self.incoming[key]
                        self._arrival.notify_all()
                    continue
                buf = bytearray(nbytes)
                await _recv_exact(sock, memoryview(buf))
                async with self._arrival:
                    self.incoming[(session, idx)] = buf
                    self._session_ts[session] = _now()
                    self._purge_stale()
                    self._arrival.notify_all()
        except (ConnectionError, OSError):
            pass
        finally:
            if (
                client_id is not None
                and self.client_conns.get(client_id, (None,))[0] is sock
            ):
                self.client_conns.pop(client_id, None)
            for sess in [
                s for s, (c, _) in self.session_conns.items() if c is sock
            ]:
                self.session_conns.pop(sess, None)
            # A send_background task may still be parked on this fd.
            asyncio.ensure_future(_graceful_close(sock))

    def _purge_stale(self) -> None:
        """Drop per-session state older than SESSION_TTL_S (client crashed
        between pushing bytes and the RPC, or aborted a session whose RPC
        never ran). Called under the _arrival lock."""
        now = _now()
        stale = [s for s, ts in self._session_ts.items() if now - ts > SESSION_TTL_S]
        for session in stale:
            del self._session_ts[session]
            self.aborted.discard(session)
            self.session_conns.pop(session, None)
            for key in [k for k in self.incoming if k[0] == session]:
                del self.incoming[key]

    async def collect(self, session: int, indices: list[int]) -> dict[int, bytearray]:
        """Await all payloads of a put session (bytes may arrive before or
        after the RPC)."""
        async with self._arrival:
            try:
                while True:
                    if session in self.aborted:
                        self.aborted.discard(session)
                        raise ConnectionError(
                            f"bulk session {session} aborted by client"
                        )
                    if all((session, i) in self.incoming for i in indices):
                        return {
                            i: self.incoming.pop((session, i)) for i in indices
                        }
                    await self._arrival.wait()
            finally:
                self._session_ts.pop(session, None)

    def send_background(
        self, client_id: int, session: int, payloads: dict[int, np.ndarray]
    ) -> None:
        """Stream get payloads without blocking the RPC response (avoiding
        the write-write deadlock for payloads larger than socket buffers)."""
        conn = self.session_conns.pop(session, None) or self.client_conns.get(
            client_id
        )
        if conn is None:
            raise ConnectionError(
                f"no bulk connection registered for client {client_id}"
            )
        sock, lock = conn

        async def _send() -> None:
            try:
                # Bounded: a peer that stops reading must not pin this task
                # (and its payload memory) forever.
                async with asyncio.timeout(SESSION_TTL_S):
                    for idx, arr in payloads.items():
                        view = memoryview(np.ascontiguousarray(arr)).cast("B")
                        await _send_frame(sock, lock, session, idx, view)
            except TimeoutError:
                # The cancelled sendall may have left a PARTIAL frame on the
                # wire — the connection's framing is unrecoverable; kill it
                # (the reader task then purges its registrations).
                logger.warning(
                    "bulk get send timed out (session=%s); closing connection",
                    session,
                )
                await _graceful_close(sock)
            except Exception:
                logger.exception("bulk get send failed (session=%s)", session)

        task = asyncio.ensure_future(_send())
        self._send_tasks.add(task)
        task.add_done_callback(self._send_tasks.discard)


class BulkServerCache(TransportCache):
    def __init__(self) -> None:
        self.server = BulkServer()

    def clear(self) -> None:
        self.server.incoming.clear()


# --------------------------------------------------------------------------
# client side
# --------------------------------------------------------------------------


class BulkClientConn:
    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.write_lock = asyncio.Lock()
        self.closed = False
        # session -> Queue[(idx, bytearray)] for demuxed get payloads
        self.sessions: dict[int, asyncio.Queue] = {}
        self._reader_task = asyncio.ensure_future(self._demux())

    async def _demux(self) -> None:
        header = bytearray(_FRAME.size)
        header_view = memoryview(header)
        try:
            while True:
                await _recv_exact(self.sock, header_view)
                session, idx, nbytes = _FRAME.unpack(header)
                buf = bytearray(nbytes)
                if nbytes:
                    await _recv_exact(self.sock, memoryview(buf))
                queue = self.sessions.get(session)
                if queue is not None:
                    queue.put_nowait(
                        (idx, buf if idx not in _CONTROL_IDXS else None)
                    )
        except (ConnectionError, OSError):
            for queue in self.sessions.values():
                queue.put_nowait((None, None))
        finally:
            # The recv op just completed/failed, so the fd is unregistered:
            # safe to close here (and only here) in the reader's own task.
            self.closed = True
            try:
                self.sock.close()
            except OSError:
                pass

    def register_session(self, session: int) -> asyncio.Queue:
        queue: asyncio.Queue = asyncio.Queue()
        self.sessions[session] = queue
        return queue

    def release_session(self, session: int) -> None:
        self.sessions.pop(session, None)

    def close_now(self) -> None:
        """Mark closed and wake the reader (which owns the actual close).
        Never closes the fd directly — in-flight loop.sock_* ops on a
        closed-and-reused fd corrupt the selector state."""
        self.closed = True
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass


async def _dial(host: str, port: int, timeout: float) -> socket.socket:
    loop = asyncio.get_running_loop()
    # Resolve first so IPv6-only hosts work (AF from the resolved address).
    infos = await loop.getaddrinfo(host, port, type=socket.SOCK_STREAM)
    family, _, _, _, sockaddr = infos[0]
    sock = socket.socket(family, socket.SOCK_STREAM)
    sock.setblocking(False)
    try:
        await asyncio.wait_for(loop.sock_connect(sock, sockaddr), timeout)
    except BaseException:
        _close_sock(sock)
        raise
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    from torchstore_tpu.runtime.auth import client_authenticate_sock

    try:
        await client_authenticate_sock(sock)
    except BaseException:
        _close_sock(sock)
        raise
    return sock


class BulkClientCache(TransportCache):
    """Promoted, reusable per-volume connections (uniflow's connected-
    transport bucket)."""

    def __init__(self) -> None:
        self.client_id = _new_id()
        self.connections: dict[str, BulkClientConn] = {}

    def get_alive(self, volume_id: str) -> Optional[BulkClientConn]:
        conn = self.connections.get(volume_id)
        if conn is not None and conn.closed:
            del self.connections[volume_id]
            return None
        return conn

    def clear(self) -> None:
        for conn in self.connections.values():
            conn.close_now()
        self.connections.clear()


class BulkTransportBuffer(TransportBuffer):
    requires_handshake = True  # dynamically skipped when a promoted conn exists
    supports_inplace = True
    requires_contiguous_inplace = False
    supports_batch_puts = True
    supports_batch_gets = True

    def __init__(self, config: Optional[StoreConfig] = None):
        self.config = config or default_config()
        self.session = _new_id()
        self.client_id: Optional[int] = None
        # RPC-carried metadata
        self.manifest: dict[int, TensorMeta] = {}
        self.objects: dict[int, Any] = {}
        self.descriptors: dict[int, TensorMeta] = {}
        # client-only live state
        self._conn: Optional[BulkClientConn] = None
        self._promoted = False
        self._volume_id: Optional[str] = None
        self._queue: Optional[asyncio.Queue] = None
        self._sent_put = False
        self._succeeded = False

    def __getstate__(self):
        # config (a plain dataclass) travels with the buffer: the server-side
        # hooks read timeouts from it.
        state = self.__dict__.copy()
        for field in ("_conn", "_queue"):
            state[field] = None
        return state

    # ---- connection management ------------------------------------------

    async def _ensure_conn(self, volume) -> BulkClientConn:
        cache: BulkClientCache = volume.transport_context.get_cache(BulkClientCache)
        self.client_id = cache.client_id
        self._volume_id = volume.volume_id
        conn = cache.get_alive(volume.volume_id)
        if conn is not None:
            self._conn = conn
            self._promoted = True  # already published
            return conn
        # Two-phase: RPC handshake learns the endpoint, then we dial it.
        endpoint = await volume.actor.handshake.call_one(self, [], "bulk_connect")
        host, port = endpoint
        sock = await _dial(host, port, self.config.handshake_timeout)
        conn = BulkClientConn(sock)
        await _send_frame(sock, conn.write_lock, cache.client_id, IDX_HELLO, None)
        self._conn = conn
        self._promoted = False  # handshake-scoped until success
        return conn

    def _post_request_success(self, volume) -> None:
        # Promote-on-success: publish the handshake-scoped connection. Under
        # a concurrent first-request storm only one connection wins the
        # cache slot; the rest stay handshake-scoped and close at drop().
        self._succeeded = True
        if self._conn is not None and not self._promoted:
            cache: BulkClientCache = volume.transport_context.get_cache(
                BulkClientCache
            )
            if cache.get_alive(volume.volume_id) is None:
                cache.connections[volume.volume_id] = self._conn
                self._promoted = True

    # ---- client: put -----------------------------------------------------

    async def put_to_storage_volume(self, volume, requests: list[Request]) -> None:
        await self._ensure_conn(volume)
        return await super().put_to_storage_volume(volume, requests)

    async def get_from_storage_volume(self, volume, requests: list[Request]):
        await self._ensure_conn(volume)
        self._queue = self._conn.register_session(self.session)
        await _send_frame(
            self._conn.sock, self._conn.write_lock, self.session, IDX_SESSION_OPEN, None
        )
        # Await the server's ack: the get RPC rides a different TCP stream,
        # so without this the volume could serve the get before routing for
        # this session exists (misdelivered or dropped payloads).
        ack_idx, _ = await asyncio.wait_for(
            self._queue.get(), timeout=self.config.handshake_timeout
        )
        if ack_idx != IDX_SESSION_OPEN:
            raise ConnectionError(
                f"bulk session-open handshake failed (got frame {ack_idx})"
            )
        try:
            return await super().get_from_storage_volume(volume, requests)
        finally:
            if self._conn is not None:
                self._conn.release_session(self.session)
            self._queue = None

    async def _perform_handshake(self, volume, requests, op) -> None:
        # The real handshake (endpoint exchange + dial) happened in
        # _ensure_conn; nothing further to negotiate per-request.
        return None

    async def _pre_put_hook(self, volume, requests: list[Request]) -> None:
        regs: ArrayRegistrationCache = volume.transport_context.get_cache(
            ArrayRegistrationCache
        )
        for idx, req in enumerate(requests):
            if req.is_object:
                self.objects[idx] = req.objects
                continue
            arr = np.ascontiguousarray(req.tensor_val)
            regs.register(arr)
            self.manifest[idx] = TensorMeta.of(arr)
            await _send_frame(
                self._conn.sock,
                self._conn.write_lock,
                self.session,
                idx,
                memoryview(arr).cast("B"),
            )
        self._sent_put = True

    # ---- server hooks ----------------------------------------------------

    async def recv_handshake(self, ctx: TransportContext, metas, existing, op: str):
        import os

        server: BulkServer = ctx.get_cache(BulkServerCache).server
        bind_host = os.environ.get("TORCHSTORE_TPU_BIND_HOST", "127.0.0.1")
        return await server.ensure_started(bind_host)

    async def handle_put_request(
        self, ctx: TransportContext, metas: list[Request], existing: dict
    ) -> dict[int, Any]:
        server: BulkServer = ctx.get_cache(BulkServerCache).server
        out: dict[int, Any] = dict(self.objects)
        from torchstore_tpu.transport.buffers import transfer_timeout

        # Size-scaled: a multi-GB DCN transfer slower than the flat
        # handshake timeout must not spuriously fail the put.
        total = sum(m.nbytes for m in self.manifest.values())
        payloads = await asyncio.wait_for(
            server.collect(self.session, sorted(self.manifest)),
            timeout=transfer_timeout(self.config.handshake_timeout, total),
        )
        for idx, raw in payloads.items():
            meta = self.manifest[idx]
            arr = np.frombuffer(raw, dtype=meta.np_dtype).reshape(meta.shape)
            prev = existing.get(idx)
            if prev is not None and prev.shape == arr.shape and prev.dtype == arr.dtype:
                fast_copy(prev, arr)  # in-place reuse (invariant 6)
                out[idx] = prev
            else:
                out[idx] = arr
        return out

    def handle_get_request(
        self, ctx: TransportContext, metas: list[Request], entries: list[Any]
    ) -> None:
        server: BulkServer = ctx.get_cache(BulkServerCache).server
        payloads: dict[int, np.ndarray] = {}
        for idx, (meta, entry) in enumerate(zip(metas, entries)):
            if meta.is_object:
                self.objects[idx] = entry
                continue
            arr = np.ascontiguousarray(entry)
            self.descriptors[idx] = TensorMeta.of(arr)
            payloads[idx] = arr
        if payloads:
            server.send_background(self.client_id, self.session, payloads)

    # ---- client: get landing --------------------------------------------

    async def _handle_storage_volume_response(
        self, volume, remote: "BulkTransportBuffer", requests: list[Request]
    ) -> list[Any]:
        from torchstore_tpu.transport.buffers import transfer_timeout

        frame_timeout = transfer_timeout(
            self.config.rpc_timeout,
            sum(m.nbytes for m in remote.descriptors.values()),
        )
        expected = set(remote.descriptors)
        received: dict[int, bytearray] = {}
        while expected - set(received):
            idx, raw = await asyncio.wait_for(
                self._queue.get(), timeout=frame_timeout
            )
            if idx is None:
                raise ConnectionError("bulk connection lost during get")
            received[idx] = raw
        results: list[Any] = []
        for idx, req in enumerate(requests):
            if req.is_object or idx in remote.objects:
                results.append(remote.objects[idx])
                continue
            meta = remote.descriptors[idx]
            arr = np.frombuffer(received[idx], dtype=meta.np_dtype).reshape(meta.shape)
            if req.destination_view is not None:
                fast_copy(req.destination_view, arr)
                results.append(req.destination_view)
            else:
                results.append(arr)
        return results

    # ---- cleanup ---------------------------------------------------------

    def drop(self) -> None:
        conn = self._conn
        if conn is not None:
            need_abort = self._sent_put and not self._succeeded and not conn.closed
            promoted = self._promoted
            session = self.session

            async def _cleanup() -> None:
                if need_abort:
                    # Failed put: abort so the volume discards landed bytes.
                    # Sent under the connection's write lock — a raw write
                    # could interleave into another request's payload stream
                    # on a shared promoted connection.
                    try:
                        await _send_frame(
                            conn.sock, conn.write_lock, session, IDX_ABORT, None
                        )
                    except Exception:
                        pass
                if not promoted:
                    # Handshake-scoped connection never gets published after
                    # a failure — close it (never poison the cache).
                    conn.close_now()

            try:
                asyncio.ensure_future(_cleanup())
            except RuntimeError:  # no running loop (interpreter teardown)
                if not promoted:
                    _close_sock(conn.sock)
        self._conn = None
        self.manifest = {}
        self.objects = {}
        self.descriptors = {}
