"""Transport selection ladder.

Equivalent of /root/reference/torchstore/transport/__init__.py:38-108. The
reference ladder (SHM -> uniflow RDMA/NVLink -> legacy RDMA -> ibverbs ->
Gloo -> RPC) maps to TPU rungs:

    ici   device-to-device via the XLA transfer engine
          (``transport/device_transfer.py``, gated by ``ici_enabled``) —
          the direct weight-sync path rides it for all-jax state dicts;
          volume-backed store entries are host memory, so this rung serves
          the direct path, not the volume ladder (the reference's device
          rung, monarch_rdma.py, likewise serves weight sync)
    shm   same-host POSIX shared memory between client and volume
          (zero-copy snapshot reads)
    bulk  dedicated-socket bulk transfer (host staging within a pod;
          DCN across pods)
    rpc   payload rides the actor-RPC frames (always available)

Selection is per-volume at request time: forced type on the
``StorageVolumeRef``/strategy wins, else the best available rung probes in.
"""

from __future__ import annotations

from enum import Enum
from typing import TYPE_CHECKING, Optional

from torchstore_tpu.config import StoreConfig, default_config
from torchstore_tpu.logging import get_logger
from torchstore_tpu.transport.buffers import TransportBuffer
from torchstore_tpu.transport.rpc import RPCTransportBuffer

if TYPE_CHECKING:
    from torchstore_tpu.strategy import StorageVolumeRef

logger = get_logger("torchstore_tpu.transport")


class TransportType(str, Enum):
    UNSET = "unset"
    RPC = "rpc"
    SHM = "shm"
    BULK = "bulk"


def shm_available(volume: "StorageVolumeRef", config: StoreConfig) -> bool:
    if not config.shm_enabled or not volume.is_same_host():
        return False
    try:
        from torchstore_tpu.transport import shared_memory  # noqa: F401

        return shared_memory.is_available()
    except ImportError:
        return False


def bulk_available(volume: "StorageVolumeRef", config: StoreConfig) -> bool:
    if not config.bulk_tcp_enabled:
        return False
    try:
        from torchstore_tpu.transport import bulk  # noqa: F401

        return bulk.is_available()
    except ImportError:
        return False


_logged_resolution = False


def demotion_ladder(
    volume: "StorageVolumeRef", config: Optional[StoreConfig] = None
) -> list[TransportType]:
    """The rungs a put retry may walk DOWN, best first, STARTING at the
    rung the volume actually uses (``ladder[0]`` is what a plain
    ``create_transport_buffer`` call resolves to): a broken shm handshake
    or reset bulk socket demotes to the next rung instead of surfacing —
    rpc (always last) rides the actor channel itself, so if it fails too
    the volume is gone, not the transport. A volume whose
    ``transport_type`` is pinned never retries ABOVE the pinned rung:
    rungs the operator excluded (e.g. shm known-broken in a deployment
    that forced rpc) stay excluded."""
    config = config or default_config()
    forced = volume.transport_type
    if forced in (None, TransportType.UNSET, TransportType.UNSET.value):
        start = None
    else:
        start = TransportType(forced)
    order = (TransportType.SHM, TransportType.BULK, TransportType.RPC)
    available = {
        TransportType.SHM: shm_available(volume, config),
        TransportType.BULK: bulk_available(volume, config),
        TransportType.RPC: True,
    }
    rungs: list[TransportType] = []
    for rung in order:
        if start is not None and not rungs:
            if rung != start:
                continue  # a rung above the pin was deliberately excluded
            rungs.append(rung)  # the pin itself: what the failure used
            continue
        if available[rung]:
            rungs.append(rung)
    return rungs or [TransportType.RPC]


def create_transport_buffer(
    volume: "StorageVolumeRef",
    config: Optional[StoreConfig] = None,
    force: "Optional[TransportType | str]" = None,
) -> TransportBuffer:
    config = config or default_config()
    forced = force if force is not None else volume.transport_type
    if forced in (None, TransportType.UNSET, TransportType.UNSET.value):
        chosen = _auto_select(volume, config)
    else:
        chosen = TransportType(forced)
    global _logged_resolution
    if not _logged_resolution:
        # One line listing every rung's availability (reference behavior,
        # /root/reference/torchstore/transport/__init__.py:70-81).
        from torchstore_tpu.transport import device_transfer

        logger.info(
            "transport resolution: volume=%s same_host=%s -> %s "
            "[ici(direct)=%s shm=%s bulk=%s rpc=True]",
            volume.volume_id,
            volume.is_same_host(),
            chosen.value,
            config.ici_enabled and device_transfer.is_available(),
            shm_available(volume, config),
            bulk_available(volume, config),
        )
        _logged_resolution = True
    try:
        if chosen == TransportType.SHM:
            from torchstore_tpu.transport.shared_memory import (
                SharedMemoryTransportBuffer,
            )

            return SharedMemoryTransportBuffer(
                config, inproc_copy=volume.is_inproc()
            )
        if chosen == TransportType.BULK:
            from torchstore_tpu.transport.bulk import BulkTransportBuffer

            return BulkTransportBuffer(
                config, inproc_copy=volume.is_inproc()
            )
    except ImportError as exc:
        raise RuntimeError(
            f"transport {chosen.value!r} was forced but is not available "
            f"in this build: {exc}"
        ) from exc
    return RPCTransportBuffer(inproc_copy=volume.is_inproc())


def _auto_select(volume: "StorageVolumeRef", config: StoreConfig) -> TransportType:
    if shm_available(volume, config):
        return TransportType.SHM
    if bulk_available(volume, config):
        return TransportType.BULK
    return TransportType.RPC
