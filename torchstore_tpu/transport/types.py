"""Wire vocabulary: ``TensorSlice`` + ``Request``.

TPU-native equivalent of /root/reference/torchstore/transport/types.py:20-218.
Where the reference derives shard metadata from torch DTensor internals
(``_compute_local_shape_and_global_offset``), we derive it from
``jax.sharding.NamedSharding`` shard indices (see ``torchstore_tpu.sharding``).
This module itself is jax-free: it only describes shards.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field, replace
from typing import Any, Optional

import numpy as np

from torchstore_tpu.utils import Box


@dataclass(frozen=True)
class TensorMeta:
    """Shape + dtype of a tensor payload; travels on meta-only requests so
    servers/transports can allocate destinations without the data."""

    shape: tuple[int, ...]
    dtype: str  # numpy dtype string, e.g. "float32", "bfloat16"

    @classmethod
    def of(cls, arr: np.ndarray) -> "TensorMeta":
        return cls(shape=tuple(int(s) for s in arr.shape), dtype=str(arr.dtype))

    @property
    def np_dtype(self) -> np.dtype:
        return _np_dtype(self.dtype)

    @property
    def nbytes(self) -> int:
        return math.prod(self.shape) * self.np_dtype.itemsize


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        # bfloat16 lives in ml_dtypes (jax's numpy extension types).
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


@dataclass(frozen=True)
class TensorSlice:
    """Metadata describing one shard of a global array.

    ``coordinates``/``mesh_shape`` identify the shard's position in the device
    mesh (used by the controller's full-commit check); ``offsets`` /
    ``local_shape`` / ``global_shape`` place the shard in the global index
    space (used by the resharding planner). Mirrors the reference's
    ``TensorSlice`` (/root/reference/torchstore/transport/types.py:20-55).
    """

    offsets: tuple[int, ...]
    local_shape: tuple[int, ...]
    global_shape: tuple[int, ...]
    coordinates: tuple[int, ...]
    mesh_shape: tuple[int, ...]

    def __post_init__(self) -> None:
        object.__setattr__(self, "offsets", tuple(int(x) for x in self.offsets))
        object.__setattr__(self, "local_shape", tuple(int(x) for x in self.local_shape))
        object.__setattr__(
            self, "global_shape", tuple(int(x) for x in self.global_shape)
        )
        object.__setattr__(self, "coordinates", tuple(int(x) for x in self.coordinates))
        object.__setattr__(self, "mesh_shape", tuple(int(x) for x in self.mesh_shape))
        if len(self.offsets) != len(self.local_shape) or len(self.offsets) != len(
            self.global_shape
        ):
            raise ValueError(f"rank mismatch in {self!r}")

    @property
    def box(self) -> Box:
        return Box(self.offsets, self.local_shape)

    @property
    def nelements(self) -> int:
        return math.prod(self.local_shape) if self.local_shape else 1

    def is_full(self) -> bool:
        return self.local_shape == self.global_shape and all(
            o == 0 for o in self.offsets
        )

    def with_box(self, box: Box) -> "TensorSlice":
        """A slice describing ``box`` of the same global array / mesh position."""
        return replace(self, offsets=box.offsets, local_shape=box.shape)


class OpaqueBlob:
    """Client-side pickled envelope for arbitrary object values.

    Storage volumes and transports carry these as opaque bytes: the user's
    types are pickled/unpickled ONLY in client processes, so a storage
    process never imports the libraries a value drags in (a flax/jax leaf
    unpickled inside a volume would initialize an accelerator backend
    there — on a TPU host that grabs the chip lock and wedges the volume)
    and never executes foreign __reduce__ payloads beyond bytes."""

    __slots__ = ("data",)

    def __init__(self, data: bytes) -> None:
        self.data = data

    @classmethod
    def wrap(cls, obj: Any) -> "OpaqueBlob":
        import pickle

        return cls(pickle.dumps(obj, protocol=5))

    def unwrap(self) -> Any:
        import pickle

        return pickle.loads(self.data)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"OpaqueBlob({len(self.data)} bytes)"


@dataclass
class Request:
    """One logical store operation on one key.

    ``tensor_val`` is a host numpy array (the shard's data on put, or the
    in-place destination on get); ``tensor_slice`` is present for sharded
    values; ``objects`` carries arbitrary picklable payloads. ``meta_only()``
    strips data before metadata-plane RPCs — the controller must never see
    tensor bytes (two-plane invariant, SURVEY §2.2.1; reference
    /root/reference/torchstore/transport/types.py:88-218).
    """

    key: str
    tensor_val: Optional[np.ndarray] = None
    tensor_slice: Optional[TensorSlice] = None
    objects: Any = None
    is_object: bool = False
    tensor_meta: Optional[TensorMeta] = None
    # Attached by the client when an in-place destination view exists for this
    # (sub-)request; never serialized to the server (stripped by meta_only).
    destination_view: Optional[np.ndarray] = field(default=None, repr=False)

    @classmethod
    def from_tensor(cls, key: str, tensor: np.ndarray) -> "Request":
        return cls(key=key, tensor_val=np.asarray(tensor))

    @classmethod
    def from_objects(cls, key: str, objects: Any) -> "Request":
        return cls(key=key, objects=objects, is_object=True)

    @classmethod
    def from_tensor_slice(
        cls, key: str, tensor_slice: TensorSlice, tensor: Optional[np.ndarray] = None
    ) -> "Request":
        if tensor is not None:
            tensor = np.asarray(tensor)
            if tuple(tensor.shape) != tensor_slice.local_shape:
                raise ValueError(
                    f"shard data shape {tensor.shape} != slice local_shape "
                    f"{tensor_slice.local_shape} for key {key!r}"
                )
        return cls(key=key, tensor_val=tensor, tensor_slice=tensor_slice)

    @classmethod
    def meta_request(cls, key: str) -> "Request":
        return cls(key=key)

    def meta_only(self) -> "Request":
        """Copy carrying metadata only (never tensor bytes or object
        payloads). Memoized: one request's meta rides the handshake, the
        put, and the notify — a many-key batch would otherwise rebuild
        thousands of identical copies (and re-stringify dtypes) per
        iteration. The cached copy is immutable by convention: every
        consumer reads it only."""
        cached = self.__dict__.get("_meta_only")
        if cached is not None:
            return cached
        meta = self.tensor_meta
        if meta is None and self.tensor_val is not None:
            meta = TensorMeta.of(self.tensor_val)
        mo = Request(
            key=self.key,
            tensor_val=None,
            tensor_slice=self.tensor_slice,
            objects=None,
            is_object=self.is_object,
            tensor_meta=meta,
        )
        self.__dict__["_meta_only"] = mo
        return mo

    @property
    def nbytes(self) -> int:
        return int(self.tensor_val.nbytes) if self.tensor_val is not None else 0

    def __getstate__(self):
        state = self.__dict__.copy()
        state["destination_view"] = None
        state.pop("_meta_only", None)
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
