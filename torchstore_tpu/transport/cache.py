"""Registration caches for byte transports.

Equivalent of /root/reference/torchstore/transport/torchcomms/cache.py:150-186
(``RdmaMemoryCache``): buffers registered once per (data_ptr, nbytes) and
auto-evicted when the owning array dies (weakref). In pure-Python mode
registration just pins a memoryview; the native backend hooks here to pin
pages / pre-register with the transfer engine.
"""

from __future__ import annotations

import weakref
from typing import Optional

import numpy as np

from torchstore_tpu.observability import metrics as obs_metrics
from torchstore_tpu.transport.buffers import TransportCache

_REGISTRATIONS = obs_metrics.counter(
    "ts_buffer_registrations_total",
    "Buffer registrations by outcome (new / cache_hit)",
)
_REGISTERED_LIVE = obs_metrics.gauge(
    "ts_buffer_registrations_live", "Currently registered buffers"
)


class ArrayRegistration:
    """Bookkeeping record for a registered buffer. Holds NO strong reference
    to the array (a registration must not extend the buffer's lifetime —
    eviction is the point); the native backend pins pages at the kernel
    level here instead."""

    def __init__(self, array: np.ndarray):
        self.ptr = array.__array_interface__["data"][0]
        self.nbytes = array.nbytes
        self.native_handle: Optional[object] = None

    def release(self) -> None:
        self.native_handle = None


class ArrayRegistrationCache(TransportCache):
    """(data_ptr, nbytes) -> registration. Evicted when the array's memory
    owner is garbage collected (weakref.finalize) for weakref-able owners
    (ndarray subclasses, jax buffers); plain ndarrays fall back to FIFO
    capacity eviction so the cache stays bounded."""

    def __init__(self, maxsize: int = 1024) -> None:
        self.maxsize = maxsize
        self._entries: dict[tuple[int, int], ArrayRegistration] = {}
        self._finalizers: dict[tuple[int, int], weakref.finalize] = {}

    def __len__(self) -> int:
        return len(self._entries)

    def register(self, array: np.ndarray) -> ArrayRegistration:
        key = (array.__array_interface__["data"][0], array.nbytes)
        entry = self._entries.get(key)
        if entry is not None:
            _REGISTRATIONS.inc(outcome="cache_hit")
            return entry
        entry = ArrayRegistration(array)
        while len(self._entries) >= self.maxsize:
            self._evict(next(iter(self._entries)))
        self._entries[key] = entry
        _REGISTRATIONS.inc(outcome="new")
        _REGISTERED_LIVE.set(len(self._entries))
        owner = array.base if array.base is not None else array
        try:
            self._finalizers[key] = weakref.finalize(owner, self._evict, key)
        except TypeError:
            pass  # plain ndarrays aren't weakref-able; FIFO bound applies
        return entry

    def lookup(self, array: np.ndarray) -> Optional[ArrayRegistration]:
        return self._entries.get(
            (array.__array_interface__["data"][0], array.nbytes)
        )

    def _evict(self, key: tuple[int, int]) -> None:
        entry = self._entries.pop(key, None)
        if entry is not None:
            entry.release()
            _REGISTERED_LIVE.set(len(self._entries))
        fin = self._finalizers.pop(key, None)
        if fin is not None:
            fin.detach()

    def clear(self) -> None:
        for key in list(self._entries):
            self._evict(key)
