"""Per-volume spill tier: demote cold versions from memory/tmpfs to disk.

One ``SpillTier`` lives inside each ``StorageVolume`` process (built at
init when ``TORCHSTORE_TPU_TIER_ENABLED`` is set). It owns:

- a crash-safe disk store (``storage_utils.file_store.FileBackedStore``
  under ``TORCHSTORE_TPU_TIER_DIR/<volume_id>`` — every fresh persist is
  write-temp → fsync → rename, so a volume killed mid-spill never leaves a
  torn file the fault-in path would trust);
- the watermark policy: when the volume's resident bytes exceed
  ``TIER_HIGH_PCT`` of the pool budget, whole version groups
  (``{channel}/v{n}``) are demoted coldest-first (LRU by access) until
  resident bytes drop under ``TIER_LOW_PCT`` — pinned (leased) groups are
  exempt, as are keys outside any version group (pointers, ad-hoc keys);
- the spilled-set bookkeeping the volume's fault-in path consults (one
  dict lookup on the warm path, nothing else).

The spill/fault-in MECHANICS — landing-stamp brackets, residency deltas,
faultpoints — stay in ``storage_volume.py`` next to the other landings;
this module is the policy + disk half.
"""

from __future__ import annotations

import os
import tempfile
import time
from typing import Any, Iterable, Optional

import numpy as np

from torchstore_tpu.logging import get_logger
from torchstore_tpu.observability import ledger as obs_ledger
from torchstore_tpu.observability import metrics as obs_metrics
from torchstore_tpu.observability import recorder as obs_recorder
from torchstore_tpu.tiering import version_group
from torchstore_tpu.transport.types import Request, TensorMeta

logger = get_logger("torchstore_tpu.tiering.spill")

# Disk-tier ledger cells ride the ledger's DISK transport label — the SAME
# constant traffic_matrix folds on, so spill I/O can never silently drift
# into "unattributed" through a one-sided rename.
DISK_TRANSPORT = obs_ledger.DISK

_SPILLS = obs_metrics.counter(
    "ts_spills_total", "Entries demoted from the memory tier to disk"
)
_FAULT_INS = obs_metrics.counter(
    "ts_fault_ins_total",
    "Spilled entries faulted back into the memory tier, by reason",
)
_TIER_RESIDENT = obs_metrics.gauge(
    "ts_tier_resident_bytes",
    "Bytes resident in this volume's memory (tmpfs) tier",
)
_TIER_SPILLED = obs_metrics.gauge(
    "ts_tier_spilled_bytes",
    "Bytes demoted to this volume's disk spill tier",
)


def enabled() -> bool:
    return os.environ.get(
        "TORCHSTORE_TPU_TIER_ENABLED", "0"
    ).strip().lower() not in ("0", "false", "no", "off", "")


def _default_budget() -> int:
    from torchstore_tpu.config import default_config

    return int(default_config().shm_pool_max_bytes)


class SpillTier:
    """Policy + disk half of one volume's spill tier (see module doc)."""

    def __init__(
        self,
        volume_id: str,
        root: Optional[str] = None,
        budget_bytes: Optional[int] = None,
        high_pct: Optional[float] = None,
        low_pct: Optional[float] = None,
    ) -> None:
        from torchstore_tpu.storage_utils.file_store import FileBackedStore

        if root is None:
            root = os.environ.get("TORCHSTORE_TPU_TIER_DIR") or os.path.join(
                tempfile.gettempdir(), "torchstore_tpu_tier"
            )
        if budget_bytes is None:
            env = os.environ.get("TORCHSTORE_TPU_TIER_BUDGET_BYTES")
            budget_bytes = int(env) if env else _default_budget()
        if high_pct is None:
            high_pct = float(
                os.environ.get("TORCHSTORE_TPU_TIER_HIGH_PCT", "0.85")
            )
        if low_pct is None:
            low_pct = float(
                os.environ.get("TORCHSTORE_TPU_TIER_LOW_PCT", "0.65")
            )
        if not (0.0 < low_pct <= high_pct):
            raise ValueError(
                f"tier watermarks must satisfy 0 < low <= high "
                f"(got low={low_pct}, high={high_pct})"
            )
        self.volume_id = str(volume_id)
        self.budget_bytes = max(1, int(budget_bytes))
        self.high_pct = high_pct
        self.low_pct = low_pct
        self.disk = FileBackedStore(os.path.join(root, self.volume_id))
        # key -> spilled bytes; the ONE structure the warm path consults
        # (``key in tier.spilled`` — a dict membership test). Seeded from
        # whatever the disk store already holds: a restarted volume pointed
        # at the same tier dir resumes serving its spilled set.
        self.spilled: dict[str, int] = {
            key: self._disk_entry_nbytes(entry)
            for key, entry in self.disk.kv.items()
        }
        # Version-group LRU clock: group -> last access (monotonic).
        self.access: dict[str, float] = {}
        # Fault-ins since the last sweep drained them (tier-state feedback
        # to the controller's index).
        self._faulted: list[str] = []
        self.publish_gauges(resident_bytes=0)

    # ---- accounting ------------------------------------------------------

    @staticmethod
    def _disk_entry_nbytes(entry: dict) -> int:
        if entry.get("type") == "tensor":
            return int(getattr(entry.get("tensor"), "nbytes", 0))
        if entry.get("type") == "sharded":
            return sum(
                int(getattr(s.get("tensor"), "nbytes", 0))
                for s in entry.get("shards", {}).values()
            )
        return 0

    @property
    def spilled_bytes(self) -> int:
        return sum(self.spilled.values())

    @property
    def high_bytes(self) -> int:
        return int(self.budget_bytes * self.high_pct)

    @property
    def low_bytes(self) -> int:
        return int(self.budget_bytes * self.low_pct)

    def publish_gauges(self, resident_bytes: int) -> None:
        _TIER_RESIDENT.set(resident_bytes, volume=self.volume_id)
        _TIER_SPILLED.set(self.spilled_bytes, volume=self.volume_id)

    def touch(self, keys: Iterable[str]) -> None:
        """Refresh the LRU clock for every version group these keys live
        in (called per put/get batch — only when tiering is enabled).

        The clock sees VOLUME-SIDE access only: zero-RPC one-sided reads
        never reach this process, so a version read exclusively warm can
        look cold here. That is by contract, not accident — a cohort that
        wants its version exempt from demotion holds a retention LEASE
        (the explicit, attributable pin); recency is only the tiebreak
        among unpinned versions, and a mistaken demotion costs one
        fault-in, never correctness."""
        now = time.monotonic()
        for key in keys:
            group = version_group(key)
            if group is not None:
                self.access[f"{group[0]}/v{group[1]}"] = now

    def drain_faulted(self) -> list[str]:
        out, self._faulted = self._faulted, []
        return out

    # ---- policy ----------------------------------------------------------

    def cold_groups(
        self, kv: dict[str, dict], pins: Iterable[str]
    ) -> list[tuple[str, list[str]]]:
        """Version groups eligible for demotion, coldest-first:
        ``[(group, [keys...]), ...]``. Pinned (leased) groups and keys
        outside any version group never appear."""
        pinned = set(pins or ())
        groups: dict[str, list[str]] = {}
        for key in kv:
            vg = version_group(key)
            if vg is None:
                continue
            group = f"{vg[0]}/v{vg[1]}"
            if group in pinned:
                continue
            groups.setdefault(group, []).append(key)
        return sorted(
            groups.items(), key=lambda kv_: self.access.get(kv_[0], 0.0)
        )

    # ---- disk half -------------------------------------------------------

    @staticmethod
    def entry_requests(
        key: str, entry: dict
    ) -> tuple[list[Request], dict[int, Any]]:
        """(metas, values) in the StorageImpl.store shape for one in-memory
        entry — the same dict layout FileBackedStore persists and recovers."""
        if entry["type"] == "object":
            return [Request(key=key, is_object=True)], {0: entry["obj"]}
        if entry["type"] == "tensor":
            arr = np.ascontiguousarray(entry["tensor"])
            return (
                [Request(key=key, tensor_meta=TensorMeta.of(arr))],
                {0: arr},
            )
        metas: list[Request] = []
        values: dict[int, Any] = {}
        for idx, shard in enumerate(entry["shards"].values()):
            arr = np.ascontiguousarray(shard["tensor"])
            metas.append(
                Request(
                    key=key,
                    tensor_slice=shard["slice"],
                    tensor_meta=TensorMeta.of(arr),
                )
            )
            values[idx] = arr
        return metas, values

    def spill(self, key: str, entry: dict) -> int:
        """Persist one in-memory entry to the disk tier (crash-safe);
        returns the spilled byte count. The caller drops the memory copy
        (under its landing bracket) only AFTER this returns — a failure
        here leaves the entry fully resident and served as before."""
        metas, values = self.entry_requests(key, entry)
        self.disk.store(metas, values)
        nbytes = self._disk_entry_nbytes(self.disk.kv.get(key, {}))
        self.spilled[key] = nbytes
        _SPILLS.inc(volume=self.volume_id)
        obs_ledger.record(
            DISK_TRANSPORT,
            obs_ledger.EGRESS,
            nbytes,
            volume=self.volume_id,
            items=[(key, nbytes)],
        )
        obs_recorder.record(
            "tier", "spill", key=key, nbytes=nbytes, volume=self.volume_id
        )
        return nbytes

    def load(self, key: str) -> tuple[list[Request], dict[int, Any]]:
        """(metas, memmap values) for a spilled entry, ready to re-land
        into the memory tier. Raises KeyError when not spilled (e.g. a
        concurrent fault-in already promoted it)."""
        entry = self.disk.kv[key]
        return self.entry_requests(key, entry)

    def faulted_in(self, key: str, reason: str) -> None:
        """Bookkeeping after the volume re-landed ``key``: drop the disk
        copy and record the promotion."""
        nbytes = self.spilled.pop(key, 0)
        self.disk.delete(key)
        self._faulted.append(key)
        _FAULT_INS.inc(reason=reason)
        obs_ledger.record(
            DISK_TRANSPORT,
            obs_ledger.INGRESS,
            nbytes,
            volume=self.volume_id,
            items=[(key, nbytes)],
        )
        obs_recorder.record(
            "tier",
            "fault_in",
            key=key,
            nbytes=nbytes,
            volume=self.volume_id,
            reason=reason,
        )

    def discard(self, key: str) -> bool:
        """Drop a stale disk copy (the key was overwritten or deleted in
        the memory tier); idempotent."""
        existed = self.spilled.pop(key, None) is not None
        if existed:
            self.disk.delete(key)
        return existed

    def manifest(self) -> list[dict]:
        """Spilled entries' meta-only manifest (controller index rebuilds
        must see the disk tier too — spilled bytes are the only copy)."""
        return self.disk.manifest()

    def reset(self) -> None:
        self.spilled.clear()
        self.access.clear()
        self._faulted.clear()
        self.disk.reset()
        self.publish_gauges(resident_bytes=0)
