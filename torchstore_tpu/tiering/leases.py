"""Cohort retention leases: TTL'd pins on (channel, version) pairs.

The registry lives in the CONTROLLER process (one per store, like streams
and health state) and is the single authority on which versions are
retained: the publisher's GC asks it before deleting, the controller's
``notify_delete_batch`` enforces it even against deletes the publisher
never saw, and the per-volume spill writers receive the pinned groups each
sweep so a leased-hot version is never demoted off the zero-copy path.

Leases are TTL'd (a crashed cohort cannot pin capacity forever) and
per-cohort-id: one cohort renewing keeps its pin alive; the same
(channel, version) pinned by several cohorts stays retained until the LAST
lease expires or is released. Expiry is lazy — every registry operation
expires first — so the guarantee holds even in fleets that never run the
background tier sweeper.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass
from typing import Optional

from torchstore_tpu.logging import get_logger
from torchstore_tpu.observability import metrics as obs_metrics
from torchstore_tpu.observability import recorder as obs_recorder

logger = get_logger("torchstore_tpu.tiering.leases")

_ACTIVE = obs_metrics.gauge(
    "ts_leases_active", "Live cohort retention leases in this controller"
)


def default_ttl_s() -> float:
    return float(os.environ.get("TORCHSTORE_TPU_LEASE_TTL_S", "30.0"))


@dataclass
class Lease:
    """One cohort's pin on one (channel, version)."""

    lease_id: str
    cohort: str
    channel: str
    version: int
    ttl_s: float
    expires_at: float  # monotonic
    created_ts: float  # wall clock, for the catalog

    def describe(self, now: Optional[float] = None) -> dict:
        now = time.monotonic() if now is None else now
        return {
            "lease_id": self.lease_id,
            "cohort": self.cohort,
            "channel": self.channel,
            "version": self.version,
            "ttl_s": self.ttl_s,
            "expires_in_s": round(max(0.0, self.expires_at - now), 3),
            "created_ts": self.created_ts,
        }


class LeaseRegistry:
    """Bounded, TTL'd lease table. Not thread-safe by design: it lives on
    the controller's event loop, where endpoint bodies interleave only at
    awaits and every method here is synchronous."""

    MAX_LEASES = 4096

    def __init__(self, ttl_s: Optional[float] = None) -> None:
        self.default_ttl_s = default_ttl_s() if ttl_s is None else float(ttl_s)
        self._leases: dict[str, Lease] = {}
        self._counter = 0

    def __len__(self) -> int:
        return len(self._leases)

    def _publish(self) -> None:
        _ACTIVE.set(len(self._leases))

    # ---- lifecycle -------------------------------------------------------

    def acquire(
        self,
        cohort: str,
        channel: str,
        version: int,
        ttl_s: Optional[float] = None,
    ) -> dict:
        """Pin (channel, version) for ``cohort``; returns the lease
        description (carry ``lease_id`` to renew/release). Re-acquiring the
        same pin from the same cohort RENEWS the existing lease instead of
        stacking a second one (crash-restart cohorts stay at one lease);
        the renewal only EXTENDS — TTL and expiry take the max of old and
        new, and the reply carries ``renewed: True`` so a read-scoped
        acquire knows not to release a pin it merely refreshed."""
        if not cohort or not channel:
            raise ValueError("lease_acquire requires cohort and channel")
        self.expire()
        ttl = self.default_ttl_s if ttl_s is None else float(ttl_s)
        if ttl <= 0:
            raise ValueError("lease ttl_s must be positive")
        now = time.monotonic()
        for lease in self._leases.values():
            if (
                lease.cohort == cohort
                and lease.channel == channel
                and lease.version == int(version)
            ):
                lease.ttl_s = max(lease.ttl_s, ttl)
                lease.expires_at = max(lease.expires_at, now + ttl)
                return {**lease.describe(now), "renewed": True}
        if len(self._leases) >= self.MAX_LEASES:
            raise RuntimeError(
                f"lease table full ({self.MAX_LEASES}); release or let "
                "TTLs expire before pinning more versions"
            )
        self._counter += 1
        lease = Lease(
            lease_id=f"{cohort}:{channel}:v{int(version)}:{self._counter}",
            cohort=cohort,
            channel=channel,
            version=int(version),
            ttl_s=ttl,
            expires_at=now + ttl,
            created_ts=time.time(),
        )
        self._leases[lease.lease_id] = lease
        self._publish()
        obs_recorder.record(
            "tier",
            "lease_acquire",
            cohort=cohort,
            channel=channel,
            version=int(version),
            ttl_s=ttl,
        )
        return {**lease.describe(now), "renewed": False}

    def renew(self, lease_id: str, ttl_s: Optional[float] = None) -> dict:
        """Extend a live lease; KeyError when unknown or already expired —
        the caller must re-acquire (and re-validate the version still
        exists) rather than trust a pin that lapsed."""
        self.expire()
        lease = self._leases.get(lease_id)
        if lease is None:
            raise KeyError(
                f"lease {lease_id!r} is unknown or expired; re-acquire"
            )
        ttl = lease.ttl_s if ttl_s is None else float(ttl_s)
        if ttl <= 0:
            raise ValueError("lease ttl_s must be positive")
        lease.ttl_s = ttl
        lease.expires_at = time.monotonic() + ttl
        return lease.describe()

    def release(self, lease_id: str) -> bool:
        """Drop one lease; idempotent (False when already gone)."""
        lease = self._leases.pop(lease_id, None)
        self._publish()
        if lease is not None:
            obs_recorder.record(
                "tier",
                "lease_release",
                cohort=lease.cohort,
                channel=lease.channel,
                version=lease.version,
            )
        return lease is not None

    def expire(self, now: Optional[float] = None) -> list[Lease]:
        """Drop every lease past its TTL; returns them (flight events)."""
        now = time.monotonic() if now is None else now
        dead = [
            lid for lid, lease in self._leases.items() if lease.expires_at <= now
        ]
        dropped = [self._leases.pop(lid) for lid in dead]
        if dropped:
            self._publish()
            for lease in dropped:
                obs_recorder.record(
                    "tier",
                    "lease_expired",
                    cohort=lease.cohort,
                    channel=lease.channel,
                    version=lease.version,
                )
                logger.warning(
                    "lease %s expired (cohort %s no longer pins %s/v%d)",
                    lease.lease_id,
                    lease.cohort,
                    lease.channel,
                    lease.version,
                )
        return dropped

    # ---- queries ---------------------------------------------------------

    def pins(
        self, channel: Optional[str] = None
    ) -> dict[str, dict[int, list[str]]]:
        """{channel: {version: [cohort, ...]}} over live leases."""
        self.expire()
        out: dict[str, dict[int, list[str]]] = {}
        for lease in self._leases.values():
            if channel is not None and lease.channel != channel:
                continue
            out.setdefault(lease.channel, {}).setdefault(
                lease.version, []
            ).append(lease.cohort)
        return out

    def pinned_groups(self) -> set[str]:
        """{"channel/vN"} prefixes of every live pin — what the spill
        writers receive each sweep."""
        from torchstore_tpu.tiering import group_key

        self.expire()
        return {
            group_key(lease.channel, lease.version)
            for lease in self._leases.values()
        }

    def is_pinned(self, channel: str, version: int) -> bool:
        self.expire()
        return any(
            lease.channel == channel and lease.version == int(version)
            for lease in self._leases.values()
        )

    def blocks_delete(self, key: str) -> bool:
        """Whether deleting ``key`` would reap a leased version's data —
        the controller's notify_delete_batch guard."""
        from torchstore_tpu.tiering import version_group

        group = version_group(key)
        if group is None:
            return False
        return self.is_pinned(*group)

    def describe(self) -> list[dict]:
        self.expire()
        now = time.monotonic()
        return [lease.describe(now) for lease in self._leases.values()]

    def clear(self) -> None:
        self._leases.clear()
        self._publish()
