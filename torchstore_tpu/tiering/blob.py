"""Blob cold tier: an object-storage-style backend below the disk tier.

One ``BlobTier`` lives inside each ``StorageVolume`` process (built at
init when ``TORCHSTORE_TPU_BLOB_ENABLED`` is set). It is the third rung
of the tiering ladder — memory (tmpfs) → disk (``tiering/spill.py``) →
blob — and owns:

- a per-volume view over the shared :class:`BlobStore`: an emulated
  object store (put/get/list/delete over a flat namespace) rooted at
  ``TORCHSTORE_TPU_BLOB_DIR``, with the latency + throughput envelope of
  a real bucket injected per op (``TORCHSTORE_TPU_BLOB_LATENCY_MS``,
  ``TORCHSTORE_TPU_BLOB_RATE_MBPS``) so benches measure cold-tier
  behavior, not local-disk behavior;
- the archived-set bookkeeping the volume's serve path consults (one
  dict membership test on the warm path, exactly like the spill tier's
  ``spilled``), seeded from the store at init so a restarted volume
  pointed at the same blob root resumes serving its archived set;
- the durable fleet **manifest** (:func:`write_fleet_manifest`): a
  committed index snapshot + blob object map written crash-safe
  (write-temp → fsync → rename, the FileBackedStore protocol), which is
  what makes scale-to-zero real — kill every volume, cold-start a fresh
  fleet, and ``ts.blob_restore()`` replays every committed generation
  out of the blob tier with zero loss.

Demotion disk→blob is decision-driven (the autoscale engine's
``blob_demote`` action → ``StorageVolume.blob_sweep``), not watermark-
driven: blob round trips are expensive enough that each one should be
auditable. Fault-in rides the existing get-RPC bracket
(``StorageVolume._blob_fault_in``), same as the disk tier.

Every BlobStore op crosses the ``blob.io`` faultpoint, so chaos
schedules can fail/delay/kill mid-archive and mid-restore. Blob bytes
ride the ledger's DISK transport label (local I/O, never wire traffic —
same rule as the spill tier).
"""

from __future__ import annotations

import base64
import json
import os
import pickle
import tempfile
import time
from typing import Any, Iterable, Optional

from torchstore_tpu import faults
from torchstore_tpu.logging import get_logger
from torchstore_tpu.observability import ledger as obs_ledger
from torchstore_tpu.observability import metrics as obs_metrics
from torchstore_tpu.observability import recorder as obs_recorder
from torchstore_tpu.transport.types import Request

logger = get_logger("torchstore_tpu.tiering.blob")

_BLOB_OPS = obs_metrics.counter(
    "ts_blob_ops_total", "Blob-store operations, by op (put/get/list/delete)"
)
_BLOB_DEMOTIONS = obs_metrics.counter(
    "ts_blob_demotions_total", "Entries demoted from the disk tier to blob"
)
_BLOB_RESTORES = obs_metrics.counter(
    "ts_blob_restores_total",
    "Blob-archived entries restored to the memory tier, by reason",
)
_BLOB_BYTES = obs_metrics.gauge(
    "ts_blob_bytes", "Bytes archived in this volume's blob tier"
)
_BLOB_KEYS = obs_metrics.gauge(
    "ts_blob_keys", "Entries archived in this volume's blob tier"
)

# The fleet manifest's object name — flat-namespace, outside any volume
# prefix so a fresh fleet (new volume ids) finds it.
MANIFEST_OBJECT = "fleet/MANIFEST"
_VOLUME_PREFIX = "vols/"


def enabled() -> bool:
    return os.environ.get(
        "TORCHSTORE_TPU_BLOB_ENABLED", "0"
    ).strip().lower() not in ("0", "false", "no", "off", "")


def blob_root() -> str:
    return os.environ.get("TORCHSTORE_TPU_BLOB_DIR") or os.path.join(
        tempfile.gettempdir(), "torchstore_tpu_blob"
    )


class BlobStore:
    """Emulated object store: a flat object namespace over one local
    directory. Object names map to urlsafe-b64 filenames (names may hold
    ``/`` freely, as bucket keys do); every put is crash-safe
    (write-temp → fsync → rename) so a process killed mid-put never
    leaves a torn object a restore would trust. Each op fires the
    ``blob.io`` faultpoint and pays the configured latency/rate envelope."""

    def __init__(
        self,
        root: Optional[str] = None,
        latency_ms: Optional[float] = None,
        rate_mbps: Optional[float] = None,
    ) -> None:
        self.root = root or blob_root()
        if latency_ms is None:
            latency_ms = float(
                os.environ.get("TORCHSTORE_TPU_BLOB_LATENCY_MS", 0) or 0
            )
        if rate_mbps is None:
            rate_mbps = float(
                os.environ.get("TORCHSTORE_TPU_BLOB_RATE_MBPS", 0) or 0
            )
        self.latency_s = max(0.0, latency_ms) / 1e3
        self.rate_bps = max(0.0, rate_mbps) * 1e6
        os.makedirs(self.root, exist_ok=True)

    def _path(self, name: str) -> str:
        return os.path.join(
            self.root, base64.urlsafe_b64encode(name.encode()).decode()
        )

    def _op(self, op: str, nbytes: int = 0) -> None:
        """The emulated service envelope, crossed by EVERY op: the chaos
        faultpoint first (a die here is a volume lost mid-blob-I/O), then
        the per-request latency, then the throughput-proportional stall."""
        faults.fire("blob.io")
        _BLOB_OPS.inc(op=op)
        stall = self.latency_s
        if self.rate_bps > 0 and nbytes:
            stall += nbytes / self.rate_bps
        if stall > 0:
            time.sleep(stall)

    def put(self, name: str, data: bytes) -> int:
        self._op("put", len(data))
        path = self._path(name)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return len(data)

    def get(self, name: str) -> bytes:
        # The service envelope fires BEFORE the read (head() supplies the
        # byte count for the rate stall) so a chaos schedule arming
        # ``blob.io`` can fail a get before any bytes move, and the
        # emulated latency models the request, not a post-I/O penalty.
        size, _ = self.head(name)
        self._op("get", size)
        try:
            with open(self._path(name), "rb") as f:
                return f.read()
        except FileNotFoundError:
            raise KeyError(name) from None

    def head(self, name: str) -> tuple[int, float]:
        """(size, mtime) without transferring the payload (no rate stall)."""
        try:
            st = os.stat(self._path(name))
        except FileNotFoundError:
            raise KeyError(name) from None
        return int(st.st_size), float(st.st_mtime)

    def list(self, prefix: str = "") -> list[str]:
        self._op("list")
        out = []
        for fname in os.listdir(self.root):
            if fname.endswith(".tmp") or ".tmp." in fname:
                continue  # torn put from a killed writer: never an object
            try:
                name = base64.urlsafe_b64decode(fname.encode()).decode()
            except Exception:  # noqa: BLE001 - foreign file in the root
                continue
            if name.startswith(prefix):
                out.append(name)
        return sorted(out)

    def delete(self, name: str) -> bool:
        self._op("delete")
        try:
            os.remove(self._path(name))
            return True
        except FileNotFoundError:
            return False


class BlobTier:
    """Per-volume view of the blob tier (see module doc)."""

    def __init__(self, volume_id: str, store: Optional[BlobStore] = None):
        self.volume_id = str(volume_id)
        self.store = store or BlobStore()
        self.prefix = f"{_VOLUME_PREFIX}{self.volume_id}/"
        # key -> archived bytes; the ONE structure the serve path consults.
        # Seeded from the store: a restarted volume resumes its archive.
        self.archived: dict[str, int] = {}
        for name in self.store.list(self.prefix):
            key = name[len(self.prefix):]
            try:
                size, _ = self.store.head(name)
            except KeyError:
                continue  # deleted between list and head
            self.archived[key] = size
        # Keys whose blob objects are CHECKPOINT copies the fleet manifest
        # references (blob_archive pins them): restored() keeps those
        # objects, because a fault-in promotion must never destroy the
        # durability copy a later cold restore replays. Seeded from the
        # last committed manifest so a restarted volume keeps honoring it.
        self.pinned: set[str] = set()
        try:
            doc = read_fleet_manifest(self.store)
        except Exception:  # noqa: BLE001 - a broken manifest must not
            # fail volume init; the next checkpoint rewrites it
            doc = None
        if doc:
            for info in (doc.get("keys") or {}).values():
                name = str(info.get("object", ""))
                if name.startswith(self.prefix):
                    self.pinned.add(name[len(self.prefix):])
        self.publish_gauges()

    def _object(self, key: str) -> str:
        return self.prefix + key

    def object_name(self, key: str) -> str:
        """The blob object name for ``key`` (what the fleet manifest's
        object map records)."""
        return self._object(key)

    @property
    def archived_bytes(self) -> int:
        return sum(self.archived.values())

    def publish_gauges(self) -> None:
        _BLOB_BYTES.set(self.archived_bytes, volume=self.volume_id)
        _BLOB_KEYS.set(len(self.archived), volume=self.volume_id)

    # ---- payload protocol ------------------------------------------------

    @staticmethod
    def encode_entry(metas: list[Request], values: dict[int, Any]) -> bytes:
        """One self-contained blob object per entry: the (metas, values)
        pair in StorageImpl.store shape — exactly what a restore re-lands,
        with no side lookup needed."""
        return pickle.dumps((metas, values), protocol=pickle.HIGHEST_PROTOCOL)

    @staticmethod
    def decode_entry(data: bytes) -> tuple[list[Request], dict[int, Any]]:
        metas, values = pickle.loads(data)
        return metas, values

    # ---- archive / restore ----------------------------------------------

    def archive(
        self, key: str, metas: list[Request], values: dict[int, Any]
    ) -> int:
        """Persist one entry to the blob store (crash-safe); returns the
        object byte count. The caller decides whether this is a demotion
        (drop the disk copy afterwards) or a checkpoint copy (keep it)."""
        nbytes = self.store.put(self._object(key), self.encode_entry(metas, values))
        self.archived[key] = nbytes
        obs_ledger.record(
            obs_ledger.DISK,
            obs_ledger.EGRESS,
            nbytes,
            volume=self.volume_id,
            items=[(key, nbytes)],
        )
        return nbytes

    def pin(self, keys: Iterable[str]) -> None:
        """Mark keys' blob objects as checkpoint copies (the fleet
        manifest references them): ``restored()`` keeps a pinned object
        on promotion — only an overwrite/delete above this tier
        (``discard``) may drop it."""
        self.pinned.update(keys)

    def demoted(self, keys: list, nbytes: int) -> None:
        """Record a disk→blob demotion batch (the volume's ``blob_sweep``
        already archived the keys and dropped the disk copies)."""
        _BLOB_DEMOTIONS.inc(len(keys))
        obs_recorder.record(
            "tier",
            "blob_demote",
            keys=len(keys),
            nbytes=nbytes,
            volume=self.volume_id,
        )
        self.publish_gauges()

    def load(self, key: str) -> tuple[list[Request], dict[int, Any]]:
        """(metas, values) for an archived entry, ready to re-land into
        the memory tier. Raises KeyError when not archived."""
        if key not in self.archived:
            raise KeyError(key)
        return self.decode_entry(self.store.get(self._object(key)))

    def restored(self, key: str, reason: str) -> None:
        """Bookkeeping after the volume re-landed ``key``. A demoted
        object (the sole copy) is dropped with the promotion; a pinned
        CHECKPOINT object is kept — the fleet manifest references it, and
        deleting it here would destroy the durable copy a later cold
        restore replays."""
        kept = key in self.pinned
        if kept:
            nbytes = self.archived.get(key, 0)
        else:
            nbytes = self.archived.pop(key, 0)
            self.store.delete(self._object(key))
        _BLOB_RESTORES.inc(reason=reason)
        obs_ledger.record(
            obs_ledger.DISK,
            obs_ledger.INGRESS,
            nbytes,
            volume=self.volume_id,
            items=[(key, nbytes)],
        )
        obs_recorder.record(
            "tier",
            "blob_restore",
            key=key,
            nbytes=nbytes,
            volume=self.volume_id,
            reason=reason,
            kept=kept,
        )
        self.publish_gauges()

    def discard(self, key: str) -> bool:
        """Drop a stale blob copy (the key was overwritten or deleted
        above this tier — new bytes supersede even a checkpoint copy);
        idempotent."""
        self.pinned.discard(key)
        existed = self.archived.pop(key, None) is not None
        if existed:
            self.store.delete(self._object(key))
            self.publish_gauges()
        return existed

    def manifest(self, exclude: Iterable[str] = ()) -> list[dict]:
        """Blob-archived entries' meta-only manifest (controller index
        rebuilds must see the blob tier too — archived bytes may be the
        only copy). ``exclude`` skips keys a warmer tier already reported.
        Each item costs one object get (the metas live in the payload) —
        acceptable for the rebuild path, never on the serve path."""
        skip = set(exclude)
        items: list[dict] = []
        for key in sorted(self.archived):
            if key in skip:
                continue
            name = self._object(key)
            try:
                metas, _values = self.decode_entry(self.store.get(name))
                _size, mtime = self.store.head(name)
            except Exception:  # noqa: BLE001 - a torn/raced object must
                # not fail the whole rebuild
                continue
            for meta in metas:
                items.append({"meta": meta.meta_only(), "mtime": mtime})
        return items

    def reset(self) -> None:
        """Drop the process-local bookkeeping ONLY: the blob objects are
        the durable cold tier — a volume reset (test teardown, store
        shutdown) must not destroy the archive a later cold restore
        replays. Tests isolate runs with per-run TORCHSTORE_TPU_BLOB_DIR
        roots; ``purge()`` is the destructive wipe."""
        self.archived.clear()
        self.pinned.clear()
        self.publish_gauges()

    def purge(self) -> None:
        """Destructive wipe: delete every archived object (test cleanup)."""
        for key in list(self.archived):
            self.store.delete(self._object(key))
        self.archived.clear()
        self.pinned.clear()
        self.publish_gauges()


# ---- fleet manifest (scale-to-zero) -------------------------------------


def write_fleet_manifest(
    store: BlobStore, keys: dict[str, dict], extra: Optional[dict] = None
) -> dict:
    """Persist the fleet manifest: the committed-key index snapshot +
    blob object map a cold restore replays. ``keys`` maps each committed
    key to ``{"object": blob object name, "nbytes": payload bytes,
    "write_gen": generation}``. Crash-safe via BlobStore.put (write-temp
    → fsync → rename): a writer killed mid-checkpoint leaves the PREVIOUS
    manifest intact, never a torn one."""
    doc = {
        "version": 1,
        "generated": time.time(),
        "count": len(keys),
        "keys": keys,
        **(extra or {}),
    }
    store.put(MANIFEST_OBJECT, json.dumps(doc).encode())
    obs_recorder.record(
        "tier", "blob_manifest", count=len(keys), root=store.root
    )
    return doc


def read_fleet_manifest(store: BlobStore) -> Optional[dict]:
    """The last committed fleet manifest, or None when no checkpoint has
    ever completed (a torn write never surfaces here: puts are atomic)."""
    try:
        return json.loads(store.get(MANIFEST_OBJECT).decode())
    except KeyError:
        return None
