"""Tiered capacity & multi-version serving (ROADMAP item 5, ISSUE 12).

The weight channel historically served LATEST and GC'd everything ``keep``
versions behind, and store capacity was hard-capped by tmpfs. Production RL
fleets run mixed cohorts — rollout generators on v_t, evaluation on v_{t−k},
canaries on an experimental branch, replay/debug on arbitrary history — so
this subsystem adds a version-retention and capacity layer between the data
plane and the channel protocol:

- **Cohort retention leases** (:mod:`torchstore_tpu.tiering.leases`): a
  controller-side TTL'd registry pinning ``(channel, version)`` pairs per
  cohort id. ``WeightPublisher._gc`` / the partial-reclaim path skip pinned
  versions, the controller's ``notify_delete_batch`` REFUSES to de-index a
  leased version's keys (the hard guarantee — a pinned version is never
  reaped mid-read, whoever issues the delete), and
  ``WeightSubscriber.acquire(version=...)`` holds a lease for the read's
  duration.

- **Spill tier** (:mod:`torchstore_tpu.tiering.spill`): a per-volume spill
  writer demotes cold versions' entries from the memory/tmpfs tier to disk
  (crash-safe write-temp → fsync → rename via ``storage_utils/file_store``)
  under a watermark policy (``TORCHSTORE_TPU_TIER_HIGH/LOW_PCT`` of the pool
  budget, LRU by version access, leased-hot versions exempt). Gets on
  spilled keys FAULT BACK IN through the existing transport ladder: the
  volume re-lands the entry from disk bracketed by the landing stamps
  (one-sided readers and doorbells observe a torn/busy bracket and fall
  back to the RPC get, exactly like any other landing), then serves — the
  warm path pays nothing beyond one dict lookup.

- **Catalog & observability**: ``ts.version_catalog()`` (per-channel
  versions × tier × leases × bytes), ``ts_tier_{resident,spilled}_bytes`` /
  ``ts_spills_total`` / ``ts_fault_ins_total{reason}`` instruments,
  spill/fault-in decisions on the flight recorder, and ``"disk"`` ledger
  cells so ``ts.traffic_matrix()`` separates spill I/O from wire bytes.
"""

from __future__ import annotations

import re
from typing import Optional

# Tier states carried per (key, volume) in the controller index
# (``controller.StorageInfo.tier``) and reported by ``ts.version_catalog``.
RESIDENT = "resident"
TIERED = "spilled"

# A channel version's keys look like "{channel}/v{n}/{leaf...}" (including
# the "{channel}/v{n}/MAPPING" commit marker). The group is the
# "{channel}/v{n}" prefix — the unit of spill LRU and lease pinning.
_VERSION_SEG = re.compile(r"^v(\d+)$")


def version_group(key: str) -> Optional[tuple[str, int]]:
    """``(channel, version)`` for a channel-version-shaped key, else None.
    The FIRST ``v<digits>`` path segment wins (channels may nest slashes;
    a version directory never does)."""
    segs = key.split("/")
    for i in range(1, len(segs)):
        m = _VERSION_SEG.match(segs[i])
        if m is not None:
            return "/".join(segs[:i]), int(m.group(1))
    return None


def group_key(channel: str, version: int) -> str:
    return f"{channel}/v{int(version)}"


from torchstore_tpu.tiering.leases import Lease, LeaseRegistry  # noqa: E402

__all__ = [
    "Lease",
    "LeaseRegistry",
    "RESIDENT",
    "TIERED",
    "group_key",
    "version_group",
]
