"""Metadata plane: the ``Controller`` actor — now the COORDINATOR.

TPU-native equivalent of /root/reference/torchstore/controller.py:22-293.
The key -> {volume_id -> StorageInfo} index itself lives in
:mod:`torchstore_tpu.metadata.index_core` (tslint ``shard-discipline``
enforces that boundary): an unsharded store hosts one ``IndexCore`` right
here, while ``ts.initialize(controller_shards=N)`` partitions it across N
``ControllerShard`` actors by stable key hash and this actor keeps only
fleet-scoped state — placement epoch, health supervisor, streams, relay
trees, leases, strategy — reached through ``self.idx`` (a local core or
the RemoteIndex fan-out; one engine code path either way). The controller
never carries tensor bytes — clients notify it with ``meta_only`` requests
after the data plane transfer completes (two-plane invariant, SURVEY §2.2.1).
"""

from __future__ import annotations

import time
from typing import Any, Optional

from torchstore_tpu import faults
from torchstore_tpu import relay as relay_mod
from torchstore_tpu import tiering
from torchstore_tpu.autoscale.engine import AutoscaleEngine
from torchstore_tpu.control.engine import ControlEngine
from torchstore_tpu.logging import get_logger
from torchstore_tpu.metadata.index_core import (  # noqa: F401 - re-exported
    IndexCore,
    ObjectType,
    PartiallyCommittedError,
    StorageInfo,
    StoreKeyError,
    resolve_manifests,
)
from torchstore_tpu.observability import metrics as obs_metrics
from torchstore_tpu.observability import recorder as obs_recorder
from torchstore_tpu.runtime import Actor, ActorRef, endpoint
from torchstore_tpu.transport.types import Request
from torchstore_tpu.utils import spawn_logged

logger = get_logger("torchstore_tpu.controller")

# Coordinator-scoped instruments (index-op counters live with the index —
# torchstore_tpu/metadata/index_core.py; surfaced through ``stats()``).
_PREWARM_RESERVED = obs_metrics.gauge(
    "ts_prewarm_reserved_bytes",
    "tmpfs bytes held by live prewarm reservations, per volume",
)
_VOLUME_HEALTH = obs_metrics.gauge(
    "ts_volume_health",
    "Supervisor view of each volume: 1 healthy, 0.5 probation, 0 quarantined",
)
_QUARANTINES = obs_metrics.counter(
    "ts_quarantines_total",
    "Volumes moved to quarantine by the health supervisor",
)
_RELAY_FORWARDED = obs_metrics.counter(
    "ts_relay_forwarded_keys_total",
    "Store keys forwarded one hop down a broadcast relay tree, per channel",
)
_RELAY_REPARENTS = obs_metrics.counter(
    "ts_relay_reparents_total",
    "Relay-tree edges re-parented onto a healthy ancestor, per channel",
)
_LEASE_BLOCKED_DELETES = obs_metrics.counter(
    "ts_lease_blocked_deletes_total",
    "Delete requests refused because a cohort lease pins the version",
)


class Controller(Actor):
    def __init__(self) -> None:
        # The index-owning state machine (torchstore_tpu/metadata/): an
        # unsharded store's whole index lives in this core; attach_shards
        # swaps ``self.idx`` to the RemoteIndex fan-out and the core goes
        # idle. Every engine below reaches the index ONLY through
        # ``self.idx`` (tslint shard-discipline).
        self.core = IndexCore(self)
        self.idx = self.core
        self._shard_refs: list[ActorRef] = []
        self._shard_stamped: list = []
        self.strategy = None
        self.volume_refs: dict[str, ActorRef] = {}
        self.volume_hostnames: dict[str, str] = {}
        # Placement epoch: bumped ONLY on structural metadata changes (a
        # key appearing/disappearing, a shape/dtype/layout change, a
        # replica detach, volume replacement, index rebuild) — NOT on
        # same-shape overwrites. The iteration-stable transfer-plan cache
        # (client.SyncPlanCache) validates against it: an RL loop's steady
        # re-publish keeps the epoch still, so iteration N+1's plans stay
        # hot, while any change that could re-route or re-shape a fetch
        # invalidates every cached plan fleet-wide. Shards report their
        # structural changes through ONE bump_placement_epoch RPC before
        # acking — the epoch stays the fleet's single clock.
        self._placement_epoch = 1
        # Stamped stream/epoch segment (metadata/stamped.py): same-host
        # clients validate plans and poll streamed publishes one-sided.
        self._meta_writer = None
        # Cross-host metadata relay (metadata/mirror.py): the root feed
        # pushes this host's stamped wire images to subscriber mirrors,
        # fanned out over the relay-tree shape so OUR egress stays O(1)
        # in subscriber count. _meta_parents holds the assigned tree
        # ("" = the root feed); _meta_subscribers each host's re-serve
        # endpoint (a mirror's child feed).
        self._meta_feed = None
        self._meta_subscribers: dict[str, dict] = {}
        self._meta_parents: dict[str, str] = {}
        # Health supervisor state: per-volume heartbeat bookkeeping. A
        # volume is 'ok' | 'probation' (answered pings again after a
        # quarantine; not yet trusted) | 'quarantined' (missed
        # consecutive-miss-threshold heartbeats: placement skips it, reads
        # are served from healthy replicas, and — with auto-repair on — its
        # keys re-replicate onto healthy volumes). One supervisor task,
        # started by init(), cancelled at teardown.
        self._vol_health: dict[str, dict] = {}
        self._health_task = None
        self._health_tasks: set = set()
        # Volumes with an auto re-replication pass in flight (one per
        # quarantine event; a flapping volume must not stack repairs).
        self._repairing: set[str] = set()
        import os

        self._health_interval = float(
            os.environ.get("TORCHSTORE_TPU_HEALTH_INTERVAL_S", 2.0)
        )
        self._miss_threshold = max(
            1, int(os.environ.get("TORCHSTORE_TPU_HEALTH_MISS_THRESHOLD", 3))
        )
        self._auto_repair = os.environ.get(
            "TORCHSTORE_TPU_AUTO_REPAIR", "1"
        ).strip().lower() not in ("0", "false", "no", "off", "")
        # Prewarm capacity reservations: rid -> (monotonic expiry,
        # {volume_id: granted bytes}). Grants are counted against volume
        # tmpfs headroom so CONCURRENT prewarms (several trainers booting on
        # one host) can't collectively oversubscribe /dev/shm; a crashed
        # prewarmer's reservation expires by TTL instead of pinning capacity
        # forever.
        self._prewarm_reservations: dict[str, tuple[float, dict[str, int]]] = {}
        # Broadcast relay distribution (torchstore_tpu/relay.py): per-
        # channel membership ({volume_id: subscriber refcount} + a topology
        # epoch bumped on every membership/health re-shape) and per-stream-
        # key relay RUNS — the live fan-out of one published version down
        # its tree. Edge forwarder tasks live in _relay_tasks (cancelled at
        # teardown); all state is controller-process-local, like streams.
        self._relay_enabled = os.environ.get(
            "TORCHSTORE_TPU_RELAY_ENABLED", "1"
        ).strip().lower() not in ("0", "false", "no", "off", "")
        self._relay_fanout = max(
            1, int(os.environ.get("TORCHSTORE_TPU_RELAY_FANOUT", 2))
        )
        self._relay_reparent_s = float(
            os.environ.get("TORCHSTORE_TPU_RELAY_REPARENT_TIMEOUT_S", 5.0)
        )
        self._relay_channels: dict[str, dict] = {}
        self._relay_runs: dict[str, dict] = {}
        self._relay_tasks: set = set()
        # Control-engine preferred member order per channel (measured edge
        # proximity): build_tree attaches these nearest the root in the
        # NEXT trees built; absent channels keep the sorted-id default.
        self._relay_prefer: dict[str, tuple[str, ...]] = {}
        # Cohort retention leases (torchstore_tpu/tiering/leases.py): the
        # authority on which (channel, version) pairs are pinned.
        # notify_delete_batch refuses to reap a pinned version's keys, the
        # tier sweeper passes the pinned groups to every volume's spill
        # writer, and WeightSubscriber.acquire(version=...) holds a lease
        # for the read's duration.
        self._leases = tiering.LeaseRegistry()
        # Background tier sweeper: every interval, run each volume's spill
        # pass (with current pins) and fold the reported transitions into
        # the index's tier states. Disabled when tiering is off or the
        # interval is <= 0 (ts.tier_sweep() still works on demand). ONE
        # parse of the enable knob, shared with the volumes' SpillTier —
        # the two sides must never disagree about whether tiering is on.
        from torchstore_tpu.tiering import spill as tiering_spill

        self._tier_enabled = tiering_spill.enabled()
        self._tier_interval = float(
            os.environ.get("TORCHSTORE_TPU_TIER_SWEEP_INTERVAL_S", 2.0)
        )
        self._tier_task = None
        # Control plane (torchstore_tpu/control/): the policy engine that
        # closes the telemetry -> placement loop. The reconcile loop runs
        # only when TORCHSTORE_TPU_CONTROL_INTERVAL_S is positive;
        # ts.control_plan() / ts.rebalance() reach the engine on demand
        # either way.
        self._control_engine = ControlEngine(self)
        self._control_interval = float(
            os.environ.get("TORCHSTORE_TPU_CONTROL_INTERVAL_S", 0.0) or 0.0
        )
        self._control_task = None
        # Autoscale plane (torchstore_tpu/autoscale/): the elastic-fleet
        # engine that scales volume count to the measured load. The
        # reconcile loop runs only when TORCHSTORE_TPU_AUTOSCALE_INTERVAL_S
        # is positive; ts.autoscale_plan() / ts.autoscale() reach the
        # engine on demand either way. ``_draining`` is the graceful
        # scale-in set: clients exclude these volumes from NEW placements
        # (get_volume_map health reads "draining") while reads keep
        # serving until every resident key has migrated off.
        self._autoscale_engine = AutoscaleEngine(self)
        self._autoscale_interval = float(
            os.environ.get("TORCHSTORE_TPU_AUTOSCALE_INTERVAL_S", 0.0) or 0.0
        )
        self._autoscale_task = None
        self._draining: set[str] = set()
        # Elastic-reshard gate for the UNSHARDED metadata plane: while set
        # (an unset Event), coordinator-side index mutations park until the
        # reshard swaps the authority — the sharded case parks on the
        # shards themselves (metadata/shards.py freeze-via-park).
        self._reshard_gate = None
        # Layer-streamed sync state: sd_key -> {"version", "sealed",
        # "watermarks": {store_key: version}}. ``version`` is the stream in
        # flight (or last begun), ``sealed`` the highest sealed version, and
        # each watermark records the NEWEST version whose bytes landed for
        # that store key (set inside notify_put_batch, so a watermark is
        # only ever visible once its data-plane bytes are committed). The
        # marker (sd_key/MAPPING) stays the terminal seal record readers of
        # the barrier path key on; these records are the append-progressive
        # half that lets streaming readers serve per-key partial versions.
        self._streams: dict[str, dict] = {}

    MAX_STREAMS = 256

    def _cond(self):
        # ONE condition serves the whole process: the core notifies it on
        # every index change (wait_for_committed/wait_for_change) and the
        # stream machinery on every watermark/seal — unsharded, they are
        # the same wakeup, exactly as before the split.
        return self.core.cond()

    # ---- IndexCore host surface + test-visible reclaim state -------------

    def quarantined_ids(self) -> set:
        return self._quarantined_ids()

    async def on_structural(self) -> int:
        return self._bump_epoch()

    def _bump_epoch(self) -> int:
        """The ONE way the placement epoch moves: every structural change
        site routes here. The stamped header is republished IMMEDIATELY —
        not debounced — because the client's zero-RPC plan validation
        treats "stamped epoch == epoch I hold" as a CONFIRMATION: a
        debounce here would let a reader confirm stale plans (and read a
        supersede-detached replica's old bytes) for the whole publish
        window. Bumps are structural-only (rare in steady state), so the
        synchronous publish costs one small stream-snapshot pickle."""
        self._placement_epoch += 1
        if self._meta_writer is not None:
            self._meta_writer.publish_now()
        return self._placement_epoch

    def _touch_streams(self) -> None:
        """A stream record changed: republish the stamped stream snapshot
        (debounced) so one-sided pollers see it."""
        if self._meta_writer is not None:
            self._meta_writer.mark_dirty()

    def _relay_stamped_view(self, stream_key: str) -> Optional[dict]:
        """The relay-gate picture for one stream record, published INTO the
        stamped snapshot so one-sided pollers apply the exact
        ``wait_for_stream`` gate formula against a local replica. Only
        gate-ELIGIBLE volumes (the same membership/quarantine/tree checks
        as :meth:`_relay_gate_run`) get a landed entry — a volume absent
        from ``landed`` polls ungated, matching the RPC's fail-safe."""
        run = self._relay_runs.get(stream_key)
        if run is None or run.get("dead"):
            return None
        ch = self._relay_channels.get(run["channel"])
        if ch is None:
            return None
        quarantined = self._quarantined_ids()
        landed = {}
        for vid in {run["root"], *run["parents"]}:
            if ch["members"].get(vid, 0) <= 0 or vid in quarantined:
                continue
            landed[vid] = sorted(run["landed"].get(vid, ()))
        if not landed:
            return None
        return {"forwarded": sorted(run["metas"]), "landed": landed}

    def _streams_payload(self) -> dict:
        """The one-sided stream view: per record, exactly what
        ``wait_for_stream`` needs (version/sealed/watermarks/aliases/
        quant, plus the relay-gate picture for gated readers). Published
        AFTER the watermark step commits — and the relay view is read in
        the same tick as the watermarks — so a reader can only under-see
        progress: never a watermark before its bytes, never a landed copy
        before its index merge."""
        streams = {}
        for key, rec in self._streams.items():
            entry = {
                "version": rec["version"],
                "sealed": rec["sealed"],
                "watermarks": dict(rec["watermarks"]),
                "aliases": dict(rec.get("aliases") or {}),
                "quant": rec.get("quant"),
            }
            relay_view = self._relay_stamped_view(key)
            if relay_view is not None:
                entry["relay"] = relay_view
            streams[key] = entry
        return {"streams": streams}

    # Direct-instantiation test compatibility: the reclaim machinery moved
    # into the core; these views keep white-box assertions working.
    @property
    def _pending_reclaims(self):
        return self.core._pending_reclaims

    @property
    def _reclaim_tasks(self):
        return self.core._reclaim_tasks

    @property
    def _reclaim_running(self):
        return self.core._reclaim_running

    # ---- bootstrap -------------------------------------------------------

    @endpoint
    async def init(self, strategy, volume_refs: list[ActorRef]) -> dict[str, Any]:
        """Resolve volume ids via a get_id fan-out (reference
        /root/reference/torchstore/strategy.py:98-109) and adopt the strategy."""
        import asyncio

        self.strategy = strategy
        infos = await asyncio.gather(*(ref.get_id.call_one() for ref in volume_refs))
        self.volume_refs = {}
        self.volume_hostnames = {}
        for ref, info in zip(volume_refs, infos):
            vid = str(info["volume_id"])
            if vid in self.volume_refs:
                raise ValueError(
                    f"duplicate volume id {vid!r}; check strategy env wiring"
                )
            self.volume_refs[vid] = ref
            self.volume_hostnames[vid] = info["hostname"]
        self._vol_health = {
            vid: {"state": "ok", "misses": 0, "oks": 0}
            for vid in self.volume_refs
        }
        for vid in self.volume_refs:
            _VOLUME_HEALTH.set(1, volume=vid)
        self._draining.clear()
        self._start_supervisor()
        self._start_tier_sweeper()
        self._start_control_loop()
        self._start_autoscale_loop()
        self._autoscale_engine.publish_fleet_gauges()
        from torchstore_tpu.metadata import stamped as stamped_mod

        if stamped_mod.enabled():
            # Coordinator segment: stream snapshot + placement epoch. The
            # unsharded core publishes its own index segment alongside;
            # attach_shards leaves index publication to the shards.
            if self._meta_writer is None:
                self._meta_writer = stamped_mod.MetaStampWriter(
                    self._streams_payload,
                    epoch_fn=lambda: self._placement_epoch,
                )
                self._meta_writer.mark_dirty()
            if self.core.meta_writer is None and not self._shard_refs:
                self.core.meta_writer = stamped_mod.MetaStampWriter(
                    self.core.meta_payload
                )
            if stamped_mod.mirror_enabled() and self._meta_feed is None:
                # Cross-host metadata relay root: push the stamped wire
                # images to subscriber mirrors (metadata/mirror.py).
                from torchstore_tpu.metadata.mirror import MetaFeedServer

                self._meta_feed = MetaFeedServer(self._meta_feed_sources)
                await self._meta_feed.ensure_started()
        # Unclean-exit post-mortem: a controller dying with faults/errors
        # in its flight ring leaves the last seconds on disk.
        obs_recorder.recorder().arm_exit_dump()
        return {
            "volume_ids": sorted(self.volume_refs),
            "hostnames": self.volume_hostnames,
        }

    @endpoint
    async def attach_shards(
        self, coordinator: ActorRef, shard_refs: list[ActorRef]
    ) -> dict[str, Any]:
        """Partition the metadata plane: hand each ControllerShard its
        slot (id, fleet refs, current quarantine picture) and swap this
        actor's index authority to the RemoteIndex fan-out. Runs at
        bootstrap, before any key is indexed — the coordinator's own core
        goes idle (its stamped index segment is never created sharded)."""
        from torchstore_tpu.metadata.shards import RemoteIndex

        self._shard_refs = list(shard_refs)
        self._shard_stamped = []
        quarantined = sorted(self._quarantined_ids())
        for i, ref in enumerate(shard_refs):
            res = await ref.shard_init.call_one(
                i,
                len(shard_refs),
                coordinator,
                self.volume_refs,
                self.volume_hostnames,
                quarantined,
            )
            self._shard_stamped.append(res.get("stamped"))
        self.idx = RemoteIndex(self._shard_refs)
        if self.core.meta_writer is not None:
            self.core.meta_writer.close()
            self.core.meta_writer = None
        self._bump_epoch()
        return {"shards": len(self._shard_refs)}

    @endpoint
    async def metadata_topology(self) -> dict[str, Any]:
        """What a client's MetadataRouter needs: shard refs for fan-out
        routing and stamped-segment descriptors for the one-sided path
        (attached only by same-host clients)."""
        if self._shard_refs:
            index_descs = list(self._shard_stamped)
        else:
            index_descs = [
                self.core.meta_writer.describe()
                if self.core.meta_writer is not None
                else None
            ]
        return {
            "shards": list(self._shard_refs),
            "stamped": {
                "coordinator": (
                    self._meta_writer.describe()
                    if self._meta_writer is not None
                    else None
                ),
                "index": index_descs,
            },
            # Cross-host subscription root: a remote router hands this to
            # mirror.ensure_mirror() and attaches the LOCAL replica instead
            # of paying metadata RPCs over DCN.
            "meta_feed": (
                {"host": self._meta_feed.host, "port": self._meta_feed.port}
                if self._meta_feed is not None and self._meta_feed.port
                else None
            ),
        }

    def _meta_feed_sources(self) -> list:
        """Descriptor table the feed pump polls: source 0 is the
        coordinator segment (streams + placement epoch), 1+i the index
        segments — positional identity mirrors adopt verbatim."""
        coord = (
            self._meta_writer.describe()
            if self._meta_writer is not None
            else None
        )
        if self._shard_refs:
            index = list(self._shard_stamped)
        else:
            index = [
                self.core.meta_writer.describe()
                if self.core.meta_writer is not None
                else None
            ]
        return [coord] + index

    def _meta_assign_parent(self, host: str, down: set) -> str:
        """Pick ``host``'s feed parent over the relay-tree shape: the root
        feed ("" — out-degree ``relay.ROOT_FANOUT`` keeps the index host's
        egress O(1)) or another subscriber's mirror, preferring in-capacity
        then shallowest then least-loaded; ``down`` hosts and ``host``'s
        own descendants (cycle avoidance) are never candidates. Over-
        capacity assignment beats refusal — a full tree still feeds."""
        kids: dict[str, int] = {}
        for h, p in self._meta_parents.items():
            if h != host:
                kids[p] = kids.get(p, 0) + 1

        def _depth(h: str) -> int:
            d = 0
            seen = set()
            while h and h not in seen:
                seen.add(h)
                h = self._meta_parents.get(h, "")
                d += 1
            return d

        def _descends_from_host(cand: str) -> bool:
            seen = set()
            while cand and cand not in seen:
                if cand == host:
                    return True
                seen.add(cand)
                cand = self._meta_parents.get(cand, "")
            return False

        scored = []
        for cand in [""] + sorted(self._meta_subscribers):
            if cand == host or cand in down or _descends_from_host(cand):
                continue
            cap = relay_mod.ROOT_FANOUT if not cand else self._relay_fanout
            load = kids.get(cand, 0)
            scored.append((int(load >= cap), _depth(cand), load, cand))
        scored.sort()
        parent = scored[0][3] if scored else ""
        self._meta_parents[host] = parent
        return parent

    @endpoint
    async def meta_subscribe(
        self,
        host: str,
        feed_host: str,
        feed_port: int,
        down: Optional[list] = None,
    ) -> dict[str, Any]:
        """Subscribe ``host``'s MetadataMirror to the fleet's metadata
        feed. ``down`` names parents the caller just lost (its re-subscribe
        after a mid-stream parent death): they are dropped from the
        subscriber table so no future assignment routes through them —
        their own children re-parent the same way when their feeds go
        quiet. Returns the assigned parent's feed endpoint."""
        if self._meta_feed is None:
            raise RuntimeError(
                "metadata feed disabled (stamped or mirror tier off)"
            )
        host = str(host)
        for dead in set(down or []):
            dead = str(dead)
            if dead != host:
                self._meta_subscribers.pop(dead, None)
                self._meta_parents.pop(dead, None)
        self._meta_subscribers[host] = {
            "host": str(feed_host),
            "port": int(feed_port),
        }
        parent = self._meta_assign_parent(host, set(down or []))
        if parent:
            ep = self._meta_subscribers[parent]
            return {
                "parent_hostname": parent,
                "host": ep["host"],
                "port": ep["port"],
            }
        from torchstore_tpu.utils import get_hostname

        # Root assignment: label the parent with THIS host's name so the
        # subscriber's ingress ledger cells attribute the feed bytes to a
        # real host edge (the index-host egress the relay tree bounds).
        return {
            "parent_hostname": get_hostname(),
            "host": self._meta_feed.host,
            "port": self._meta_feed.port,
        }

    @endpoint
    async def meta_unsubscribe(self, host: str) -> None:
        """Drop ``host`` from the metadata feed tree (clean shutdown). Its
        children re-parent through their own quiet-feed re-subscription."""
        self._meta_subscribers.pop(str(host), None)
        self._meta_parents.pop(str(host), None)

    @endpoint
    async def get_volume_map(self) -> dict[str, dict]:
        return {
            vid: {
                "ref": ref,
                "hostname": self.volume_hostnames[vid],
                "health": self._vol_health.get(vid, {}).get("state", "ok"),
            }
            for vid, ref in self.volume_refs.items()
        }

    @endpoint
    async def get_strategy(self):
        return self.strategy

    # ---- endpoints -------------------------------------------------------

    @endpoint
    async def locate_volumes(
        self,
        keys: list[str],
        missing_ok: bool = False,
        require_fully_committed: bool = True,
    ) -> dict[str, dict[str, StorageInfo]]:
        return await self.idx.locate(keys, missing_ok, require_fully_committed)

    @endpoint
    async def contains(self, key: str) -> str:
        return await self.idx.contains(key)

    @endpoint
    async def notify_put_batch(
        self,
        metas: list[Request],
        volume_id: "str | list[str]",
        detach_volume_ids: Optional[list[str]] = None,
        write_gens: Optional[dict[str, dict[str, int]]] = None,
        supersede: bool = False,
        watermark: Optional[tuple] = None,
        unchanged: Optional[dict] = None,
    ) -> None:
        """Index ``metas`` as stored on ``volume_id`` — a single id, or a
        LIST of ids for replicated puts (one RPC, one generation bump, and
        counters measuring LOGICAL puts regardless of replication).

        ``detach_volume_ids``: replicas whose data-plane write FAILED for
        exactly these metas — their stale copies are detached in the same
        indexing step (no await between index and detach), so no reader
        ever sees new metadata alongside a stale-replica location. Detach
        is meta-granular: for sharded keys only the failed shard's coords
        are removed; sibling ranks' shards on the same volume survive.

        ``write_gens``: {volume_id: {key: gen}} — the volume-assigned write
        generations from the data-plane acks; indexed per replica so later
        reclaims of this copy can be made conditional.

        ``supersede``: this notify is a full overwrite of each meta (a
        normal client put that landed on EVERY replica the strategy chose):
        any OTHER volume still indexed for the same meta now holds
        superseded bytes under committed metadata — e.g. an extra copy an
        auto-repair re-replicated while its home volume was quarantined —
        and is detached + reclaimed in the same indexing step. Must stay
        False for partial writers (``replicate_to``/repair, which add
        copies without touching the others).

        ``watermark``: ``(stream_key, version)`` from a layer-streamed
        publish — every meta in this batch records ``version`` as its
        per-key stream watermark IN THE SAME INDEXING STEP as the metadata
        (no RPC between bytes-committed and watermark-visible), and the
        generation bump below wakes ``wait_for_stream`` long-pollers.

        ``unchanged``: ``{new_store_key: (base_store_key, base_version)}``
        — unchanged-key aliases of the SAME streamed publish (delta wire
        tier): each alias watermarks ``new_store_key`` at the stream
        version, pointing readers at the base key's already-committed
        bytes, in the same watermark step as this batch's metas (requires
        ``watermark``). The base keys are validated committed — a GC'd
        base fails the publish loudly instead of wedging every reader.

        Under a SHARDED metadata plane clients never call this endpoint:
        the router fans the batch to the owning shards and records the
        watermark here afterwards (``stream_watermark``)."""
        await self._reshard_wait()
        if self._shard_refs:
            raise RuntimeError(
                "this store's metadata plane is sharded: notify_put_batch "
                "routes through the client-side shard router, not the "
                "coordinator (stale store handle?)"
            )
        await faults.afire("controller.notify")
        volume_ids = [volume_id] if isinstance(volume_id, str) else volume_id
        await self.core.apply_put_batch(
            metas,
            volume_ids,
            detach_volume_ids=detach_volume_ids,
            write_gens=write_gens,
            supersede=supersede,
        )
        if watermark is not None:
            stream_key, version = watermark
            await self._apply_watermark(
                stream_key, int(version), metas, volume_ids, unchanged
            )
        elif unchanged:
            raise ValueError(
                "notify_put_batch(unchanged=...) requires watermark=: "
                "unchanged-key aliases are a streamed-publish protocol"
            )
        await self.core.bump({meta.key for meta in metas})
        # The reply carries the placement epoch so publishers track it for
        # free (no extra RPC): a bump invalidates their cached plans.
        return self._placement_epoch

    async def _apply_watermark(
        self,
        stream_key: str,
        version: int,
        metas: list[Request],
        volume_ids: list,
        unchanged: Optional[dict],
    ) -> None:
        """The watermark step of a streamed publish (see notify_put_batch):
        shared verbatim by the unsharded notify and the sharded router's
        ``stream_watermark`` follow-up — in both, it runs strictly AFTER
        the batch's metadata committed to the owning index."""
        # Faultpoint INSIDE the watermark step: a delay/wedge here holds
        # committed bytes invisible to streaming readers (they keep
        # long-polling — never serve unwatermarked keys); a raise fails
        # the whole notify, so the publisher sees the error before any
        # reader could have trusted the partial version.
        await faults.afire("channel.watermark")
        rec = self._stream_rec(stream_key, int(version))
        now = time.time()
        for meta in metas:
            prev = rec["watermarks"].get(meta.key, 0)
            # max(): a delayed notify from a superseded stream must
            # never roll a key's watermark backwards.
            rec["watermarks"][meta.key] = max(prev, int(version))
            if int(version) == rec["version"]:
                # Landing timestamp for the CURRENT generation's
                # timeline (setdefault: the first commit of a key is
                # its landing; superseded late notifies don't count).
                rec["landing_ts"].setdefault(meta.key, now)
        if unchanged:
            # Unchanged-key aliases ride the SAME watermark step as
            # the batch's metas: readers woken by this notify see the
            # aliased keys ready together with the landed ones.
            await self._record_unchanged(rec, unchanged, int(version), now)
        # Broadcast fan-out: keys that just landed on the origin
        # volume(s) start flowing down the channel's relay tree, per
        # layer — interior hops forward as watermarks land, never
        # waiting for the seal.
        await self._relay_on_landing(
            stream_key, int(version), metas, volume_ids
        )
        self._touch_streams()

    @endpoint
    async def stream_watermark(
        self,
        stream_key: str,
        version: int,
        metas: list[Request],
        volume_ids: list,
        unchanged: Optional[dict] = None,
    ) -> None:
        """Sharded-notify follow-up: record the batch's stream watermarks
        AFTER every owning shard indexed its slice (the router orders the
        two), preserving bytes-committed-before-watermark-visible across
        the partition. Wakes this coordinator's ``wait_for_stream``
        long-pollers — per-key generations live on the shards."""
        await self._apply_watermark(
            stream_key, int(version), metas, volume_ids, unchanged
        )
        cond = self._cond()
        async with cond:
            cond.notify_all()

    def _lease_guard(self, keys: list[str]) -> list[str]:
        """Retention-lease guard (tiering/): filter out keys under a PINNED
        (channel, version) — they stay indexed whoever issued the delete
        (the publisher's GC, close(delete=True), a raw delete_prefix).
        This is the hard "never reaped mid-read" guarantee; lease-aware
        callers (WeightPublisher._gc) skip pinned versions before ever
        asking, and reap a retained version on a LATER publish's GC once
        its last lease lapses. One pinned-groups snapshot serves the whole
        batch (a per-key lease-table scan would be O(keys x leases) on the
        controller loop)."""
        pinned = self._leases.pinned_groups()
        if not pinned:
            return keys
        blocked = []
        passed = []
        for key in keys:
            group = tiering.version_group(key)
            if group is not None and tiering.group_key(*group) in pinned:
                blocked.append(key)
            else:
                passed.append(key)
        if blocked:
            _LEASE_BLOCKED_DELETES.inc(len(blocked))
            obs_recorder.record(
                "tier",
                "delete_blocked",
                keys=len(blocked),
                sample=blocked[0],
            )
            logger.warning(
                "refusing to delete %d key(s) under leased version(s) "
                "(e.g. %s); release or let the cohort leases expire "
                "first",
                len(blocked),
                blocked[0],
            )
        return passed

    def _retire_stream_records(self, deleted) -> None:
        """Deleting a streamed state dict's commit marker retires its
        stream record too (delete_prefix of a version directory takes the
        marker with it): established wait_for_stream pollers wake and
        observe the record gone instead of blocking forever, and per-key
        watermarks are dropped with the bytes they described."""
        for key in deleted:
            self._streams.pop(key, None)
            self._relay_stop_run(key)
            if key.endswith("/MAPPING"):
                self._streams.pop(key[: -len("/MAPPING")], None)
                self._relay_stop_run(key[: -len("/MAPPING")])
        self._touch_streams()

    @endpoint
    async def notify_delete_batch(self, keys: list[str]) -> dict[str, list[str]]:
        """Remove keys from the index FIRST (notify-before-delete ordering,
        /root/reference/torchstore/client.py:408-411) and return which
        volumes held each key so the client can clear the data plane.
        Sharded stores route through delete_guard -> shard delete_keys ->
        delete_finish instead (the router owns the ordering)."""
        await self._reshard_wait()
        if self._shard_refs:
            raise RuntimeError(
                "this store's metadata plane is sharded: deletes route "
                "through the client-side shard router, not the coordinator"
            )
        self.core.count_deletes(len(keys))
        keys = self._lease_guard(keys)
        # The bump below is gated on `if deleted:` — a delete that removed
        # nothing changed no placement, so skipping the bump is correct.
        by_volume = self.core.delete_keys(keys)  # tslint: disable=epoch-discipline
        # A delete is an observable change: wake wait_for_change waiters
        # (they re-check state and see 'missing').
        deleted = {k for vkeys in by_volume.values() for k in vkeys}
        if deleted:
            self._retire_stream_records(deleted)
            self._bump_epoch()
            await self.core.bump(deleted)
        return by_volume

    @endpoint
    async def delete_guard(self, keys: list[str]) -> list[str]:
        """Sharded delete, phase 1: the fleet-scoped lease guard. Returns
        the keys the owning shards may actually drop."""
        return self._lease_guard(keys)

    @endpoint
    async def delete_finish(self, deleted: list[str]) -> None:
        """Sharded delete, phase 3: retire stream records for what the
        shards actually removed, invalidate plans, wake stream pollers."""
        if not deleted:
            return
        self._retire_stream_records(deleted)
        self._bump_epoch()
        cond = self._cond()
        async with cond:
            cond.notify_all()

    @endpoint
    async def placement_epoch(self) -> int:
        """Current placement epoch (see __init__): ONE cheap RPC that lets a
        consumer validate a whole cached transfer plan instead of
        re-fetching the commit marker and re-locating every key (and the
        stamped header serves the same answer with ZERO RPCs same-host)."""
        return self._placement_epoch

    @endpoint
    async def bump_placement_epoch(self) -> int:
        """Force-invalidate every cached transfer plan fleet-wide. Called by
        publishers that restructure a state dict in a way the index cannot
        see (e.g. dropping keys from a push without deleting them), and by
        every ControllerShard reporting a structural index change."""
        return self._bump_epoch()

    @endpoint
    async def keys(self, prefix: Optional[str] = None) -> list[str]:
        return await self.idx.keys_list(prefix)

    # ---- blocking waits --------------------------------------------------

    @endpoint
    async def wait_for_committed(
        self, keys: list[str], timeout: Optional[float] = None
    ) -> None:
        """Block until every key exists and is fully committed (sharded keys:
        all mesh coordinates landed). Raises TimeoutError on expiry. The
        reference has no wait primitive — consumers poll get_state_dict in
        try/except loops; this replaces the poll with a single blocking RPC
        woken by the notify that commits the key."""
        await self.idx.wait_for_committed(keys, timeout)

    @endpoint
    async def wait_for_change(
        self, key: str, last_gen: int = 0, timeout: Optional[float] = None
    ) -> dict[str, Any]:
        """Block until ``key``'s update generation DIFFERS from ``last_gen``
        (every indexed put or delete of the key bumps it), then return
        ``{"gen", "state"}`` with state ∈ missing|partial|committed.
        ``last_gen=0`` returns immediately for any key that has ever been
        written — so a new subscriber picks up the current version without
        racing the next publish. Inequality, not ``>``: a controller
        restarted over a durable store re-seeds generations from scratch
        (rebuild_index), so a subscriber holding a larger pre-restart gen
        must wake immediately and resync rather than block through every
        subsequent publish (ADVICE r2)."""
        return await self.idx.wait_for_change(key, last_gen, timeout)

    # ---- layer-streamed sync (watermark protocol) ------------------------

    def _stream_rec(self, key: str, version: Optional[int] = None) -> dict:
        """The stream record for ``key``, created on first touch. Bounded:
        at MAX_STREAMS the least-recently-touched SEALED (idle) record is
        evicted first — a hot RL channel's live record must never lose to
        256 one-shot streams — falling back to the overall oldest only
        when every record has a stream in flight. Readers of an evicted
        record fall back to the barrier path loudly."""
        rec = self._streams.pop(key, None)
        if rec is None:
            if len(self._streams) >= self.MAX_STREAMS:
                victim = next(
                    (
                        k
                        for k, r in self._streams.items()
                        if r["sealed"] >= r["version"]
                    ),
                    next(iter(self._streams)),
                )
                self._streams.pop(victim)
            rec = {
                "version": version or 1,
                "sealed": 0,
                "watermarks": {},
                # Unchanged-watermark aliases (delta wire tier):
                # store_key -> (base_store_key, base_channel_version). A
                # delta publish whose key is fully unchanged records its
                # watermark HERE pointing at the previous version's bytes,
                # so streamed readers deliberately serve bit-identical
                # v/v-1 layers with zero re-transfer.
                "aliases": {},
                # Static quantization meta the publisher registered at
                # stream_begin (readers decode per-layer blobs before the
                # seal's marker exists).
                "quant": None,
                # Generation timeline (observability/timeline.py): begin ->
                # per-key landings -> seal -> per-subscriber acquire acks.
                "begin_ts": time.time(),
                "seal_ts": None,
                "landing_ts": {},
                "acks": {},
            }
        elif version is not None and version > rec["version"]:
            rec["version"] = version
            # A new generation restarts the timeline; the watermarks map
            # deliberately survives (max semantics across generations).
            rec["begin_ts"] = time.time()
            rec["seal_ts"] = None
            rec["landing_ts"] = {}
            rec["acks"] = {}
        # Re-insert at the END: dict order doubles as touch recency, so a
        # steadily re-streamed key stays clear of the eviction scan.
        self._streams[key] = rec
        return rec

    @endpoint
    async def stream_begin(self, key: str, quant: Optional[dict] = None) -> int:
        """Open the next streamed publish of ``key``; returns the assigned
        version (monotonic per key per controller lifetime). Long-pollers
        waiting for a stream to appear are woken (they observe the new
        in-flight version and can start acquiring layer by layer).

        ``quant``: static quantization meta (fmt/block/delta context) for
        a quantized streamed publish — readers need it to decode layer
        blobs BEFORE the seal writes the commit marker."""
        rec = self._streams.get(key)
        version = (max(rec["version"], rec["sealed"]) + 1) if rec else 1
        rec = self._stream_rec(key, version)
        # Unconditional: a reused record must not keep a PREVIOUS
        # generation's quant meta when this stream publishes unquantized
        # (readers would skip in-place landings and misdecode).
        rec["quant"] = quant
        self._touch_streams()
        cond = self._cond()
        async with cond:
            cond.notify_all()
        return version

    @endpoint
    async def stream_seal(self, key: str, version: int) -> None:
        """Terminal seal record for one streamed publish: the publisher
        calls it AFTER writing the MAPPING commit marker, so a sealed
        stream always has a readable barrier-path state dict too."""
        rec = self._stream_rec(key, int(version))
        rec["sealed"] = max(rec["sealed"], int(version))
        if int(version) == rec["version"] and rec.get("seal_ts") is None:
            rec["seal_ts"] = time.time()
        await self._relay_on_seal(key, int(version))
        self._touch_streams()
        cond = self._cond()
        async with cond:
            cond.notify_all()

    async def _record_unchanged(
        self, rec: dict, aliases: dict, version: int, now: float
    ) -> None:
        """Record unchanged-key watermark aliases on one stream record:
        each ``new_store_key`` is watermarked at ``version`` with its bytes
        aliased to an already-committed base store key. Validated HERE so a
        publish aliasing GC'd bytes fails the publisher loudly instead of
        handing readers a key they can never fetch. ONE batched locate
        validates every base key (the delta tier's target case is MOST of
        a state dict unchanged — a per-alias round trip would put O(keys)
        shard RPCs on the publish critical path)."""
        base_keys = sorted({alias[0] for alias in aliases.values()})
        located = await self.idx.locate(
            base_keys, missing_ok=True, require_fully_committed=False
        )
        for new_sk, alias in aliases.items():
            base_sk, base_version = alias[0], int(alias[1])
            infos = located.get(base_sk)
            if not infos or self.core.committed_state(infos) != "committed":
                raise ValueError(
                    f"unchanged-watermark alias {new_sk!r} -> {base_sk!r}: "
                    "base bytes are not committed (GC'd, spilled out of the "
                    "index, or never landed) — readers could never serve "
                    "this key; publish a keyframe instead"
                )
            prev = rec["watermarks"].get(new_sk, 0)
            rec["watermarks"][new_sk] = max(prev, version)
            rec.setdefault("aliases", {})[new_sk] = (base_sk, base_version)
            if version == rec["version"]:
                rec["landing_ts"].setdefault(new_sk, now)

    @endpoint
    async def stream_mark_unchanged(
        self, key: str, version: int, aliases: dict
    ) -> None:
        """Watermark unchanged keys of a streamed publish whose fragment
        carried NO landed bytes at all (every key aliased): the standalone
        counterpart of ``notify_put_batch(unchanged=...)``. Safe as its own
        RPC — the aliased bytes committed with a previous version's notify,
        so there is no bytes-before-watermark window to close. Wakes
        ``wait_for_stream`` long-pollers like any landing."""
        rec = self._stream_rec(key, int(version))
        await self._record_unchanged(rec, aliases, int(version), time.time())
        self._touch_streams()
        cond = self._cond()
        async with cond:
            cond.notify_all()

    @endpoint
    async def stream_state(self, key: str) -> Optional[dict]:
        """Snapshot of a stream record ({"version", "sealed", "watermarks"})
        or None when ``key`` was never streamed (or its record was evicted
        / lost to a controller restart) — the acquire side's final
        consistency re-check reads this once after serving every layer."""
        rec = self._streams.get(key)
        if rec is None:
            return None
        return {
            "version": rec["version"],
            "sealed": rec["sealed"],
            "watermarks": dict(rec["watermarks"]),
            "aliases": dict(rec.get("aliases") or {}),
            "quant": rec.get("quant"),
            # Generation timeline (observability.timeline.reconstruct
            # folds these into publish-window / first-layer / per-
            # subscriber completion figures).
            "begin_ts": rec.get("begin_ts"),
            "seal_ts": rec.get("seal_ts"),
            "landing_ts": dict(rec.get("landing_ts") or {}),
            "acks": {
                sub: dict(ack) for sub, ack in (rec.get("acks") or {}).items()
            },
        }

    MAX_STREAM_ACKS = 64

    @endpoint
    async def stream_ack(
        self, key: str, version: int, subscriber: str
    ) -> None:
        """Record one subscriber's acquire completion on the stream's
        timeline (``{"version", "ts"}`` per subscriber; bounded — oldest
        entries evicted past MAX_STREAM_ACKS). Advisory: a missing record
        (evicted / never streamed) is a no-op, never an error — acks are
        telemetry, not protocol."""
        rec = self._streams.get(key)
        if rec is None:
            return
        acks = rec.setdefault("acks", {})
        if subscriber not in acks and len(acks) >= self.MAX_STREAM_ACKS:
            acks.pop(next(iter(acks)))
        acks[subscriber] = {"version": int(version), "ts": time.time()}

    @endpoint
    async def flight_record(self) -> list:
        """The controller process's flight-recorder ring (see
        observability/recorder.py); ts.flight_record() merges it with the
        client's and every volume's."""
        return obs_recorder.snapshot()

    @endpoint
    async def wait_for_stream(
        self,
        key: str,
        version: int,
        known: int = 0,
        timeout: Optional[float] = None,
        volume_id: Optional[str] = None,
    ) -> dict[str, Any]:
        """Long-poll for streamed-publish progress (notify-woken, no spin):
        blocks until ``key``'s stream has MORE than ``known`` store keys
        watermarked at ``version`` or newer, or version ``version`` seals,
        or a newer stream begins (superseded), or the record disappears.
        ``known = -1`` waits for the stream record to EXIST at all (a
        consumer arriving before the publisher's first layer).

        ``volume_id`` gates progress on the caller's RELAY copy: when the
        volume is a live member of the key's broadcast tree, a store key is
        only reported ready once it is indexed on that volume (the relay
        hop landed the host's local copy — the acquire then reads it
        zero-copy/locally instead of pulling from the origin), and
        ``sealed`` additionally waits for every watermarked key to land
        there. A volume that is not a relay member (or a channel with no
        relay) ignores the gate entirely — fail-safe to origin reads.

        Returns ``{"missing", "version", "sealed", "superseded", "ready",
        "watermarks"}`` — ``ready`` lists store keys whose watermark is at
        least ``version`` and ``watermarks`` carries their exact values
        (a reader treats > ``version`` as mixed-generation and restarts)."""
        import asyncio

        version = int(version)
        cond = self._cond()

        def _view() -> Optional[dict]:
            rec = self._streams.get(key)
            if rec is None:
                return None
            ready = {
                k: v for k, v in rec["watermarks"].items() if v >= version
            }
            sealed = rec["sealed"] >= version
            # Membership re-checked per wake: an unsubscribe/quarantine
            # mid-poll drops the gate instead of wedging the reader. The
            # gate covers only keys the run actually forwards — sharded
            # keys and layers published before the first member joined
            # pass ungated (point-to-point fail-safe, never a hang).
            run = (
                self._relay_gate_run(key, volume_id)
                if volume_id is not None
                else None
            )
            if run is not None:
                forwarded = run["metas"]
                # The run's landed sets are the gate (updated in the same
                # step a relay hop's copies are indexed): a sync predicate
                # can't fan out to the sharded index, and the landed view
                # is authoritative for exactly the keys the run forwards.
                landed = run["landed"].get(volume_id, ())
                local = {
                    k: v
                    for k, v in ready.items()
                    if k not in forwarded or k in landed
                }
                sealed = sealed and len(local) == len(ready)
                ready = local
            rec_aliases = rec.get("aliases") or {}
            return {
                "missing": False,
                "version": rec["version"],
                "sealed": sealed,
                "superseded": rec["version"] > version,
                "ready": sorted(ready),
                "watermarks": ready,
                # Unchanged-watermark aliases for the ready keys: the
                # reader serves these from the aliased (v-1) bytes — or
                # from its own accumulated state with zero re-transfer.
                "aliases": {
                    k: rec_aliases[k] for k in ready if k in rec_aliases
                },
                "quant": rec.get("quant"),
            }

        def _changed() -> bool:
            view = _view()
            if view is None:
                return known >= 0  # absent record wakes established readers
            if known < 0:
                return True  # the record exists: that is what was awaited
            return (
                len(view["ready"]) > known
                or view["sealed"]
                or view["superseded"]
            )

        async with cond:
            try:
                await asyncio.wait_for(cond.wait_for(_changed), timeout)
            except asyncio.TimeoutError:
                raise TimeoutError(
                    f"wait_for_stream({key!r}, v{version}) timed out after "
                    f"{timeout}s with {known} key(s) already served"
                ) from None
            view = _view()
            if view is None:
                return {
                    "missing": True,
                    "version": 0,
                    "sealed": False,
                    "superseded": False,
                    "ready": [],
                    "watermarks": {},
                    "aliases": {},
                    "quant": None,
                }
            return view

    # ---- broadcast relay distribution (torchstore_tpu/relay.py) ----------
    #
    # One published weight_channel version -> one RUN: the set of member
    # volumes (one per subscribed host), a tree rooted at the origin volume
    # (root out-degree 1 — O(1) trainer-host egress), and one forwarder
    # task per edge that pulls freshly watermarked layers volume-to-volume
    # (``pull_from(relay=True)``, bulk/striped) the moment the parent holds
    # them — interior hops forward per LAYER, never per version, so deep
    # trees add per-hop latency only. Children keep their landed-key sets
    # across re-parenting, so an orphaned subtree resumes from its last
    # landed watermark and never re-pulls layers it already holds.

    MAX_RELAY_RUNS = 16

    def _relay_channel_of(self, stream_key: str) -> Optional[str]:
        """The subscribed channel a stream key publishes under (stream keys
        are ``{channel}/v{n}``), or None when no channel matches."""
        for channel in self._relay_channels:
            if stream_key.startswith(channel + "/v"):
                seg = stream_key[len(channel) + 2 :]
                if seg.isdigit():
                    return channel
        return None

    def _relay_healthy_members(self, channel: str) -> list[str]:
        ch = self._relay_channels.get(channel)
        if ch is None:
            return []
        quarantined = self._quarantined_ids()
        return [
            vid
            for vid, subs in ch["members"].items()
            if subs > 0 and vid in self.volume_refs and vid not in quarantined
        ]

    def _relay_gate_run(
        self, stream_key: str, volume_id: str
    ) -> Optional[dict]:
        """The live relay run gating ``volume_id``'s streamed reads of
        ``stream_key`` — None when the volume is not a subscribed member,
        is quarantined, or no fan-out is running (fail-safe: ungated
        readers serve from the origin volumes)."""
        channel = self._relay_channel_of(stream_key)
        if channel is None:
            return None
        ch = self._relay_channels.get(channel)
        if not ch or ch["members"].get(volume_id, 0) <= 0:
            return None
        if volume_id in self._quarantined_ids():
            return None
        run = self._relay_runs.get(stream_key)
        if run is None or run.get("dead"):
            return None
        if volume_id != run["root"] and volume_id not in run["parents"]:
            # Member, but not in THIS run's tree — excluded at run
            # creation (quarantined then) or dropped mid-run and later
            # reinstated (reinstatement does not re-attach to live runs;
            # the next version's tree picks it back up). Gating it would
            # wedge the reader on copies no forwarder will ever land.
            return None
        return run

    async def _relay_notify(self, run: dict) -> None:
        async with run["cond"]:
            run["cond"].notify_all()

    def _relay_new_run(
        self,
        stream_key: str,
        channel: str,
        version: int,
        volume_ids: list[str],
    ) -> Optional[dict]:
        import asyncio

        members = self._relay_healthy_members(channel)
        root = str(volume_ids[0])
        parents = relay_mod.build_tree(
            root,
            members,
            self._relay_fanout,
            prefer=self._relay_prefer.get(channel),
        )
        if not parents:
            return None  # nobody to relay to (or origin is the only member)
        while len(self._relay_runs) >= self.MAX_RELAY_RUNS:
            victim = next(
                (
                    k
                    for k, r in self._relay_runs.items()
                    if r.get("dead")
                    or (
                        r["sealed"]
                        and all(
                            r["landed"].get(c, set()) >= set(r["metas"])
                            for c in r["parents"]
                        )
                    )
                ),
                next(iter(self._relay_runs)),
            )
            self._relay_stop_run(victim)
        run = {
            "channel": channel,
            "version": int(version),
            "root": root,
            "parents": parents,
            "landed": {root: set()},
            "metas": {},
            "sealed": False,
            "cond": asyncio.Condition(),
            "tasks": {},
            "failing": {},
        }
        self._relay_runs[stream_key] = run
        obs_recorder.record(
            "stream",
            f"relay_begin/{channel}",
            key=stream_key,
            root=root,
            members=len(parents),
        )
        logger.info(
            "relay %s: broadcasting v%d from volume %s to %d member(s) "
            "(fanout %d)",
            stream_key,
            version,
            root,
            len(parents),
            self._relay_fanout,
        )
        return run

    async def _relay_on_landing(
        self,
        stream_key: str,
        version: int,
        metas: list[Request],
        volume_ids: list[str],
    ) -> None:
        """Watermarked keys just landed on the origin volume(s): seed them
        into the key's relay run (creating it on the first layer of the
        stream's CURRENT version) and wake the edge forwarders."""
        channel = self._relay_channel_of(stream_key)
        if channel is None:
            return
        run = self._relay_runs.get(stream_key)
        if run is None:
            rec = self._streams.get(stream_key)
            if rec is None or int(version) != rec["version"]:
                return  # superseded late notify: nothing to fan out
            run = self._relay_new_run(
                stream_key, channel, version, [str(v) for v in volume_ids]
            )
            if run is None:
                return
        if run.get("dead") or int(version) != run["version"]:
            return
        if run["root"] not in {str(v) for v in volume_ids}:
            # The batch landed off-root (a put failover re-routed it):
            # the root's forwarders could never source these keys, so
            # keeping them OUT of run["metas"] leaves them ungated —
            # relay readers fetch them point-to-point instead of
            # stalling on copies the tree cannot deliver.
            return
        added = False
        for meta in metas:
            if meta.tensor_slice is not None:
                # Relay forwards full-tensor/object keys; sharded keys stay
                # point-to-point (per-coord forwarding is not implemented —
                # readers of those keys are simply not gated on them).
                if not run.get("warned_sharded"):
                    run["warned_sharded"] = True
                    logger.warning(
                        "relay %s: sharded key %r (and siblings) stay "
                        "point-to-point",
                        stream_key,
                        meta.key,
                    )
                continue
            run["metas"][meta.key] = meta
            for vid in volume_ids:
                run["landed"].setdefault(str(vid), set()).add(meta.key)
            added = True
        if added:
            self._relay_sync_tasks(run)
            await self._relay_notify(run)

    async def _relay_on_seal(self, stream_key: str, version: int) -> None:
        """The publisher sealed: mark the run terminal and forward the
        MAPPING commit marker too, so leaf hosts finalize their acquire
        against a LOCAL marker copy instead of a point-to-point get."""
        run = self._relay_runs.get(stream_key)
        if run is None or run.get("dead") or int(version) != run["version"]:
            return
        marker_key = f"{stream_key}/MAPPING"
        infos = await self.idx.get_entry(marker_key)
        if infos:
            run["metas"][marker_key] = Request(key=marker_key, is_object=True)
            for vid in infos:
                run["landed"].setdefault(str(vid), set()).add(marker_key)
        run["sealed"] = True
        self._relay_sync_tasks(run)
        self._touch_streams()
        await self._relay_notify(run)

    def _relay_sync_tasks(self, run: dict) -> None:
        for child in list(run["parents"]):
            task = run["tasks"].get(child)
            if task is None or task.done():
                run["tasks"][child] = spawn_logged(
                    self._relay_edge(run, child),
                    name="controller.relay_edge",
                    tasks=self._relay_tasks,
                    log=logger,
                )

    async def _relay_edge(self, run: dict, child: str) -> None:
        """One tree edge's forwarder: pull batches of keys the parent holds
        and this child doesn't, index the copies, wake gated readers and
        the child's own children. Lives until the run completes for this
        child, the child leaves the tree, or the run dies."""
        import asyncio

        from torchstore_tpu.config import RetryPolicy

        stream_key = next(
            (k for k, r in self._relay_runs.items() if r is run), "?"
        )
        child_ref = self.volume_refs.get(child)
        if child_ref is None:
            return
        # Edge failures heal by RE-PARENTING, not by giving up, so the
        # unified policy supplies the backoff curve only — capped so the
        # re-parent window is actually reached within a few attempts —
        # while the supervised loop itself runs until the run completes.
        policy = RetryPolicy.from_env()
        streak = 0
        while True:
            if run.get("dead") or child in self._quarantined_ids():
                return
            parent = run["parents"].get(child)
            if parent is None:
                return  # re-parented away / unsubscribed / quarantined
            have = run["landed"].setdefault(child, set())
            avail = run["landed"].get(parent, set())
            pending = sorted(
                k for k in avail if k not in have and k in run["metas"]
            )
            if not pending:
                if run["sealed"] and have >= set(run["metas"]):
                    return  # this subtree root is fully served
                async with run["cond"]:
                    try:
                        await asyncio.wait_for(run["cond"].wait(), timeout=0.5)
                    except asyncio.TimeoutError:
                        pass
                continue
            # Bounded batches, same cadence as auto-repair: one pull RPC
            # moves up to 64 keys (striped on the bulk rung when the
            # payload crosses the stripe threshold).
            batch = pending[:64]
            metas = [run["metas"][k] for k in batch]
            src_ref = self.volume_refs.get(parent)
            try:
                if src_ref is None:
                    raise RuntimeError(f"relay parent {parent!r} has no ref")
                result = await child_ref.pull_from.call_one(
                    src_ref,
                    metas,
                    src_hostname=self.volume_hostnames.get(parent, ""),
                    src_volume=parent,
                    relay=True,
                )
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # noqa: BLE001 - edge failures heal
                # by re-parenting, never by surfacing
                now = time.monotonic()
                first = run["failing"].setdefault(child, now)
                if now - first >= self._relay_reparent_s:
                    run["failing"].pop(child, None)
                    await self._relay_reparent_edge(
                        run, stream_key, child, str(exc)
                    )
                await asyncio.sleep(
                    min(
                        policy.backoff(streak),
                        max(0.05, self._relay_reparent_s / 4),
                    )
                )
                streak += 1
                continue
            streak = 0
            run["failing"].pop(child, None)
            gens = result.get("write_gens", {})
            # Index the pulled copies through the authority (the owning
            # shard, when sharded): new replica placement is structural
            # (same rule as notify_put_batch) and the merge's bump wakes
            # per-key waiters; keys deleted mid-run are never re-indexed.
            touched = await self.idx.merge_copies(child, metas, gens)
            have.update(batch)
            # The child's landed set moved: republish the stamped stream
            # snapshot so relay-gated ONE-SIDED pollers (local segment or
            # cross-host mirror) see the landing without an RPC.
            self._touch_streams()
            _RELAY_FORWARDED.inc(len(batch), channel=run["channel"])
            if touched:
                # Relay-gated wait_for_stream long-pollers wait on THIS
                # process's condition; wake them now that the child's
                # landed set moved (the shard's own bump can't reach it).
                cond = self._cond()
                async with cond:
                    cond.notify_all()
            await self._relay_notify(run)

    async def _relay_reparent_edge(
        self, run: dict, stream_key: str, child: str, reason: str
    ) -> None:
        """An edge's parent kept failing past the re-parent window: move
        ``child`` under the nearest healthy ancestor. Its landed set
        survives, so it resumes from its last landed watermark."""
        parents = run["parents"]
        old = parents.get(child)
        if old is None:
            return
        down = {old} | self._quarantined_ids()
        anc = relay_mod.healthy_ancestor(parents, run["root"], old, down)
        if anc == old:
            return
        parents[child] = anc
        ch = self._relay_channels.get(run["channel"])
        if ch is not None:
            ch["epoch"] += 1
        _RELAY_REPARENTS.inc(channel=run["channel"])
        obs_recorder.record(
            "health",
            f"relay_reparent/{run['channel']}",
            child=child,
            old_parent=old,
            new_parent=anc,
            key=stream_key,
            reason=reason[:120],
        )
        logger.warning(
            "relay %s: re-parented %s from %s onto ancestor %s (%s); "
            "resuming from %d landed key(s)",
            stream_key,
            child,
            old,
            anc,
            reason,
            len(run["landed"].get(child, ())),
        )
        await self._relay_notify(run)

    async def _relay_on_quarantine(self, vid: str) -> None:
        """The health supervisor quarantined ``vid``: every live run drops
        it from its tree NOW — orphaned subtrees re-attach to a healthy
        ancestor and resume from their last landed watermark — and future
        trees exclude it until reinstated."""
        touched_channels = set()
        for stream_key, run in list(self._relay_runs.items()):
            if run.get("dead"):
                continue
            parents = run["parents"]
            if vid not in parents and vid not in set(parents.values()):
                continue
            new_parents, moved = relay_mod.reparent(
                parents, run["root"], {vid}
            )
            parents.clear()
            parents.update(new_parents)
            task = run["tasks"].pop(vid, None)
            if task is not None:
                task.cancel()
            touched_channels.add(run["channel"])
            for child, (old, new) in moved.items():
                _RELAY_REPARENTS.inc(channel=run["channel"])
                obs_recorder.record(
                    "health",
                    f"relay_reparent/{run['channel']}",
                    child=child,
                    old_parent=old,
                    new_parent=new,
                    key=stream_key,
                    reason=f"quarantine:{vid}",
                )
                logger.warning(
                    "relay %s: quarantine of %s re-parented %s onto %s; "
                    "resuming from %d landed key(s)",
                    stream_key,
                    vid,
                    child,
                    new,
                    len(run["landed"].get(child, ())),
                )
            self._relay_sync_tasks(run)
            await self._relay_notify(run)
        for channel in touched_channels:
            ch = self._relay_channels.get(channel)
            if ch is not None:
                ch["epoch"] += 1

    def _relay_stop_run(self, stream_key: str) -> None:
        run = self._relay_runs.pop(stream_key, None)
        if run is None:
            return
        run["dead"] = True
        for task in run["tasks"].values():
            task.cancel()
        run["tasks"].clear()

    async def _relay_join_live_runs(self, channel: str) -> None:
        """A member joined mid-run: attach every NEW member to live runs of
        the channel per the fresh tree, WITHOUT moving existing children
        (mid-version stability beats topological optimality; the next
        version's run rebuilds the whole tree anyway)."""
        members = self._relay_healthy_members(channel)
        for run in self._relay_runs.values():
            if run["channel"] != channel or run.get("dead"):
                continue
            fresh = relay_mod.build_tree(
                run["root"],
                members,
                self._relay_fanout,
                prefer=self._relay_prefer.get(channel),
            )
            added = False
            for child, parent in fresh.items():
                if child not in run["parents"]:
                    run["parents"][child] = parent
                    added = True
            if added:
                self._relay_sync_tasks(run)
                await self._relay_notify(run)

    @endpoint
    async def relay_subscribe(
        self, channel: str, host: str, volume_id: Optional[str] = None
    ) -> dict[str, Any]:
        """A generator (fleet) on ``host`` joins ``channel``'s broadcast
        tree. The controller assigns the host's relay volume — the volume
        co-located with ``host`` when one exists, else a stable healthy
        pick — or adopts an explicit ``volume_id`` (tests/benches emulating
        multi-host fleets). All co-located subscribers share one member
        (refcounted): each HOST lands exactly one copy. Members joining
        mid-version attach to live runs immediately. Returns
        ``{"volume_id", "epoch", "fanout"}``."""
        if not channel:
            raise ValueError("relay_subscribe requires a channel name")
        if not self._relay_enabled:
            # The CONTROLLER process is where one setting is actually
            # fleet-wide: clients launched without the knob still get a
            # no-op subscription (same shape the client-side check
            # returns), so no tree is ever built.
            return {
                "volume_id": None,
                "disabled": True,
                "epoch": 0,
                "fanout": self._relay_fanout,
            }
        if volume_id is not None:
            volume_id = str(volume_id)
            if volume_id not in self.volume_refs:
                raise ValueError(
                    f"unknown relay volume {volume_id!r}; have "
                    f"{sorted(self.volume_refs)}"
                )
        else:
            quarantined = self._quarantined_ids()
            healthy = sorted(
                v for v in self.volume_refs if v not in quarantined
            )
            if not healthy:
                raise RuntimeError("no healthy volume to host a relay copy")
            same_host = [
                v for v in healthy if self.volume_hostnames.get(v) == host
            ]
            if same_host:
                volume_id = same_host[0]
            else:
                import zlib

                volume_id = healthy[
                    zlib.crc32(host.encode("utf-8", "replace")) % len(healthy)
                ]
        ch = self._relay_channels.setdefault(
            channel, {"members": {}, "epoch": 0}
        )
        ch["members"][volume_id] = ch["members"].get(volume_id, 0) + 1
        ch["epoch"] += 1
        await self._relay_join_live_runs(channel)
        obs_recorder.record(
            "stream",
            f"relay_subscribe/{channel}",
            host=host,
            volume=volume_id,
        )
        return {
            "volume_id": volume_id,
            "epoch": ch["epoch"],
            "fanout": self._relay_fanout,
        }

    @endpoint
    async def relay_unsubscribe(
        self, channel: str, volume_id: str
    ) -> dict[str, Any]:
        """Drop one subscription from ``channel``'s member on
        ``volume_id``. The last subscriber leaving a host removes the
        member: live runs re-parent its children onto its parent and stop
        forwarding to it (already-landed copies stay until version GC).
        Idempotent."""
        ch = self._relay_channels.get(channel)
        if ch is None:
            return {"members": 0}
        volume_id = str(volume_id)
        subs = ch["members"].get(volume_id, 0)
        if subs > 1:
            ch["members"][volume_id] = subs - 1
        elif subs == 1:
            ch["members"].pop(volume_id, None)
            for stream_key, run in list(self._relay_runs.items()):
                if run["channel"] != channel or run.get("dead"):
                    continue
                parents = run["parents"]
                if volume_id not in parents and volume_id not in set(
                    parents.values()
                ):
                    continue
                new_parents, _moved = relay_mod.reparent(
                    parents, run["root"], {volume_id}
                )
                parents.clear()
                parents.update(new_parents)
                task = run["tasks"].pop(volume_id, None)
                if task is not None:
                    task.cancel()
                self._relay_sync_tasks(run)
                await self._relay_notify(run)
        ch["epoch"] += 1
        if not ch["members"]:
            self._relay_channels.pop(channel, None)
        obs_recorder.record(
            "stream", f"relay_unsubscribe/{channel}", volume=volume_id
        )
        return {"members": ch["members"].get(volume_id, 0) if ch else 0}

    @endpoint
    async def relay_topology(self) -> dict[str, Any]:
        """Operator view of every channel's broadcast shape: members (with
        subscriber refcounts), topology epoch, configured fanout, and each
        live run's tree + per-member landed progress — ``ts.relay_topology()``
        surfaces this without reading controller state."""
        out: dict[str, Any] = {}
        for channel, ch in self._relay_channels.items():
            runs: dict[str, Any] = {}
            for stream_key, run in self._relay_runs.items():
                if run["channel"] != channel:
                    continue
                runs[stream_key] = {
                    "version": run["version"],
                    "root": run["root"],
                    "parents": dict(run["parents"]),
                    "sealed": bool(run["sealed"]),
                    "keys": len(run["metas"]),
                    "landed": {
                        vid: len(keys) for vid, keys in run["landed"].items()
                    },
                }
            out[channel] = {
                "members": dict(ch["members"]),
                "epoch": ch["epoch"],
                "fanout": self._relay_fanout,
                "runs": runs,
            }
        return out

    # ---- tiered capacity & multi-version serving (torchstore_tpu/tiering)

    def _start_tier_sweeper(self) -> None:
        """(Re)start the background tier sweeper — called from init();
        idempotent across re-inits. Off unless tiering is enabled AND the
        interval is positive (manual ``tier_sweep`` still serves)."""
        if self._tier_task is not None:
            self._tier_task.cancel()
            self._tier_task = None
        if not self._tier_enabled or self._tier_interval <= 0:
            return
        self._tier_task = spawn_logged(
            self._tier_loop(),
            name="controller.tier_sweep",
            tasks=self._health_tasks,
            log=logger,
        )

    async def _tier_loop(self) -> None:
        import asyncio

        while True:
            await asyncio.sleep(self._tier_interval)
            try:
                await self._tier_sweep_once()
            except Exception:  # noqa: BLE001 - one bad sweep must not
                # kill the sweeper (volumes may be mid-repair)
                logger.exception("tier sweep failed; retrying next round")

    async def _tier_sweep_once(self) -> dict[str, dict]:
        """One fleet spill pass: push the current pinned groups to every
        healthy volume's spill writer, fold the reported spill/fault-in
        transitions into the index's tier states, and leave a flight-
        recorder breadcrumb per demotion batch. Tier flips are metadata
        only — NOT structural: cached plans keep serving the resident hot
        set, and readers of demoted keys fall back through the normal
        ladder (which is where the fault-in lives)."""
        self._leases.expire()
        pins = sorted(self._leases.pinned_groups())
        quarantined = self._quarantined_ids()
        reports: dict[str, dict] = {}
        for vid, ref in list(self.volume_refs.items()):
            if vid in quarantined:
                continue
            try:
                rep = await ref.tier_sweep.call_one(pins)
            except Exception as exc:  # noqa: BLE001 - a dead/wedged volume
                # is the supervisor's problem, not the sweeper's
                reports[vid] = {"error": f"{type(exc).__name__}: {exc}"}
                continue
            if not rep.get("enabled"):
                reports[vid] = rep
                continue
            await self.idx.set_tiers(
                vid,
                list(rep.get("spilled", ())),
                list(rep.get("fault_ins", ())),
            )
            if rep.get("spilled"):
                obs_recorder.record(
                    "tier",
                    f"sweep/{vid}",
                    spilled=len(rep["spilled"]),
                    resident_bytes=rep.get("resident_bytes"),
                    spilled_bytes=rep.get("spilled_bytes"),
                    pins=len(pins),
                )
            reports[vid] = {
                "spilled": len(rep.get("spilled", ())),
                "fault_ins": len(rep.get("fault_ins", ())),
                "resident_bytes": rep.get("resident_bytes"),
                "spilled_bytes": rep.get("spilled_bytes"),
                "spilled_keys": rep.get("spilled_keys"),
            }
        return reports

    @endpoint
    async def tier_sweep(self) -> dict[str, dict]:
        """Run one fleet spill pass NOW (``ts.tier_sweep()``) — the
        deterministic entry the benches/tests use instead of waiting out
        the background interval. Returns a per-volume summary."""
        return await self._tier_sweep_once()

    # ---- control plane (torchstore_tpu/control) --------------------------

    def _start_control_loop(self) -> None:
        """(Re)start the policy engine's reconcile loop — called from
        init(); idempotent across re-inits. Off unless the interval is
        positive (``ts.control_plan()``/``ts.rebalance()`` still serve)."""
        if self._control_task is not None:
            self._control_task.cancel()
            self._control_task = None
        if self._control_interval <= 0:
            return
        self._control_task = spawn_logged(
            self._control_loop(),
            name="controller.control_reconcile",
            tasks=self._health_tasks,
            log=logger,
        )

    async def _control_loop(self) -> None:
        import asyncio

        while True:
            await asyncio.sleep(self._control_interval)
            try:
                await self._control_engine.reconcile(trigger="interval")
            except Exception:  # noqa: BLE001 - one bad round must not
                # kill the engine (volumes may be mid-repair/reshard)
                logger.exception("control reconcile failed; retrying next round")

    @endpoint
    async def control_plan(
        self,
        traffic: Optional[dict] = None,
        overload: Optional[dict] = None,
    ) -> dict[str, Any]:
        """Dry run (``ts.control_plan()``): the actions the policy engine
        WOULD take on a fresh telemetry snapshot, applying nothing. The
        caller may feed its fleet-wide traffic matrix and SLO overload
        view — signals only clients can fully assemble."""
        return await self._control_engine.plan(
            traffic=traffic, overload=overload
        )

    @endpoint
    async def control_reconcile(
        self,
        traffic: Optional[dict] = None,
        overload: Optional[dict] = None,
    ) -> dict[str, Any]:
        """One reconcile round NOW (``ts.rebalance()`` manual trigger):
        snapshot, solve, apply, audit. Safe alongside the periodic loop —
        actions cool down by subject, so back-to-back rounds converge."""
        return await self._control_engine.reconcile(
            traffic=traffic, overload=overload, trigger="manual"
        )

    # ---- autoscale plane (torchstore_tpu/autoscale) ----------------------

    def _start_autoscale_loop(self) -> None:
        """(Re)start the autoscale engine's reconcile loop — called from
        init(); idempotent across re-inits. Off unless the interval is
        positive (``ts.autoscale_plan()``/``ts.autoscale()`` still
        serve)."""
        if self._autoscale_task is not None:
            self._autoscale_task.cancel()
            self._autoscale_task = None
        if self._autoscale_interval <= 0:
            return
        self._autoscale_task = spawn_logged(
            self._autoscale_loop(),
            name="controller.autoscale_reconcile",
            tasks=self._health_tasks,
            log=logger,
        )

    async def _autoscale_loop(self) -> None:
        import asyncio

        while True:
            await asyncio.sleep(self._autoscale_interval)
            try:
                await self._autoscale_engine.reconcile(trigger="interval")
            except Exception:  # noqa: BLE001 - one bad round must not
                # kill the engine (volumes may be mid-repair/drain)
                logger.exception(
                    "autoscale reconcile failed; retrying next round"
                )

    @endpoint
    async def autoscale_plan(
        self,
        traffic: Optional[dict] = None,
        overload: Optional[dict] = None,
    ) -> dict[str, Any]:
        """Dry run (``ts.autoscale_plan()``): the scale actions the
        engine WOULD take on a fresh fleet snapshot, applying nothing."""
        return await self._autoscale_engine.plan(
            traffic=traffic, overload=overload
        )

    @endpoint
    async def autoscale_reconcile(
        self,
        traffic: Optional[dict] = None,
        overload: Optional[dict] = None,
    ) -> dict[str, Any]:
        """One autoscale round NOW (``ts.autoscale()`` manual trigger):
        snapshot, solve, apply drains/retires/demotions inline, surface
        scale-out as a deferred decision the caller executes (spawn +
        ``attach_volume``). Safe alongside the periodic loop — actions
        cool down by subject."""
        return await self._autoscale_engine.reconcile(
            traffic=traffic, overload=overload, trigger="manual"
        )

    @endpoint
    async def blob_checkpoint(self) -> dict[str, Any]:
        """Archive every live volume's committed payloads into the blob
        tier and write the durable fleet manifest (``ts.blob_checkpoint()``
        — the scale-to-zero prerequisite)."""
        return await self._autoscale_engine.checkpoint()

    @endpoint
    async def attach_volume(
        self, volume_id: str, new_ref: ActorRef, hostname: str
    ) -> dict[str, Any]:
        """Adopt a freshly spawned volume into the live fleet (scale-out:
        ``ts.autoscale()`` spawns, this attaches). The volume starts
        empty and healthy; shards learn its ref BEFORE the epoch bump so
        no placement can route to a volume a shard can't reach."""
        if volume_id in self.volume_refs:
            raise ValueError(f"volume {volume_id!r} already attached")
        self.volume_refs[volume_id] = new_ref
        self.volume_hostnames[volume_id] = hostname
        self._vol_health[volume_id] = {"state": "ok", "misses": 0, "oks": 0}
        _VOLUME_HEALTH.set(1, volume=volume_id)
        if self._shard_refs:
            import asyncio

            await asyncio.gather(
                *(
                    ref.update_volume_ref.call_one(
                        volume_id, new_ref, hostname
                    )
                    for ref in self._shard_refs
                )
            )
        self._bump_epoch()
        self._push_health()
        self._autoscale_engine.publish_fleet_gauges()
        obs_recorder.record("health", f"attached/{volume_id}")
        return {"volumes": len(self.volume_refs)}

    def mark_draining(self, volume_id: str) -> bool:
        """Flag a volume as draining (autoscale scale-in): clients see
        ``health == "draining"`` in get_volume_map and route NEW
        placements around it while reads keep serving the resident keys
        until migration empties it. Returns True when newly marked."""
        if volume_id in self._draining:
            return False
        self._draining.add(volume_id)
        h = self._vol_health.setdefault(
            volume_id, {"state": "ok", "misses": 0, "oks": 0}
        )
        if h["state"] != "quarantined":
            h["state"] = "draining"
        _VOLUME_HEALTH.set(0.75, volume=volume_id)
        obs_recorder.record("health", f"draining/{volume_id}")
        self._bump_epoch()
        self._push_health()
        self._autoscale_engine.publish_fleet_gauges()
        return True

    def clear_draining(self, volume_id: str) -> None:
        """Abandon a drain (volume vanished or scale-in reversed): the
        volume rejoins normal placement if still healthy."""
        if volume_id not in self._draining:
            return
        self._draining.discard(volume_id)
        h = self._vol_health.get(volume_id)
        if h is not None and h["state"] == "draining":
            h["state"] = "ok"
            _VOLUME_HEALTH.set(1, volume=volume_id)
        self._bump_epoch()
        self._push_health()
        self._autoscale_engine.publish_fleet_gauges()

    async def drop_volume(self, volume_id: str) -> None:
        """Remove a retired volume from every fleet map (the retire
        actuator already detached its — empty — index slice). Relay
        trees re-shape around it exactly as they do on quarantine."""
        self.volume_refs.pop(volume_id, None)
        self.volume_hostnames.pop(volume_id, None)
        self._vol_health.pop(volume_id, None)
        self._draining.discard(volume_id)
        await self._relay_on_quarantine(volume_id)
        self._bump_epoch()
        self._push_health()
        self._autoscale_engine.publish_fleet_gauges()
        obs_recorder.record("health", f"retired/{volume_id}")

    async def _reshard_wait(self) -> None:
        gate = self._reshard_gate
        if gate is not None:
            await gate.wait()

    @endpoint
    async def reshard(
        self, coordinator: ActorRef, shard_refs: list[ActorRef]
    ) -> dict[str, Any]:
        """Runtime elastic reshard of the metadata plane: move the whole
        index onto a NEW shard mesh (``ts.rebalance(shards=N)`` spawns it;
        1 -> N, N -> M, and N -> 1 merges all route here) with zero lost
        keys and zero failed client ops.

        Protocol (freeze-via-park): (1) FREEZE the current authority —
        sharded mutations park on their shard, unsharded ones park on the
        coordinator gate; reads keep serving the frozen index throughout.
        (2) EXPORT every (volume, meta, write_gen) entry. (3) INIT the new
        mesh and REPLAY the export through ``reindex`` (generation seeding
        wakes long-pollers into a resync instead of blocking them).
        (4) SWAP ``self.idx`` + the advertised topology, bump the
        placement epoch (one bump: stamped readers re-confirm against the
        new mesh). (5) RETIRE the old shards — their parked mutations wake
        raising the stale-topology error the router answers with a
        topology reload + one retry. A failure before the swap thaws the
        old authority and re-raises: the store keeps serving exactly as
        before."""
        import asyncio

        from torchstore_tpu.metadata.shards import RemoteIndex

        old_refs = list(self._shard_refs)
        n_new = len(shard_refs)
        # Phase 1+2: freeze the current authority and export its entries.
        if old_refs:
            await asyncio.gather(
                *(ref.shard_freeze.call_one() for ref in old_refs)
            )
            parts = await asyncio.gather(
                *(ref.export_entries.call_one() for ref in old_refs)
            )
            entries = [e for part in parts for e in part]
        else:
            self._reshard_gate = asyncio.Event()
            entries = self.core.export_entries()
        exported_keys = len({meta.key for _, meta, _ in entries})
        try:
            quarantined = sorted(self._quarantined_ids())
            if n_new <= 1:
                # Merge back to the coordinator-hosted core: a fresh core
                # adopts the export (the idle core may hold a pre-shard
                # index — replaying into it would resurrect stale entries).
                old_writer = self.core.meta_writer
                if old_writer is not None:
                    old_writer.close()
                self.core.teardown()
                self.core = IndexCore(self)
                count = await self.core.reindex(entries)
                from torchstore_tpu.metadata import stamped as stamped_mod

                if stamped_mod.enabled():
                    self.core.meta_writer = stamped_mod.MetaStampWriter(
                        self.core.meta_payload
                    )
                    self.core.meta_writer.mark_dirty()
                self.idx = self.core
                self._shard_refs = []
                self._shard_stamped = []
            else:
                stamped = []
                for i, ref in enumerate(shard_refs):
                    res = await ref.shard_init.call_one(
                        i,
                        n_new,
                        coordinator,
                        self.volume_refs,
                        self.volume_hostnames,
                        quarantined,
                    )
                    stamped.append(res.get("stamped"))
                new_idx = RemoteIndex(list(shard_refs))
                count = await new_idx.reindex(entries)
                self._shard_refs = list(shard_refs)
                self._shard_stamped = stamped
                self.idx = new_idx
                if self.core.meta_writer is not None:
                    # The coordinator's own index segment retires with its
                    # authority; one-sided readers fall back and reload.
                    self.core.meta_writer.close()
                    self.core.meta_writer = None
        except BaseException:
            # Thaw: the old authority resumes exactly as frozen — parked
            # mutations proceed against it, nothing was swapped.
            if old_refs:
                await asyncio.gather(
                    *(ref.shard_thaw.call_one() for ref in old_refs),
                    return_exceptions=True,
                )
            elif self._reshard_gate is not None:
                self._reshard_gate.set()
                self._reshard_gate = None
            raise
        # Phase 4: one epoch bump — every cached plan/location re-resolves
        # and every stamped reader re-confirms against the new topology.
        self._bump_epoch()
        # Phase 5: retire the old authority. Parked mutations wake into
        # the stale-topology raise the router retries through.
        if old_refs:
            await asyncio.gather(
                *(ref.shard_retire.call_one() for ref in old_refs),
                return_exceptions=True,
            )
        if self._reshard_gate is not None:
            self._reshard_gate.set()
            self._reshard_gate = None
        obs_recorder.record(
            "decision",
            "control/reshard_applied",
            shards=max(1, n_new),
            was=len(old_refs) or 1,
            keys=exported_keys,
            reindexed=count,
            epoch=self._placement_epoch,
        )
        logger.warning(
            "metadata plane resharded %d -> %d shard(s): %d key(s) "
            "replayed, placement epoch %d",
            len(old_refs) or 1,
            max(1, n_new),
            exported_keys,
            self._placement_epoch,
        )
        return {
            "shards": max(1, n_new),
            "was": len(old_refs) or 1,
            "keys": exported_keys,
            "reindexed": count,
            "epoch": self._placement_epoch,
        }

    @endpoint
    async def lease_acquire(
        self,
        cohort: str,
        channel: str,
        version: int,
        ttl_s: Optional[float] = None,
    ) -> dict:
        """Pin (channel, version) for a cohort (TTL'd — renew to keep).
        Returns the lease description; carry its ``lease_id`` to
        renew/release. Pinning a version whose keys are already gone is
        allowed (pre-pinning before a publish) but reported via
        ``resident_keys=0`` so the caller can fail fast if it expected
        retained data."""
        lease = self._leases.acquire(cohort, channel, version, ttl_s)
        # Segment-bounded prefix: "chan/v1" matches "chan/v1/..." but
        # never "chan/v10/..." (trie path-wise semantics).
        prefix = tiering.group_key(channel, version)
        lease["resident_keys"] = await self.idx.count_prefix(prefix)
        return lease

    @endpoint
    async def lease_renew(
        self, lease_id: str, ttl_s: Optional[float] = None
    ) -> dict:
        return self._leases.renew(lease_id, ttl_s)

    @endpoint
    async def lease_release(self, lease_id: str) -> bool:
        return self._leases.release(lease_id)

    @endpoint
    async def lease_list(
        self, channel: Optional[str] = None
    ) -> dict[str, dict[int, list[str]]]:
        """{channel: {version: [cohort, ...]}} over live leases — what
        ``WeightPublisher._gc`` consults before reaping old versions."""
        return self._leases.pins(channel)

    @endpoint
    async def version_catalog(
        self, channel: Optional[str] = None
    ) -> dict[str, dict[int, dict]]:
        """Per-channel version inventory: for every ``{channel}/v{n}``
        group in the index, its key count, logical bytes (one replica's),
        replica volumes, tier split (a key counts resident while ANY
        replica still serves it from memory), and the live leases pinning
        it (including pre-pins on versions with no keys yet)."""
        self._leases.expire()
        # The per-key walk lives with the index (IndexCore.catalog; the
        # sharded authority merges per-shard slices); leases are
        # coordinator state and fold in here.
        out = await self.idx.catalog(channel)

        def _rec(chan: str, ver: int) -> dict:
            return out.setdefault(chan, {}).setdefault(
                ver,
                {
                    "keys": 0,
                    "bytes": 0,
                    "resident_keys": 0,
                    "spilled_keys": 0,
                    "volumes": set(),
                    "leases": [],
                },
            )

        for lease in self._leases.describe():
            if channel is not None and lease["channel"] != channel:
                continue
            _rec(lease["channel"], lease["version"])["leases"].append(lease)
        for versions in out.values():
            for rec in versions.values():
                rec["volumes"] = sorted(rec["volumes"])
        return out

    # ---- prewarm capacity reservations -----------------------------------

    def _expire_prewarm(self) -> None:
        import time

        now = time.monotonic()
        for rid in [
            r
            for r, (expiry, _) in self._prewarm_reservations.items()
            if expiry <= now
        ]:
            del self._prewarm_reservations[rid]
        outstanding: dict[str, int] = {vid: 0 for vid in self.volume_refs}
        for _, grants in self._prewarm_reservations.values():
            for vid, nbytes in grants.items():
                outstanding[vid] = outstanding.get(vid, 0) + nbytes
        for vid, nbytes in outstanding.items():
            _PREWARM_RESERVED.set(nbytes, volume=vid)

    @endpoint
    async def reserve_prewarm(
        self,
        reservation_id: str,
        asks: dict[str, int],
        ttl_s: float = 120.0,
        config=None,
    ) -> dict[str, Any]:
        """Grant tmpfs capacity for a prewarm: for each asked volume, the
        grant is ``min(ask, volume headroom - outstanding grants)`` where
        headroom is the smaller of actual /dev/shm availability and the
        pool cap's remaining room (the volume's own view via its
        ``shm_capacity`` endpoint). Unreachable volumes grant 0 and land in
        ``errors`` — the prewarmer skips them and the lazy path serves.
        Returns ``{"grants": {vid: bytes}, "errors": {vid: reason}}``."""
        import asyncio
        import time

        self._expire_prewarm()
        outstanding: dict[str, int] = {}
        for _, grants in self._prewarm_reservations.values():
            for vid, nbytes in grants.items():
                outstanding[vid] = outstanding.get(vid, 0) + nbytes
        # Placeholder reservation at the FULL ask BEFORE awaiting the
        # capacity RPCs: endpoints dispatch concurrently, so without it two
        # simultaneous reservers would both compute headroom against the
        # same outstanding set and collectively over-grant — the exact
        # oversubscription this endpoint exists to prevent. Pessimistic
        # (may under-grant a concurrent peer); replaced by the real grants
        # below, dropped on failure.
        self._prewarm_reservations[reservation_id] = (
            time.monotonic() + ttl_s,
            {vid: int(nbytes) for vid, nbytes in asks.items()},
        )

        async def capacity(vid: str):
            ref = self.volume_refs.get(vid)
            if ref is None:
                return vid, None, "unknown volume"
            try:
                # The asking client's config rides along so the volume
                # reports headroom against the POOL CAP the later
                # provision_shm will actually run under.
                info = await asyncio.wait_for(
                    ref.shm_capacity.call_one(config), timeout=10.0
                )
                return vid, info, None
            except Exception as exc:  # noqa: BLE001 - reported, not raised
                return vid, None, f"{type(exc).__name__}: {exc}"

        try:
            results = await asyncio.gather(
                *(capacity(vid) for vid in sorted(asks))
            )
        except BaseException:
            self._prewarm_reservations.pop(reservation_id, None)
            raise
        # tmpfs is a PER-HOST resource: volumes co-located on one host share
        # /dev/shm, so availability is budgeted per hostname (each co-located
        # volume reports the same tmpfs; take the min) with outstanding
        # grants netted per host too — otherwise two volumes on one host
        # could be jointly granted more than the tmpfs holds. Pool-cap
        # headroom stays per volume (each volume owns its pool).
        host_of = {
            vid: self.volume_hostnames.get(vid, vid) for vid in asks
        }
        host_budget: dict[str, int] = {}
        for vid, info, err in results:
            if info is not None and info.get("shm"):
                host = host_of[vid]
                avail = int(info["available_bytes"])
                host_budget[host] = min(host_budget.get(host, avail), avail)
        for rid_vid, nbytes in outstanding.items():
            host = self.volume_hostnames.get(rid_vid, rid_vid)
            if host in host_budget:
                host_budget[host] = max(0, host_budget[host] - nbytes)
        granted: dict[str, int] = {}
        errors: dict[str, str] = {}
        for vid, info, err in results:
            if info is None or not info.get("shm"):
                granted[vid] = 0
                errors[vid] = err or "shm unavailable on volume"
                continue
            host = host_of[vid]
            cap_headroom = max(
                0, int(info["pool_cap"]) - int(info["pool_bytes"])
            ) - outstanding.get(vid, 0)
            grant = max(
                0,
                min(int(asks[vid]), cap_headroom, host_budget.get(host, 0)),
            )
            host_budget[host] = host_budget.get(host, 0) - grant
            granted[vid] = grant
        self._prewarm_reservations[reservation_id] = (
            time.monotonic() + ttl_s,
            dict(granted),
        )
        self._expire_prewarm()
        return {"grants": granted, "errors": errors}

    @endpoint
    async def release_prewarm(self, reservation_id: str) -> None:
        """Drop a reservation once its provisioning landed (the pool itself
        now holds the bytes) or was abandoned. Idempotent."""
        self._prewarm_reservations.pop(reservation_id, None)
        self._expire_prewarm()

    @endpoint
    async def check_volumes(self, timeout: float = 5.0) -> dict[str, str]:
        """Health-check every volume (failure detection — SURVEY §5 notes
        the reference has no heartbeats at all). Returns volume_id ->
        'ok' | 'wedged: ...' (alive but unresponsive — e.g. stopped or
        overloaded; may recover) | 'dead: ...' (unreachable)."""
        import asyncio

        async def ping(vid: str, ref: ActorRef) -> tuple[str, str]:
            try:
                await asyncio.wait_for(ref.ping(), timeout=timeout)
                # The supervisor's verdict outranks one lucky ping: a
                # quarantined volume stays reported as such until probation
                # reinstates it, so clients keep avoiding it meanwhile.
                state = self._vol_health.get(vid, {}).get("state", "ok")
                if state == "quarantined":
                    return vid, "quarantined: skipped by placement until reinstated"
                return vid, "ok"
            except asyncio.TimeoutError:
                return (
                    vid,
                    f"wedged: no ping response within {timeout:.0f}s "
                    "(process alive but stuck — SIGSTOP'd, deadlocked, or "
                    "overloaded; may recover)",
                )
            except Exception as exc:  # noqa: BLE001 - reported, not raised
                return vid, f"dead: {type(exc).__name__}"

        results = await asyncio.gather(
            *(ping(vid, ref) for vid, ref in self.volume_refs.items())
        )
        return dict(results)

    # ---- health supervisor ------------------------------------------------

    def _quarantined_ids(self) -> set:
        return {
            vid
            for vid, h in self._vol_health.items()
            if h["state"] == "quarantined"
        }

    def _start_supervisor(self) -> None:
        """(Re)start the heartbeat loop — called from init(); idempotent
        across re-inits. Disabled when the interval is <= 0."""
        if self._health_task is not None:
            self._health_task.cancel()
            self._health_task = None
        if self._health_interval <= 0:
            return
        self._health_task = spawn_logged(
            self._health_loop(),
            name="controller.health",
            tasks=self._health_tasks,
            log=logger,
        )

    async def _health_loop(self) -> None:
        import asyncio

        while True:
            await asyncio.sleep(self._health_interval)
            await self._health_sweep()

    async def _health_sweep(self) -> None:
        """One heartbeat round: ping every volume, run the per-volume state
        machine (ok -> quarantined after miss-threshold consecutive misses;
        quarantined -> probation on the first answered ping -> ok after
        miss-threshold consecutive answers), bump the placement epoch on
        every transition so clients drop plans/locations, and kick off auto
        re-replication when a volume is quarantined."""
        import asyncio

        timeout = min(max(self._health_interval, 0.5), 5.0)

        async def ping(vid: str, ref: ActorRef) -> tuple[str, bool]:
            try:
                await asyncio.wait_for(ref.ping(), timeout=timeout)
                return vid, True
            except Exception:  # noqa: BLE001 - any failure is a miss
                return vid, False

        results = await asyncio.gather(
            *(ping(vid, ref) for vid, ref in self.volume_refs.items())
        )
        changed = False
        for vid, ok in results:
            h = self._vol_health.get(vid)
            if h is None:
                h = self._vol_health[vid] = {
                    "state": "ok", "misses": 0, "oks": 0
                }
            state = h["state"]
            if ok:
                h["misses"] = 0
                if state == "quarantined":
                    h["state"] = "probation"
                    h["oks"] = 1
                    _VOLUME_HEALTH.set(0.5, volume=vid)
                    changed = True
                    obs_recorder.record("health", f"probation/{vid}")
                    logger.warning(
                        "volume %s answered pings again: probation "
                        "(%d/%d stable rounds to reinstate)",
                        vid, 1, self._miss_threshold,
                    )
                elif state == "probation":
                    h["oks"] += 1
                    if h["oks"] >= self._miss_threshold:
                        h["state"] = "ok"
                        _VOLUME_HEALTH.set(1, volume=vid)
                        changed = True
                        obs_recorder.record("health", f"reinstated/{vid}")
                        logger.warning(
                            "volume %s reinstated after %d stable rounds",
                            vid, h["oks"],
                        )
            else:
                h["oks"] = 0
                h["misses"] += 1
                if (
                    state != "quarantined"
                    and h["misses"] >= self._miss_threshold
                ):
                    h["state"] = "quarantined"
                    _VOLUME_HEALTH.set(0, volume=vid)
                    _QUARANTINES.inc(volume=vid)
                    changed = True
                    obs_recorder.record(
                        "health", f"quarantine/{vid}", misses=h["misses"]
                    )
                    logger.warning(
                        "volume %s QUARANTINED after %d missed heartbeats; "
                        "placement skips it%s",
                        vid,
                        h["misses"],
                        "; auto-repair starting" if self._auto_repair else "",
                    )
                    # Fault-triggered flight recorder: dump a MERGED
                    # post-mortem (controller ring + every reachable
                    # volume's) the moment a volume goes dark — the
                    # "last five seconds" an operator reads first. Off
                    # the sweep's critical path.
                    spawn_logged(
                        self._dump_flight(f"quarantine:{vid}"),
                        name="controller.flight_dump",
                        tasks=self._health_tasks,
                        log=logger,
                    )
                    # A draining volume that went dark abandons its drain:
                    # quarantine + auto-repair own recovery from here (the
                    # autoscale engine's next round sees it gone from the
                    # draining set and plans nothing for it).
                    if vid in self._draining:
                        self._draining.discard(vid)
                        obs_recorder.record(
                            "health", f"drain_abandoned/{vid}"
                        )
                        self._autoscale_engine.publish_fleet_gauges()
                    # Broadcast trees route around the dark node NOW:
                    # orphaned subtrees re-attach to a healthy ancestor and
                    # resume from their last landed watermark.
                    await self._relay_on_quarantine(vid)
                    if self._auto_repair:
                        self._start_auto_repair(vid)
        if changed:
            # One bump per sweep however many volumes transitioned: clients
            # drop cached plans/locations and re-resolve against the new
            # health picture on their next operation.
            self._bump_epoch()
            self._push_health()

    def _push_health(self) -> None:
        """Propagate the quarantine picture to every index host: shards
        re-filter their locates immediately (best-effort — a shard that
        misses the push serves slightly stale health until the next one),
        and the local core republishes its stamped index filtered."""
        self.core.mark_meta_dirty()
        if not self._shard_refs:
            return
        quarantined = sorted(self._quarantined_ids())

        async def push() -> None:
            import asyncio

            await asyncio.gather(
                *(
                    ref.set_quarantined.call_one(quarantined)
                    for ref in self._shard_refs
                ),
                return_exceptions=True,
            )

        spawn_logged(
            push(),
            name="controller.health_push",
            tasks=self._health_tasks,
            log=logger,
        )

    async def _dump_flight(self, trigger: str) -> Optional[str]:
        """Write a MERGED flight-recorder post-mortem: this controller's
        ring plus every volume's that still answers (2 s budget each — the
        volume the trigger is about is usually the one that can't). Best-
        effort by construction: a post-mortem must never fail its fleet."""
        import asyncio

        async def one(vid: str, ref: ActorRef) -> list:
            try:
                events = await asyncio.wait_for(
                    ref.flight_record.call_one(), timeout=2.0
                )
                for event in events:
                    event.setdefault("process", f"volume:{vid}")
                return events
            except Exception:  # noqa: BLE001 - unreachable: ring lost
                return []

        gathered = await asyncio.gather(
            *(one(vid, ref) for vid, ref in self.volume_refs.items())
        )
        extra = [event for events in gathered for event in events]
        return obs_recorder.dump_postmortem(trigger, extra)

    def _start_auto_repair(self, volume_id: str) -> None:
        if volume_id in self._repairing:
            return
        self._repairing.add(volume_id)
        obs_recorder.record("health", f"auto_repair/{volume_id}")
        spawn_logged(
            self._auto_repair_volume(volume_id),
            name="controller.auto_repair",
            tasks=self._health_tasks,
            log=logger,
        )

    async def _auto_repair_volume(self, volume_id: str) -> None:
        """Re-replicate every key the quarantined volume held that still
        has a healthy copy onto healthy volumes — the plan/pull/index pass
        lives with the index (IndexCore.auto_repair_pass; each shard runs
        its own slice when sharded). See the core method for the raced-
        overwrite and shard-coverage rules."""
        try:
            healthy = [
                vid
                for vid, h in self._vol_health.items()
                if h["state"] == "ok" and vid in self.volume_refs
            ]
            repaired = await self.idx.auto_repair_pass(volume_id, healthy)
            if repaired:
                logger.warning(
                    "auto-repair for quarantined volume %s: re-replicated "
                    "%d key(s) onto healthy volumes",
                    volume_id,
                    repaired,
                )
        finally:
            self._repairing.discard(volume_id)

    @endpoint
    async def volume_health(self) -> dict[str, dict]:
        """Supervisor view per volume: {"state", "misses", "oks"} — the
        fleet's self-healing dashboard (also embedded in stats())."""
        return {vid: dict(h) for vid, h in self._vol_health.items()}

    # ---- fault injection (test/chaos control plane) ------------------------

    @endpoint
    async def inject_fault(
        self,
        name: str,
        action: str,
        count: Optional[int] = None,
        prob: Optional[float] = None,
        delay_ms: Optional[float] = None,
    ) -> dict:
        """Arm a faultpoint INSIDE the controller process (see
        torchstore_tpu/faults.py) — the control RPC that lets tests
        schedule deterministic failures in an already-running fleet."""
        return faults.arm(
            name, action, count=count, prob=prob, delay_ms=delay_ms
        )

    @endpoint
    async def clear_faults(self, name: Optional[str] = None) -> int:
        return faults.disarm(name)

    @endpoint
    async def list_faults(self) -> list:
        return faults.armed()

    @endpoint
    async def replace_volume(
        self, volume_id: str, new_ref: ActorRef, hostname: str
    ) -> dict[str, Any]:
        """Swap in a replacement actor for a dead volume (elastic repair —
        the recovery story SURVEY §5 notes the reference lacks). The dead
        volume's index entries are detached (the replacement starts empty);
        returns what it held so the repairer can re-replicate:

        - ``recoverable``: {key: [TensorSlice, ...] | None} — entries another
          volume still serves (None = whole tensor/object, else the shard
          slices this volume held).
        - ``lost``: keys with NO surviving copy (now absent from the index —
          reads fail loudly with missing instead of hanging on a dead ref).
        """
        if volume_id not in self.volume_refs:
            raise ValueError(f"unknown volume {volume_id!r}")
        self.volume_refs[volume_id] = new_ref
        self.volume_hostnames[volume_id] = hostname
        if self._shard_refs:
            # Shards hold their own ref tables (reclaims, repair pulls):
            # swap the replacement in everywhere before detaching entries.
            import asyncio

            await asyncio.gather(
                *(
                    ref.update_volume_ref.call_one(
                        volume_id, new_ref, hostname
                    )
                    for ref in self._shard_refs
                )
            )
        result = await self.idx.detach_volume(volume_id)
        self._bump_epoch()
        return result

    @endpoint
    async def rebuild_index(self) -> int:
        """Recover the metadata index from volume manifests (durable
        backends). Returns the number of entries indexed — the recovery
        path the reference lacks (its store is memory-only, SURVEY §5).

        Mixed shard layouts for one key (a crash mid re-shard: one volume
        already on the new mesh/global shape, another still holding old
        shards) are resolved by keeping only the NEWEST layout (max file
        mtime). Indexing both would pass the commit check on a mixed coords
        set and serve overlapping stale+fresh slices; preferring a complete
        old layout would silently serve stale weights. The newest layout
        stays partial until re-pushed — gets fail loudly instead."""
        import asyncio

        manifests = await asyncio.gather(
            *(ref.manifest.call_one() for ref in self.volume_refs.values())
        )
        survivors, dropped = resolve_manifests(
            list(zip(self.volume_refs.keys(), manifests))
        )
        # Indexing + generation seeding live with the index (IndexCore.
        # reindex seeds recovered keys at a random epoch offset so a
        # surviving subscriber's pre-restart gen can never collide; the
        # sharded authority partitions survivors to their owning shards).
        count = await self.idx.reindex(survivors)
        if dropped:
            logger.warning(
                "rebuild_index dropped %d superseded-layout shard(s); the "
                "surviving layout may be partially committed until re-pushed",
                dropped,
            )
        self._bump_epoch()  # rebuilt routing invalidates all plans
        return count

    @endpoint
    async def stats(
        self,
        include_volumes: bool = False,
        history: Optional[dict] = None,
    ) -> dict:
        """Store-level observability: counters + index summary.
        ``include_volumes=True`` additionally fans out to every volume for
        its data-plane view (entries, stored bytes, SHM segment economics);
        unreachable volumes report an ``error`` string instead.
        ``history={"series": ..., "since": ...}`` embeds this process's
        retained time-series rings under ``"history"`` and forwards the
        request to any volume fan-out (ts.history() rides this; routine
        scrapes omit it)."""
        # Index rollup (op counters, key/byte totals, pending reclaims)
        # comes from the authority — summed across shards when sharded.
        summary = await self.idx.summary()
        out = {
            **summary,
            "num_volumes": len(self.volume_refs),
            "metadata_shards": len(self._shard_refs) or 1,
            # Health supervisor view (state/misses/oks per volume) — the
            # same data volume_health() serves, embedded for fleet scrapes.
            "volume_health": {
                vid: dict(h) for vid, h in self._vol_health.items()
            },
            # Live cohort retention leases (tiering/): how many versions
            # are pinned against GC/spill right now.
            "active_leases": len(self._leases),
            # The controller process's own registry — metrics are
            # process-local, so remote clients reach these through stats().
            "metrics": obs_metrics.metrics_snapshot(),
        }
        if history is not None:
            from torchstore_tpu.observability import history as obs_history

            out["history"] = obs_history.history(
                series=history.get("series"), since=history.get("since")
            )
        if include_volumes:
            import asyncio

            async def one(vid: str, ref: ActorRef):
                try:
                    return vid, await asyncio.wait_for(
                        ref.stats.call_one(history=history)
                        if history is not None
                        else ref.stats.call_one(),
                        timeout=10.0,
                    )
                except Exception as exc:  # noqa: BLE001 - reported inline
                    return vid, {"error": f"{type(exc).__name__}: {exc}"}

            results = await asyncio.gather(
                *(one(vid, ref) for vid, ref in self.volume_refs.items())
            )
            out["volumes"] = dict(results)
        return out

    @endpoint
    async def teardown(self) -> None:
        import asyncio

        if self._health_task is not None:
            self._health_task.cancel()
            self._health_task = None
        if self._tier_task is not None:
            self._tier_task.cancel()
            self._tier_task = None
        if self._control_task is not None:
            self._control_task.cancel()
            self._control_task = None
        if self._autoscale_task is not None:
            self._autoscale_task.cancel()
            self._autoscale_task = None
        self._draining.clear()
        if self._reshard_gate is not None:
            self._reshard_gate.set()
            self._reshard_gate = None
        self._relay_prefer.clear()
        self._leases.clear()
        for task in list(self._health_tasks):
            task.cancel()
        self._health_tasks.clear()
        for task in list(self._reclaim_tasks):
            task.cancel()
        self._reclaim_tasks.clear()
        for task in list(self._relay_tasks):
            task.cancel()
        self._relay_tasks.clear()
        self._relay_runs.clear()
        self._relay_channels.clear()
        self._prewarm_reservations.clear()
        self._expire_prewarm()  # zero the reserved-bytes gauges too
        self._streams.clear()
        if self._shard_refs:
            # Shards unlink their stamped segments and cancel reclaim
            # drainers; best-effort — a dead shard's segments are reaped
            # with its process.
            from torchstore_tpu.metadata.shards import RemoteIndex

            if isinstance(self.idx, RemoteIndex):
                await self.idx.teardown()
            self._shard_refs = []
            self._shard_stamped = []
        if self._meta_feed is not None:
            self._meta_feed.close()
            self._meta_feed = None
        self._meta_subscribers.clear()
        self._meta_parents.clear()
        if self._meta_writer is not None:
            self._meta_writer.close()
            self._meta_writer = None
        if self.core.meta_writer is not None:
            self.core.meta_writer.close()
            self.core.meta_writer = None
        self.core.teardown()
        self.idx = self.core
        await asyncio.gather(
            *(ref.reset.call_one() for ref in self.volume_refs.values()),
            return_exceptions=True,
        )
