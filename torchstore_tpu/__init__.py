"""torchstore_tpu: a TPU-native distributed async tensor store.

Same capabilities as meta-pytorch/torchstore (RL-style weight sync: publish a
sharded model ``state_dict`` from one actor group and pull it into a
differently-sharded model in another, with automatic resharding + transport
selection), designed TPU-first: ``jax.Array`` + ``NamedSharding`` sharding
metadata, storage volumes on TPU (host, chip) coordinates, and a same-host
SHM / bulk-TCP (ICI-adjacent / DCN) / RPC transport ladder.
"""

from torchstore_tpu.api import (
    DEFAULT_STORE,
    Shard,
    autoscale,
    autoscale_plan,
    barrier,
    blob_checkpoint,
    blob_restore,
    clear_faults,
    client,
    collect_trace,
    control_plan,
    delete,
    delete_batch,
    delete_prefix,
    exists,
    fleet_snapshot,
    flight_record,
    get,
    get_batch,
    direct_staging_buffers,
    history,
    get_state_dict,
    get_state_dict_streamed,
    state_dict_stream,
    initialize,
    initialize_spmd,
    inject_fault,
    keys,
    lease_acquire,
    lease_list,
    lease_release,
    lease_renew,
    metrics_snapshot,
    prewarm,
    put,
    put_batch,
    put_state_dict,
    rebalance,
    relay_topology,
    repair,
    reset_client,
    shutdown,
    slo_report,
    sync_timeline,
    tier_sweep,
    traffic_matrix,
    version_catalog,
    volume_health,
    wait_for,
)
from torchstore_tpu.provision import StateDictManifest
from torchstore_tpu.client import LocalClient
from torchstore_tpu.weight_channel import WeightPublisher, WeightSubscriber
from torchstore_tpu.config import StoreConfig
from torchstore_tpu.logging import init_logging
from torchstore_tpu.observability import (
    maybe_start_dumper,
    maybe_start_history,
    maybe_start_http_exporter,
    span,
)
from torchstore_tpu.strategy import (
    HostStrategy,
    LocalRankStrategy,
    SingletonStrategy,
    StoreStrategy,
)
from torchstore_tpu.transport.factory import TransportType
from torchstore_tpu.transport.types import Request, TensorMeta, TensorSlice

init_logging()
# Every torchstore process (clients, volume actors, the controller) starts
# its metrics dump thread here when TORCHSTORE_TPU_METRICS_DUMP is set, and
# its live /metrics + /healthz HTTP endpoint when
# TORCHSTORE_TPU_METRICS_PORT is set (siblings that lose the port race fall
# back to an ephemeral port, published via the ts_metrics_http_port gauge).
maybe_start_dumper()
maybe_start_http_exporter()
# ... and its 1 Hz time-series history sampler (TORCHSTORE_TPU_HISTORY,
# default on; bounded rings, ~1% CPU budget) so every process can answer
# "what did this look like five minutes ago" without external scrapers.
maybe_start_history()

__version__ = "0.1.0"

__all__ = [
    "DEFAULT_STORE",
    "HostStrategy",
    "LocalClient",
    "LocalRankStrategy",
    "Request",
    "Shard",
    "SingletonStrategy",
    "StateDictManifest",
    "StoreConfig",
    "StoreStrategy",
    "TensorMeta",
    "TensorSlice",
    "TransportType",
    "WeightPublisher",
    "WeightSubscriber",
    "autoscale",
    "autoscale_plan",
    "barrier",
    "blob_checkpoint",
    "blob_restore",
    "clear_faults",
    "client",
    "collect_trace",
    "control_plan",
    "delete",
    "delete_batch",
    "delete_prefix",
    "exists",
    "fleet_snapshot",
    "flight_record",
    "get",
    "get_batch",
    "get_state_dict",
    "history",
    "get_state_dict_streamed",
    "state_dict_stream",
    "initialize",
    "initialize_spmd",
    "inject_fault",
    "keys",
    "lease_acquire",
    "lease_list",
    "lease_release",
    "lease_renew",
    "metrics_snapshot",
    "prewarm",
    "put",
    "put_batch",
    "direct_staging_buffers",
    "put_state_dict",
    "rebalance",
    "relay_topology",
    "repair",
    "reset_client",
    "shutdown",
    "slo_report",
    "span",
    "sync_timeline",
    "tier_sweep",
    "traffic_matrix",
    "version_catalog",
    "volume_health",
    "wait_for",
]
