"""torchstore_tpu: a TPU-native distributed async tensor store.

Same capabilities as meta-pytorch/torchstore (RL-style weight sync: publish a
sharded state_dict from one actor group, pull it into a differently sharded
model in another, with automatic resharding + transport selection), designed
TPU-first: jax.Array/NamedSharding sharding metadata, storage volumes on TPU
(host, chip) coordinates, and a same-host-SHM / bulk-TCP(DCN) / RPC transport
ladder.
"""

from torchstore_tpu.logging import init_logging

init_logging()

__version__ = "0.1.0"
