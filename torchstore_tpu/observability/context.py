"""Trace-context propagation: one trace id across the whole fleet.

A logical store operation fans out over processes — client span -> controller
notify -> N volume puts — and PR 1's per-process Chrome traces land those
spans in disconnected files with no way to say "these belong to one put".
This module carries a W3C-traceparent-shaped context (``trace_id`` +
``parent_span_id``) in :mod:`contextvars`, so:

- ``span()`` (tracing.py) stamps every emitted event with the active
  ``trace_id``/``span_id``/``parent_id`` and pushes itself as the parent for
  anything nested under it — across ``await`` boundaries, since asyncio tasks
  snapshot the context at creation;
- the actor RPC layer (runtime/actors.py) injects the current context into
  every request frame and re-activates it around endpoint dispatch on the
  server, so a volume-side span carries the CLIENT's trace id;
- ``merge_traces`` / ``ts.collect_trace()`` then stitch the per-process files
  into one Perfetto timeline where the shared trace id (and parent links)
  align client, controller, and volume tracks.

Ids are hex strings (16 hex chars — 8 random bytes), cheap to mint per
logical op. Context creation is O(two contextvar sets); when tracing is
disabled only the ids ride the RPC frames (useful for slow-op log
correlation) and nothing is buffered.
"""

from __future__ import annotations

import contextvars
import secrets
from typing import Optional

_trace_id: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "torchstore_tpu_trace_id", default=None
)
_parent_span_id: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "torchstore_tpu_parent_span_id", default=None
)


def new_id() -> str:
    return secrets.token_hex(8)


def trace_id() -> Optional[str]:
    """The active trace id, or None outside any traced operation."""
    return _trace_id.get()


def parent_span_id() -> Optional[str]:
    return _parent_span_id.get()


def current() -> Optional[dict]:
    """The propagatable context: ``{"trace_id", "parent_span_id"}`` or None.

    This is exactly what rides an RPC frame — the receiving side's spans
    adopt the trace id and hang off the caller's span as children."""
    tid = _trace_id.get()
    if tid is None:
        return None
    return {"trace_id": tid, "parent_span_id": _parent_span_id.get()}


def push_span(span_id: str) -> "contextvars.Token":
    """Make ``span_id`` the parent of anything opened under it. Returns the
    token for :func:`pop_span`; the token's ``old_value`` is this span's own
    parent (used when emitting the span's trace event)."""
    return _parent_span_id.set(span_id)


def pop_span(token: "contextvars.Token") -> None:
    _parent_span_id.reset(token)


def token_parent(token: "contextvars.Token") -> Optional[str]:
    """The parent id that was active before ``push_span`` minted this token."""
    old = token.old_value
    return None if old is contextvars.Token.MISSING else old


class activate:
    """Adopt an incoming (RPC-carried) context for the duration of a block.

    ``activate(None)`` is a no-op — server dispatch wraps every endpoint call
    unconditionally and untraced callers cost nothing."""

    __slots__ = ("_ctx", "_tokens")

    def __init__(self, ctx: Optional[dict]) -> None:
        self._ctx = ctx if isinstance(ctx, dict) else None
        self._tokens = None

    def __enter__(self) -> "activate":
        if self._ctx is not None and self._ctx.get("trace_id"):
            self._tokens = (
                _trace_id.set(str(self._ctx["trace_id"])),
                _parent_span_id.set(self._ctx.get("parent_span_id")),
            )
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._tokens is not None:
            _trace_id.reset(self._tokens[0])
            _parent_span_id.reset(self._tokens[1])
            self._tokens = None


class ensure_root:
    """Start a new trace unless one is already active (client ops wrap their
    whole body in this, so every put/get roots exactly one trace and nested
    store calls — weight channel publishes, state-dict flattening — join
    their caller's)."""

    __slots__ = ("_token",)

    def __enter__(self) -> "ensure_root":
        self._token = (
            None if _trace_id.get() is not None else _trace_id.set(new_id())
        )
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._token is not None:
            _trace_id.reset(self._token)
            self._token = None
