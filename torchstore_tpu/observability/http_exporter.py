"""Opt-in live HTTP metrics endpoint: ``/metrics`` + ``/healthz`` (+
``/metrics.json``, ``/history.json``, ``/slo.json`` — the surfaces
``scripts/ts_top.py`` polls in --url mode).

Set ``TORCHSTORE_TPU_METRICS_PORT`` and every torchstore process starts a
stdlib ``http.server`` thread serving its own registry in Prometheus text —
``curl host:PORT/metrics`` scrapes a LIVE run instead of waiting for the
periodic file dump, and ``/healthz`` gives tpu_watch.sh / load balancers a
liveness probe (200 + JSON with pid/uptime).

Port contention is expected, not an error: volume actors inherit the same
env var as the client that spawned them, so the FIRST process to bind gets
the configured port and every sibling falls back to an ephemeral one; each
process publishes its actual bound port in the ``ts_metrics_http_port``
gauge, so a fleet snapshot (``ts.fleet_snapshot()``) doubles as endpoint
discovery. Zero cost when the env var is unset.

The endpoint is UNAUTHENTICATED (a registry dump, no control surface), so
it binds loopback by default; set ``TORCHSTORE_TPU_METRICS_HOST=0.0.0.0``
to deliberately expose it for cross-host scraping (e.g. a Prometheus
server on another machine).
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from torchstore_tpu.observability import metrics as obs_metrics

ENV_METRICS_PORT = "TORCHSTORE_TPU_METRICS_PORT"
ENV_METRICS_HOST = "TORCHSTORE_TPU_METRICS_HOST"

_START_TIME = time.time()


class _Handler(BaseHTTPRequestHandler):
    # Liveness probes every few seconds must not spam operator logs.
    def log_message(self, format, *args):  # noqa: A002 - stdlib signature
        pass

    def _send(self, code: int, content_type: str, body: str) -> None:
        payload = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def do_GET(self) -> None:  # noqa: N802 - stdlib signature
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                self._send(
                    200,
                    "text/plain; version=0.0.4; charset=utf-8",
                    obs_metrics.get_registry().render_prometheus(),
                )
            elif path == "/healthz":
                self._send(
                    200,
                    "application/json",
                    json.dumps(
                        {
                            "status": "ok",
                            "pid": os.getpid(),
                            "uptime_s": round(time.time() - _START_TIME, 3),
                        }
                    ),
                )
            elif path == "/metrics.json":
                self._send(
                    200,
                    "application/json",
                    obs_metrics.get_registry().render_json(),
                )
            elif path == "/history.json":
                self._send(
                    200,
                    "application/json",
                    json.dumps(self._history_doc()),
                )
            elif path == "/slo.json":
                from torchstore_tpu.observability import (
                    timeline as obs_timeline,
                )

                self._send(
                    200, "application/json", json.dumps(obs_timeline.slo_report())
                )
            else:
                self._send(404, "text/plain", "not found\n")
        except (BrokenPipeError, ConnectionResetError):
            pass  # scraper went away mid-response

    def _history_doc(self) -> dict:
        """This process's retained time-series rings
        (``/history.json?series=<glob>[,<glob>...]&since=<s>&level=<i>``)
        — what ts_top.py polls in --url mode."""
        from urllib.parse import parse_qs

        from torchstore_tpu.observability import history as obs_history

        query = parse_qs(
            self.path.split("?", 1)[1] if "?" in self.path else ""
        )
        series = None
        if query.get("series"):
            series = [
                g for raw in query["series"] for g in raw.split(",") if g
            ] or None
        since = None
        if query.get("since"):
            try:
                since = float(query["since"][0])
            except ValueError:
                since = None
        level = None
        if query.get("level"):
            try:
                level = int(query["level"][0])
            except ValueError:
                level = None
        return obs_history.history(series=series, since=since, level=level)


class MetricsHTTPExporter:
    """One process's metrics server: a daemon thread around a
    ``ThreadingHTTPServer``. ``port`` is the actually-bound port (differs
    from the requested one after an ephemeral fallback)."""

    def __init__(self, host: str, port: int) -> None:
        self._server = ThreadingHTTPServer((host, port), _Handler)
        self._server.daemon_threads = True
        self.host = host
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="torchstore-tpu-metrics-http",
            daemon=True,
        )
        self._thread.start()
        obs_metrics.gauge(
            "ts_metrics_http_port",
            "Port this process's live /metrics endpoint is bound to",
        ).set(self.port)

    def close(self) -> None:
        """Stop serving and release the port (idempotent)."""
        try:
            self._server.shutdown()
            self._server.server_close()
        except Exception:
            pass
        self._thread.join(timeout=5.0)


_exporter_lock = threading.Lock()
_exporter: Optional[MetricsHTTPExporter] = None


def start_http_exporter(
    port: int, host: Optional[str] = None
) -> MetricsHTTPExporter:
    """Explicitly start an exporter (tests, embedding apps). Raises
    ``OSError`` if the port is taken — use :func:`maybe_start_http_exporter`
    for the fall-back-to-ephemeral behavior."""
    return MetricsHTTPExporter(
        host if host is not None else os.environ.get(ENV_METRICS_HOST, "127.0.0.1"),
        port,
    )


def get_http_exporter() -> Optional[MetricsHTTPExporter]:
    return _exporter


def stop_http_exporter() -> None:
    global _exporter
    with _exporter_lock:
        exporter, _exporter = _exporter, None
    if exporter is not None:
        exporter.close()


def reinit_after_fork() -> Optional[MetricsHTTPExporter]:
    """Re-arm in an actor child. Under forkserver, an inherited exporter
    has a DEAD serving thread but a live listening fd — close the fd
    (never ``shutdown()``: it waits on serve_forever's ack, which no
    thread will ever give) and start fresh against the child's env
    (falling back to an ephemeral port, since the spawner usually still
    holds the configured one). Under spawn, the child's own import already
    started a live, serving exporter — keep it; closing its socket under a
    running serve_forever thread would leave a zombie."""
    global _exporter
    with _exporter_lock:
        exporter = _exporter
        if exporter is not None and exporter._thread.is_alive():
            return exporter
        _exporter = None
    if exporter is not None:
        try:
            exporter._server.server_close()
        except Exception:
            pass
    return maybe_start_http_exporter()


def maybe_start_http_exporter() -> Optional[MetricsHTTPExporter]:
    """Start the env-gated exporter once per process when
    ``TORCHSTORE_TPU_METRICS_PORT`` is set. Idempotent. Sibling processes
    that lose the port race (volume actors inherit the same env) fall back
    to an ephemeral port — discover it via the ``ts_metrics_http_port``
    gauge in ``ts.fleet_snapshot()``. Called from ``torchstore_tpu``
    import."""
    global _exporter
    raw = os.environ.get(ENV_METRICS_PORT)
    if not raw:
        return None
    with _exporter_lock:
        if _exporter is not None:
            return _exporter
        try:
            port = int(raw)
        except ValueError:
            from torchstore_tpu.logging import get_logger

            get_logger("torchstore_tpu.observability").warning(
                "ignoring malformed %s=%r", ENV_METRICS_PORT, raw
            )
            return None
        host = os.environ.get(ENV_METRICS_HOST, "127.0.0.1")
        try:
            _exporter = MetricsHTTPExporter(host, port)
        except OSError:
            # A sibling process (the spawner, or an earlier volume) holds
            # the configured port; serve on an ephemeral one instead.
            try:
                _exporter = MetricsHTTPExporter(host, 0)
            except OSError:
                return None
        atexit.register(stop_http_exporter)
        from torchstore_tpu.logging import get_logger

        get_logger("torchstore_tpu.observability").info(
            "metrics http exporter serving on %s:%d (/metrics, /healthz)",
            host,
            _exporter.port,
        )
        return _exporter
