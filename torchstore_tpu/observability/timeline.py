"""Sync-timeline telemetry: rolling op quantiles, SLO thresholds, and
weight-sync generation reconstruction.

Three facilities that turn the bench-only numbers (``overlap_ratio``,
``first_token``) and the fixed-bucket op histograms into production
signals:

- **Rolling quantile digests** (:class:`OpQuantiles`): per-op ring of the
  last ``WINDOW`` wall times with true p50/p99 published as gauges
  (``ts_op_p50_seconds`` / ``ts_op_p99_seconds``, labeled ``op=``). The
  fixed-bucket histograms stay (Prometheus-aggregatable); the digests add
  the exact quantiles an SLO needs, refreshed lazily (every
  ``REFRESH_EVERY`` observations) so the hot path pays one deque append.

- **SLO thresholds** (``TORCHSTORE_TPU_SLO_*``): a typed family of
  operator-set bars. On breach the violation is logged (rate-limited per
  SLO) and counted in ``ts_slo_violations_total{slo=...}``. Shipped knobs:

      TORCHSTORE_TPU_SLO_PUT_P99_MS      rolling put p99 above this
      TORCHSTORE_TPU_SLO_GET_P99_MS      rolling get p99 above this
      TORCHSTORE_TPU_SLO_VERSION_LAG     subscriber version lag above this
      TORCHSTORE_TPU_SLO_FIRST_LAYER_MS  stream first-layer latency above
      TORCHSTORE_TPU_SLO_OVERLAP_MIN     stream overlap ratio BELOW this

  Unset = disabled; thresholds are re-read per check (one getenv) so live
  operators can retune a running fleet.

- **Generation reconstruction** (:func:`reconstruct`): folds a controller
  stream record (now timestamped — ``stream_begin`` -> per-key watermark
  landings -> ``stream_seal`` -> per-subscriber acquire acks) into one
  readable lifecycle: publish window, first-layer latency, landing
  timeline, and per-subscriber completion lag. ``ts.sync_timeline(key)``
  is the public entry point.

Live gauges the acquire side maintains (stream_sync.py): per-subscriber
``ts_stream_overlap_ratio`` / ``ts_stream_first_layer_seconds`` — the
production twins of the bench's ``overlap_ratio`` / ``first_token``.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Optional

from torchstore_tpu.observability import metrics as obs_metrics

# The blessed SLO knob family. Names are read via these literals (the
# env-registry lint cross-references them against config.ENV_REGISTRY);
# anything else under the TORCHSTORE_TPU_SLO_ prefix is accepted as an
# operator extension (registered dynamic prefix family).
SLO_PUT_P99_MS = "TORCHSTORE_TPU_SLO_PUT_P99_MS"
SLO_GET_P99_MS = "TORCHSTORE_TPU_SLO_GET_P99_MS"
SLO_VERSION_LAG = "TORCHSTORE_TPU_SLO_VERSION_LAG"
SLO_FIRST_LAYER_MS = "TORCHSTORE_TPU_SLO_FIRST_LAYER_MS"
SLO_OVERLAP_MIN = "TORCHSTORE_TPU_SLO_OVERLAP_MIN"

_SLO_VIOLATIONS = obs_metrics.counter(
    "ts_slo_violations_total",
    "SLO threshold breaches (TORCHSTORE_TPU_SLO_* family), by slo",
)
_P50 = obs_metrics.gauge(
    "ts_op_p50_seconds", "Rolling-window p50 wall time, by op"
)
_P99 = obs_metrics.gauge(
    "ts_op_p99_seconds", "Rolling-window p99 wall time, by op"
)


def slo_threshold(env_name: str) -> Optional[float]:
    """The configured threshold, or None when unset/disabled. Read per
    check (not cached) so a live operator can retune a running process."""
    raw = os.environ.get(env_name)
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        return None


# Rate-limit state for SLO breach logs: slo name -> last log monotonic.
# Inherited pre-fork contents only delay a child's first breach log by one
# window — no correctness or resource impact, so no fork hook is needed.
_last_slo_log: dict[str, float] = {}  # tslint: disable=fork-safety
_SLO_LOG_EVERY_S = 5.0


def check_slo(
    env_name: str,
    value: float,
    worse: str = "above",
    **context,
) -> bool:
    """Check ``value`` against the env-configured threshold; on breach,
    bump ``ts_slo_violations_total{slo=...}`` and log (rate-limited).
    ``worse="above"`` breaches when value > threshold; ``"below"`` when
    value < threshold (e.g. overlap ratio). Returns whether it breached."""
    threshold = slo_threshold(env_name)
    if threshold is None:
        return False
    breached = value > threshold if worse == "above" else value < threshold
    if not breached:
        return False
    slo = env_name.rsplit("TORCHSTORE_TPU_SLO_", 1)[-1].lower()
    _SLO_VIOLATIONS.inc(slo=slo)
    now = time.monotonic()
    if now - _last_slo_log.get(slo, 0.0) >= _SLO_LOG_EVERY_S:
        _last_slo_log[slo] = now
        from torchstore_tpu.logging import get_logger

        get_logger("torchstore_tpu.observability").warning(
            "SLO violation: %s=%.4g %s threshold %.4g%s",
            slo,
            value,
            "above" if worse == "above" else "below",
            threshold,
            f" ({context})" if context else "",
        )
    from torchstore_tpu.observability import recorder as obs_recorder

    obs_recorder.record(
        "slo", slo, value=round(float(value), 6), threshold=threshold
    )
    return True


class OpQuantiles:
    """Rolling per-op quantile digest: a bounded deque of recent wall
    times; p50/p99 gauges refreshed every REFRESH_EVERY observations (one
    sort of <= WINDOW samples, off the per-op critical path rhythm)."""

    WINDOW = 512
    REFRESH_EVERY = 32

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._samples: dict[str, collections.deque] = {}
        self._pending: dict[str, int] = {}

    def observe(self, op: str, dur_s: float) -> None:
        with self._lock:
            ring = self._samples.get(op)
            if ring is None:
                ring = self._samples[op] = collections.deque(
                    maxlen=self.WINDOW
                )
            ring.append(dur_s)
            pending = self._pending.get(op, 0) + 1
            if pending < self.REFRESH_EVERY and len(ring) != 1:
                self._pending[op] = pending
                return
            self._pending[op] = 0
            ordered = sorted(ring)
        p50 = ordered[len(ordered) // 2]
        p99 = ordered[min(len(ordered) - 1, int(len(ordered) * 0.99))]
        _P50.set(p50, op=op)
        _P99.set(p99, op=op)
        if op == "put":
            check_slo(SLO_PUT_P99_MS, p99 * 1e3, op=op)
        elif op == "get":
            check_slo(SLO_GET_P99_MS, p99 * 1e3, op=op)

    def quantiles(self, op: str, qs=(0.5, 0.99)) -> Optional[dict]:
        with self._lock:
            ring = self._samples.get(op)
            if not ring:
                return None
            ordered = sorted(ring)
        return {
            repr(q): ordered[min(len(ordered) - 1, int(len(ordered) * q))]
            for q in qs
        }

    def snapshot(self) -> dict:
        with self._lock:
            ops = list(self._samples)
        return {op: self.quantiles(op) for op in ops}

    def reset(self) -> None:
        with self._lock:
            self._samples.clear()
            self._pending.clear()


_quantiles = OpQuantiles()


def op_quantiles() -> OpQuantiles:
    return _quantiles


def observe_op(op: str, dur_s: float) -> None:
    """Feed one completed logical op into the rolling digests (and their
    p99 SLO checks). Called from the client's op completion path."""
    _quantiles.observe(op, dur_s)


# --------------------------------------------------------------------------
# generation reconstruction (controller stream records -> lifecycle)
# --------------------------------------------------------------------------


def reconstruct(state: Optional[dict]) -> Optional[dict]:
    """Fold a timestamped controller stream record (``stream_state``) into
    one generation lifecycle:

    ``{"version", "sealed", "begin_ts", "seal_ts", "publish_window_s",
    "first_layer_s", "landings": [{"key", "ts", "offset_s"}, ...],
    "subscribers": {sub: {"version", "ts", "completion_s"}}}``

    ``offset_s``/``completion_s`` are relative to ``begin_ts``. Returns
    None for a missing record; fields are None when the record predates
    the timestamping (controller upgrade mid-run)."""
    if state is None:
        return None
    begin_ts = state.get("begin_ts")
    seal_ts = state.get("seal_ts")
    landing_ts: dict = state.get("landing_ts") or {}
    landings = [
        {
            "key": key,
            "ts": ts,
            "offset_s": (
                round(ts - begin_ts, 6) if begin_ts is not None else None
            ),
        }
        for key, ts in sorted(landing_ts.items(), key=lambda kv: kv[1])
    ]
    first_layer_s = (
        round(landings[0]["ts"] - begin_ts, 6)
        if landings and begin_ts is not None
        else None
    )
    subscribers = {
        sub: {
            "version": ack.get("version"),
            "ts": ack.get("ts"),
            "completion_s": (
                round(ack["ts"] - begin_ts, 6)
                if begin_ts is not None and ack.get("ts") is not None
                else None
            ),
        }
        for sub, ack in (state.get("acks") or {}).items()
    }
    return {
        "version": state.get("version"),
        "sealed": state.get("sealed"),
        "begin_ts": begin_ts,
        "seal_ts": seal_ts,
        "publish_window_s": (
            round(seal_ts - begin_ts, 6)
            if begin_ts is not None and seal_ts is not None
            else None
        ),
        "first_layer_s": first_layer_s,
        "landings": landings,
        "subscribers": subscribers,
    }


def subscriber_id() -> str:
    """This process's identity in stream acquire acks (bounded: one entry
    per process per stream record)."""
    from torchstore_tpu.utils import get_hostname

    return f"{get_hostname()}:{os.getpid()}"
