"""Sync-timeline telemetry: rolling op quantiles, stage attribution, SLO
thresholds + scoreboard, and weight-sync generation reconstruction.

Facilities that turn the bench-only numbers (``overlap_ratio``,
``first_token``) and the fixed-bucket op histograms into production
signals:

- **Rolling quantile digests** (:class:`OpQuantiles`): per-op ring of the
  last ``WINDOW`` wall times with true p50/p99 published as gauges
  (``ts_op_p50_seconds`` / ``ts_op_p99_seconds``, labeled ``op=``). The
  fixed-bucket histograms stay (Prometheus-aggregatable); the digests add
  the exact quantiles an SLO needs, refreshed lazily (every
  ``REFRESH_EVERY`` observations) so the hot path pays one deque append.

- **Stage attribution** (:class:`StageQuantiles`, :func:`observe_stage`):
  client and volume ops record per-stage wall-clock segments — metadata
  resolve, transport wire, landing copy, stamp verify, watermark wait —
  into per-(op, stage) digests (``ts_op_stage_p50/p99_seconds{op,stage}``)
  plus rolling per-stage time totals. When an SLO blows, the totals answer
  the question an end-to-end timer can't: *which stage ate the budget*
  (:func:`dominant_stage`). Stage names MUST come from :data:`STAGE_CATALOG`
  — the ``stage-discipline`` tslint rule holds client and volume sites to
  the same taxonomy so digests from both sides fold together.

- **SLO thresholds** (``TORCHSTORE_TPU_SLO_*``): a typed family of
  operator-set bars. On breach the violation is logged (rate-limited per
  SLO) and counted in ``ts_slo_violations_total{slo=...}``. Shipped knobs:

      TORCHSTORE_TPU_SLO_PUT_P99_MS      rolling put p99 above this
      TORCHSTORE_TPU_SLO_GET_P99_MS      rolling get p99 above this
      TORCHSTORE_TPU_SLO_VERSION_LAG     subscriber version lag above this
      TORCHSTORE_TPU_SLO_FIRST_LAYER_MS  stream first-layer latency above
      TORCHSTORE_TPU_SLO_OVERLAP_MIN     stream overlap ratio BELOW this

  Unset = disabled; thresholds are re-read per check (one getenv) so live
  operators can retune a running fleet.

- **SLO scoreboard** (:func:`slo_report`): the live fold of all of the
  above — every configured ``TORCHSTORE_TPU_SLO_*`` threshold with its
  current value, violation count, violated flag, and (per violated SLO)
  the dominant stage with the per-stage breakdown. ``ts.slo_report()``
  wraps it with fleet overload signals (per-volume inflight landings,
  resident doorbell plans, metadata RPC inflight) — the inputs item 3's
  admission control consumes.

- **Generation reconstruction** (:func:`reconstruct`): folds a controller
  stream record (now timestamped — ``stream_begin`` -> per-key watermark
  landings -> ``stream_seal`` -> per-subscriber acquire acks) into one
  readable lifecycle: publish window, first-layer latency, landing
  timeline, and per-subscriber completion lag. ``ts.sync_timeline(key)``
  is the public entry point.

Live gauges the acquire side maintains (stream_sync.py): per-subscriber
``ts_stream_overlap_ratio`` / ``ts_stream_first_layer_seconds`` — the
production twins of the bench's ``overlap_ratio`` / ``first_token``.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Optional

from torchstore_tpu.observability import metrics as obs_metrics

# The blessed SLO knob family. Names are read via these literals (the
# env-registry lint cross-references them against config.ENV_REGISTRY);
# anything else under the TORCHSTORE_TPU_SLO_ prefix is accepted as an
# operator extension (registered dynamic prefix family).
SLO_PUT_P99_MS = "TORCHSTORE_TPU_SLO_PUT_P99_MS"
SLO_GET_P99_MS = "TORCHSTORE_TPU_SLO_GET_P99_MS"
SLO_VERSION_LAG = "TORCHSTORE_TPU_SLO_VERSION_LAG"
SLO_FIRST_LAYER_MS = "TORCHSTORE_TPU_SLO_FIRST_LAYER_MS"
SLO_OVERLAP_MIN = "TORCHSTORE_TPU_SLO_OVERLAP_MIN"

# The registered stage catalog. Every wall-clock segment recorded into the
# stage digests — client-side or volume-side — names one of these, so
# digests from both ends of a transfer fold into the same taxonomy (the
# ``stage-discipline`` tslint rule rejects free-string stage labels):
#
#   plan            metadata resolve: locate (RPC or stamped), plan/epoch
#                   validation, request building, placement selection
#   transport       the wire leg: handshake + frames + RPC data movement
#   landing         landing copies: bytes into store/destination memory
#   stamp_verify    one-sided seqlock checks (pre-copy match + post-copy
#                   re-gather) proving a read raced no landing
#   watermark_wait  streamed acquires blocked on per-key watermarks
#                   (wait_for_stream long-polls, stamped or RPC)
#   notify          the metadata commit: notify_put_batch / watermark step
STAGE_CATALOG = frozenset(
    {
        "plan",
        "transport",
        "landing",
        "stamp_verify",
        "watermark_wait",
        "notify",
    }
)

_SLO_VIOLATIONS = obs_metrics.counter(
    "ts_slo_violations_total",
    "SLO threshold breaches (TORCHSTORE_TPU_SLO_* family), by slo",
)
_P50 = obs_metrics.gauge(
    "ts_op_p50_seconds", "Rolling-window p50 wall time, by op"
)
_P99 = obs_metrics.gauge(
    "ts_op_p99_seconds", "Rolling-window p99 wall time, by op"
)
_STAGE_P50 = obs_metrics.gauge(
    "ts_op_stage_p50_seconds",
    "Rolling-window p50 stage wall time, by op and stage",
)
_STAGE_P99 = obs_metrics.gauge(
    "ts_op_stage_p99_seconds",
    "Rolling-window p99 stage wall time, by op and stage",
)


def slo_threshold(env_name: str) -> Optional[float]:
    """The configured threshold, or None when unset/disabled. Read per
    check (not cached) so a live operator can retune a running process."""
    raw = os.environ.get(env_name)
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        return None


# Rate-limit state for SLO breach logs: slo name -> last log monotonic.
# Inherited pre-fork contents only delay a child's first breach log by one
# window — no correctness or resource impact, so no fork hook is needed.
_last_slo_log: dict[str, float] = {}  # tslint: disable=fork-safety
_SLO_LOG_EVERY_S = 5.0


def check_slo(
    env_name: str,
    value: float,
    worse: str = "above",
    **context,
) -> bool:
    """Check ``value`` against the env-configured threshold; on breach,
    bump ``ts_slo_violations_total{slo=...}`` and log (rate-limited).
    ``worse="above"`` breaches when value > threshold; ``"below"`` when
    value < threshold (e.g. overlap ratio). Returns whether it breached."""
    threshold = slo_threshold(env_name)
    if threshold is None:
        return False
    breached = value > threshold if worse == "above" else value < threshold
    if not breached:
        return False
    # slo_name() is THE label derivation: the violation counter's label
    # here and slo_report's lookup key must never diverge, or every
    # scoreboard violation count silently reads zero.
    slo = slo_name(env_name)
    _SLO_VIOLATIONS.inc(slo=slo)
    now = time.monotonic()
    if now - _last_slo_log.get(slo, 0.0) >= _SLO_LOG_EVERY_S:
        _last_slo_log[slo] = now
        from torchstore_tpu.logging import get_logger

        get_logger("torchstore_tpu.observability").warning(
            "SLO violation: %s=%.4g %s threshold %.4g%s",
            slo,
            value,
            "above" if worse == "above" else "below",
            threshold,
            f" ({context})" if context else "",
        )
    from torchstore_tpu.observability import recorder as obs_recorder

    obs_recorder.record(
        "slo", slo, value=round(float(value), 6), threshold=threshold
    )
    return True


class OpQuantiles:
    """Rolling per-op quantile digest: a bounded deque of recent wall
    times; p50/p99 gauges refreshed every REFRESH_EVERY observations (one
    sort of <= WINDOW samples, off the per-op critical path rhythm)."""

    WINDOW = 512
    REFRESH_EVERY = 32

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._samples: dict[str, collections.deque] = {}
        self._pending: dict[str, int] = {}

    def observe(self, op: str, dur_s: float) -> None:
        with self._lock:
            ring = self._samples.get(op)
            if ring is None:
                ring = self._samples[op] = collections.deque(
                    maxlen=self.WINDOW
                )
            ring.append(dur_s)
            pending = self._pending.get(op, 0) + 1
            if pending < self.REFRESH_EVERY and len(ring) != 1:
                self._pending[op] = pending
                return
            self._pending[op] = 0
            ordered = sorted(ring)
        p50 = ordered[len(ordered) // 2]
        p99 = ordered[min(len(ordered) - 1, int(len(ordered) * 0.99))]
        _P50.set(p50, op=op)
        _P99.set(p99, op=op)
        if op == "put":
            check_slo(SLO_PUT_P99_MS, p99 * 1e3, op=op)
        elif op == "get":
            check_slo(SLO_GET_P99_MS, p99 * 1e3, op=op)

    def quantiles(self, op: str, qs=(0.5, 0.99)) -> Optional[dict]:
        with self._lock:
            ring = self._samples.get(op)
            if not ring:
                return None
            ordered = sorted(ring)
        return {
            repr(q): ordered[min(len(ordered) - 1, int(len(ordered) * q))]
            for q in qs
        }

    def snapshot(self) -> dict:
        with self._lock:
            ops = list(self._samples)
        return {op: self.quantiles(op) for op in ops}

    def reset(self) -> None:
        with self._lock:
            self._samples.clear()
            self._pending.clear()


_quantiles = OpQuantiles()


def op_quantiles() -> OpQuantiles:
    return _quantiles


def observe_op(op: str, dur_s: float) -> None:
    """Feed one completed logical op into the rolling digests (and their
    p99 SLO checks). Called from the client's op completion path."""
    _quantiles.observe(op, dur_s)


# --------------------------------------------------------------------------
# stage attribution (per-(op, stage) digests + dominant-stage totals)
# --------------------------------------------------------------------------


class StageQuantiles:
    """Rolling per-(op, stage) wall-time digests plus decaying per-stage
    time totals. The digests publish ``ts_op_stage_p50/p99_seconds`` on the
    same lazy-refresh rhythm as :class:`OpQuantiles`; the totals are the
    attribution input: when an op's SLO blows, the stage holding the
    largest share of recent wall time is the *dominant* stage — the answer
    ``ts.slo_report()`` surfaces next to each violated threshold.

    Totals decay exponentially in WALL TIME (half-life ``HALF_LIFE_S``),
    applied lazily at each touch, so a stage that dominated an hour ago
    cannot outvote the stage dominating NOW. The decay must be time-based,
    not sample-count-based: stages record at different RATES (put's
    transport leg records once per replica, its plan leg once per batch) —
    a per-stage count-triggered decay would normalize the rate away and
    make steady-state totals proportional to mean segment duration instead
    of aggregate wall time, inverting the dominant-stage vote exactly on
    the long-running fleets this exists for."""

    WINDOW = 512
    REFRESH_EVERY = 32
    HALF_LIFE_S = 60.0

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # (op, stage) -> [ring, pending, total_s, last_decay_monotonic]
        self._state: dict[tuple, list] = {}

    @classmethod
    def _decay_locked(cls, state: list, now: float) -> None:
        dt = now - state[3]
        if dt > 0:
            state[2] *= 0.5 ** (dt / cls.HALF_LIFE_S)
            state[3] = now

    def observe(self, op: str, stage: str, dur_s: float) -> None:
        if stage not in STAGE_CATALOG:
            raise ValueError(
                f"unregistered stage {stage!r} (catalog: "
                f"{sorted(STAGE_CATALOG)}); register it in "
                "observability.timeline.STAGE_CATALOG"
            )
        now = time.monotonic()
        with self._lock:
            state = self._state.get((op, stage))
            if state is None:
                state = self._state[(op, stage)] = [
                    collections.deque(maxlen=self.WINDOW), 0, 0.0, now,
                ]
            ring, pending, _, _ = state
            ring.append(dur_s)
            self._decay_locked(state, now)
            state[2] += dur_s
            state[1] = pending + 1
            if state[1] < self.REFRESH_EVERY and len(ring) != 1:
                return
            state[1] = 0
            ordered = sorted(ring)
        p50 = ordered[len(ordered) // 2]
        p99 = ordered[min(len(ordered) - 1, int(len(ordered) * 0.99))]
        _STAGE_P50.set(p50, op=op, stage=stage)
        _STAGE_P99.set(p99, op=op, stage=stage)

    def breakdown(self, op: str) -> dict[str, dict]:
        """Per-stage view for one op: ``{stage: {"samples", "total_s",
        "p99_s", "share"}}`` with ``share`` the stage's fraction of the
        op's summed (decayed) stage time."""
        now = time.monotonic()
        with self._lock:
            rows = {}
            for (o, stage), state in self._state.items():
                if o != op:
                    continue
                # Decay every stage to the SAME instant before comparing:
                # an idle stage must not keep a stale (undecayed) total.
                self._decay_locked(state, now)
                rows[stage] = (list(state[0]), state[2])
        out: dict[str, dict] = {}
        grand = sum(total for _, total in rows.values()) or 0.0
        for stage, (samples, total) in rows.items():
            ordered = sorted(samples)
            out[stage] = {
                "samples": len(samples),
                "total_s": round(total, 6),
                "p99_s": (
                    ordered[min(len(ordered) - 1, int(len(ordered) * 0.99))]
                    if ordered
                    else None
                ),
                "share": round(total / grand, 4) if grand > 0 else 0.0,
            }
        return out

    def dominant(self, op: str) -> Optional[str]:
        """The stage holding the largest share of ``op``'s recent wall
        time, or None when nothing was recorded."""
        rows = self.breakdown(op)
        if not rows:
            return None
        return max(rows.items(), key=lambda kv: kv[1]["total_s"])[0]

    def snapshot(self) -> dict[str, dict]:
        with self._lock:
            ops = sorted({op for op, _ in self._state})
        return {op: self.breakdown(op) for op in ops}

    def reset(self) -> None:
        with self._lock:
            self._state.clear()


_stages = StageQuantiles()


def stage_quantiles() -> StageQuantiles:
    return _stages


def observe_stage(op: str, stage: str, dur_s: float) -> None:
    """Record one wall-clock stage segment of a logical op. ``stage`` MUST
    name a :data:`STAGE_CATALOG` entry (raises ValueError otherwise — the
    ``stage-discipline`` tslint rule catches drift statically; this is the
    loud runtime backstop)."""
    _stages.observe(op, stage, dur_s)


def dominant_stage(op: str) -> Optional[str]:
    """Which stage of ``op`` recent wall time concentrated in."""
    return _stages.dominant(op)


# --------------------------------------------------------------------------
# SLO scoreboard
# --------------------------------------------------------------------------

# env knob -> (worse direction, the op whose stage digests attribute a
# breach, a callable producing the CURRENT value in threshold units).
def _p99_ms(op: str):
    def current() -> Optional[float]:
        qs = _quantiles.quantiles(op, qs=(0.99,))
        return None if qs is None else qs["0.99"] * 1e3

    return current


def _gauge_value(name: str, scale: float = 1.0):
    def current() -> Optional[float]:
        metric = obs_metrics.get_registry().get(name)
        if metric is None:
            return None
        series = metric.snapshot().get("series") or []
        if not series:
            return None
        # Labeled gauges (channel=...): the scoreboard reports the worst
        # series — an SLO is about the worst-off consumer.
        return max(float(s["value"]) for s in series) * scale

    return current


_SLO_TABLE: dict[str, tuple[str, Optional[str], Any]] = {
    SLO_PUT_P99_MS: ("above", "put", _p99_ms("put")),
    SLO_GET_P99_MS: ("above", "get", _p99_ms("get")),
    SLO_VERSION_LAG: (
        "above", None, _gauge_value("ts_weight_channel_version_lag"),
    ),
    SLO_FIRST_LAYER_MS: (
        "above", "stream", _gauge_value("ts_stream_first_layer_seconds", 1e3),
    ),
    SLO_OVERLAP_MIN: (
        "below", "stream", _gauge_value("ts_stream_overlap_ratio"),
    ),
}

_SLO_PREFIX = "TORCHSTORE_TPU_SLO_"


def slo_name(env_name: str) -> str:
    return env_name.rsplit(_SLO_PREFIX, 1)[-1].lower()


def slo_report() -> dict:
    """This process's live SLO scoreboard: every configured
    ``TORCHSTORE_TPU_SLO_*`` threshold (the blessed family plus any
    operator-extension knobs set under the prefix) with its current value,
    lifetime violation count, violated flag, and — for SLOs whose op has
    stage digests — the dominant stage with the full per-stage breakdown.

    Returns ``{"slos": {name: {...}}, "stages": {op: breakdown},
    "trends": {detector: result}, "generated_ts": wall_ts}``.
    ``ts.slo_report()`` wraps this with fleet
    overload signals; loadgen drivers ship it home per process and
    ``loadgen.report.merge_slo_reports`` folds driver scoreboards into the
    fleet view."""
    names = dict(_SLO_TABLE)
    for env_name in os.environ:
        if env_name.startswith(_SLO_PREFIX) and env_name not in names:
            names[env_name] = ("above", None, lambda: None)
    slos: dict[str, dict] = {}
    for env_name, (worse, op, current_fn) in names.items():
        threshold = slo_threshold(env_name)
        if threshold is None:
            continue
        name = slo_name(env_name)
        current = current_fn()
        violations = int(_SLO_VIOLATIONS.value(slo=name))
        violated = current is not None and (
            current > threshold if worse == "above" else current < threshold
        )
        entry: dict[str, Any] = {
            "env": env_name,
            "threshold": threshold,
            "worse": worse,
            "current": None if current is None else round(current, 4),
            "violations": violations,
            "violated": bool(violated),
            "op": op,
        }
        if op is not None and (violated or violations):
            entry["dominant_stage"] = _stages.dominant(op)
            entry["stages"] = _stages.breakdown(op)
        slos[name] = entry
    # Trend detectors over the local history rings: the "is this a burst
    # or a regime change" companion to the instantaneous gates above.
    # History may be disabled (TORCHSTORE_TPU_HISTORY=0) or mid-bootstrap;
    # the scoreboard must not care.
    try:
        from torchstore_tpu.observability import detect as obs_detect

        trends = obs_detect.evaluate_trends()
    except Exception:  # noqa: BLE001 - scoreboard survives without trends
        trends = {}
    return {
        "slos": slos,
        "stages": _stages.snapshot(),
        "trends": trends,
        "generated_ts": time.time(),
    }


# --------------------------------------------------------------------------
# generation reconstruction (controller stream records -> lifecycle)
# --------------------------------------------------------------------------


def reconstruct(state: Optional[dict]) -> Optional[dict]:
    """Fold a timestamped controller stream record (``stream_state``) into
    one generation lifecycle:

    ``{"version", "sealed", "begin_ts", "seal_ts", "publish_window_s",
    "first_layer_s", "landings": [{"key", "ts", "offset_s"}, ...],
    "subscribers": {sub: {"version", "ts", "completion_s"}}}``

    ``offset_s``/``completion_s`` are relative to ``begin_ts``. Returns
    None for a missing record; fields are None when the record predates
    the timestamping (controller upgrade mid-run)."""
    if state is None:
        return None
    begin_ts = state.get("begin_ts")
    seal_ts = state.get("seal_ts")
    landing_ts: dict = state.get("landing_ts") or {}
    landings = [
        {
            "key": key,
            "ts": ts,
            "offset_s": (
                round(ts - begin_ts, 6) if begin_ts is not None else None
            ),
        }
        for key, ts in sorted(landing_ts.items(), key=lambda kv: kv[1])
    ]
    first_layer_s = (
        round(landings[0]["ts"] - begin_ts, 6)
        if landings and begin_ts is not None
        else None
    )
    subscribers = {
        sub: {
            "version": ack.get("version"),
            "ts": ack.get("ts"),
            "completion_s": (
                round(ack["ts"] - begin_ts, 6)
                if begin_ts is not None and ack.get("ts") is not None
                else None
            ),
        }
        for sub, ack in (state.get("acks") or {}).items()
    }
    return {
        "version": state.get("version"),
        "sealed": state.get("sealed"),
        "begin_ts": begin_ts,
        "seal_ts": seal_ts,
        "publish_window_s": (
            round(seal_ts - begin_ts, 6)
            if begin_ts is not None and seal_ts is not None
            else None
        ),
        "first_layer_s": first_layer_s,
        "landings": landings,
        "subscribers": subscribers,
    }


def subscriber_id() -> str:
    """This process's identity in stream acquire acks (bounded: one entry
    per process per stream record)."""
    from torchstore_tpu.utils import get_hostname

    return f"{get_hostname()}:{os.getpid()}"
