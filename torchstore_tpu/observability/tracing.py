"""Span tracing: public Chrome-trace emission for store operations.

Generalizes the private ``_TraceCollector`` that used to live in
``torchstore_tpu/logging.py`` into a public subsystem: set
``TORCHSTORE_TPU_TRACE=/path/trace.json`` and every ``span(...)`` — put/get
batches, per-volume fetches, transport transfers, resharding assembly,
weight-channel publishes — lands as a Chrome-trace complete event. The file
loads directly in Perfetto / chrome://tracing and aligns store phases with
jax profiler traces on one timeline.

Usage (sync context manager; works around ``await`` since it only brackets
wall time):

    from torchstore_tpu.observability import span

    with span("put_batch", keys=3, nbytes=total, transport="shm") as sp:
        ...
        sp.set(volume=vid)          # attrs may be added mid-span

Cost when disabled (no env var): one ``perf_counter`` call per span and an
attribute check — nothing is buffered.

Events stream to disk in the JSON *array* format, appending every
``FLUSH_EVERY`` events — the format's closing ``]`` is optional, so the file
is loadable after a crash and memory stays bounded in long-running loops.
One file per process: the path is claimed with O_EXCL (volume actors and the
client all trace) and losers take a pid-suffixed name.
"""

from __future__ import annotations

import atexit
import glob as _glob
import json
import os
import re
import threading
import time
from typing import Optional

from torchstore_tpu.observability import context as trace_context
from torchstore_tpu.observability.metrics import _pid_alive

ENV_TRACE = "TORCHSTORE_TPU_TRACE"
# One id per RUN (process tree): minted by the first process to claim a
# trace file, inherited by every actor child through the TORCHSTORE_TPU_*
# env forwarding. Distinguishes "sibling of this run already exited" (its
# events must survive into the merge) from "leftover file of a FINISHED
# run" (must be cleared, or tpu_watch's reused OUTDIR merges dead spans).
ENV_TRACE_RUN = "TORCHSTORE_TPU_TRACE_RUN"


def _current_run_id() -> str:
    rid = os.environ.get(ENV_TRACE_RUN)
    if not rid:
        rid = f"{os.getpid()}.{trace_context.new_id()}"
        os.environ[ENV_TRACE_RUN] = rid
    return rid


# spawn_actors calls this BEFORE forwarding env to children, so the whole
# process tree shares one run id (a child minting its own would mistake an
# exited sibling's file for a dead run's and truncate it).
ensure_run_id = _current_run_id


def process_label() -> str:
    """Human-readable track label for this process in a merged trace.
    Actor children are named ``ts-<actor>-<rank>`` by spawn_actors; the
    initiating process shows up as its script (or ``MainProcess``)."""
    import multiprocessing as mp
    import sys

    name = mp.current_process().name
    if name in ("MainProcess", None, ""):
        argv0 = os.path.basename(sys.argv[0] or "") or "python"
        name = argv0
    return f"{name}[{os.getpid()}]"


class TraceCollector:
    """Process-global Chrome-trace event buffer (enabled by env var)."""

    FLUSH_EVERY = 1000

    def __init__(self) -> None:
        self.path = os.environ.get(ENV_TRACE)
        self.events: list[dict] = []
        self._lock = threading.Lock()
        self._registered = False
        self._resolved_path: Optional[str] = None
        self._resolved_for: Optional[str] = None
        self._wrote_header = False

    @property
    def enabled(self) -> bool:
        return bool(self.path)

    def add_event(
        self,
        name: str,
        start_s: float,
        dur_s: float,
        args: Optional[dict] = None,
    ) -> None:
        """Record one complete ('X') event. ``args`` ride into the trace's
        ``args`` pane; a ``bytes`` entry gets a derived GBps alongside."""
        if not self.path:
            return
        event = {
            "name": name,
            "cat": "torchstore",
            "ph": "X",
            "ts": start_s * 1e6,
            "dur": dur_s * 1e6,
            "pid": os.getpid(),
            "tid": threading.get_ident() & 0xFFFF,
        }
        if args:
            args = dict(args)
            nbytes = args.get("bytes")
            if isinstance(nbytes, (int, float)) and "GBps" not in args:
                args["GBps"] = (
                    round(nbytes / dur_s / 1e9, 3) if dur_s > 0 else None
                )
            event["args"] = args
        with self._lock:
            self.events.append(event)
            if not self._registered:
                self._registered = True
                atexit.register(self.flush)
            if len(self.events) >= self.FLUSH_EVERY:
                self._flush_locked()

    def add(
        self,
        name: str,
        phase: str,
        start_s: float,
        dur_s: float,
        nbytes: Optional[int],
    ) -> None:
        """LatencyTracker-shaped entry point (``{name}/{phase}`` naming) —
        kept so the tracker's phases land in the same trace as spans."""
        args = {"bytes": nbytes} if nbytes is not None else None
        self.add_event(f"{name}/{phase}", start_s, dur_s, args)

    def _resolve_path(self) -> str:
        # Claim the base path through a ``<base>.owner`` sidecar recording
        # the claimant's pid (same arbitration as the metrics dumper): a
        # LIVE concurrent process owning it sends us to a pid-suffixed
        # sibling, but a leftover file from a FINISHED run is taken over and
        # truncated — tpu_watch reuses its OUTDIR across runs, and a stale
        # base full of dead spans must not pollute the next merge. The pid
        # path is always truncated on claim: any existing content is ours
        # from a previous resolution or a recycled pid's dead run, and
        # appending to it would emit a second '[' header (corrupt JSON).
        if self._resolved_path is None or self._resolved_for != self.path:
            base = self.path
            root, ext = os.path.splitext(base)
            pid_path = f"{root}.{os.getpid()}{ext or '.json'}"
            self._resolved_path = self._claim(base, pid_path)
            self._resolved_for = self.path
            self._wrote_header = False
        return self._resolved_path

    @staticmethod
    def _claim(base: str, pid_path: str) -> str:
        def truncate(path: str) -> None:
            os.close(os.open(path, os.O_CREAT | os.O_TRUNC | os.O_WRONLY, 0o644))

        # The whole decide-and-claim sequence runs under an exclusive flock
        # on the owner sidecar: two processes racing a stale claim must
        # never BOTH conclude "dead owner, mine" — each would truncate the
        # other's header mid-append and corrupt the base file. flock is
        # released by the kernel even on SIGKILL, so a crashed claimant
        # can't wedge the path.
        import fcntl

        pid = os.getpid()
        run_id = _current_run_id()
        payload = f"{pid}\n{run_id}"
        try:
            fd = os.open(f"{base}.owner", os.O_CREAT | os.O_RDWR, 0o644)
        except OSError:
            try:
                truncate(pid_path)
            except OSError:
                pass
            return pid_path
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            lines = os.read(fd, 256).decode(errors="replace").splitlines()
            try:
                owner = int((lines[0] if lines else "").strip() or 0)
            except ValueError:
                owner = 0
            owner_run = lines[1].strip() if len(lines) > 1 else ""
            if owner and owner_run == run_id and owner != pid:
                # A sibling process of THIS run owns the base — alive, or
                # already exited with its events in the file. Either way
                # those events belong in the merge: take a pid path.
                claim_base = False
            elif owner and owner_run != run_id and _pid_alive(owner):
                claim_base = False  # live owner from another run
            else:
                # Unclaimed, our own re-claim, or a FINISHED run's leftover:
                # take the base and clear any dead run's file set so stale
                # spans can't pollute this run's merge.
                claim_base = True
            if claim_base:
                os.ftruncate(fd, 0)
                os.lseek(fd, 0, os.SEEK_SET)
                os.write(fd, payload.encode())
                truncate(base)
                if owner and owner != pid and owner_run != run_id:
                    for stale in trace_files(base):
                        if stale != base:
                            try:
                                os.unlink(stale)
                            except OSError:
                                pass
                return base
        except OSError:
            pass
        finally:
            os.close(fd)  # releases the flock
        try:
            truncate(pid_path)
        except OSError:
            pass
        return pid_path

    def _flush_locked(self) -> None:
        if not self.path or not self.events:
            return
        chunk = self.events
        self.events = []
        try:
            path = self._resolve_path()
            if not self._wrote_header:
                # First write into this file: lead with a process_name
                # metadata event so a merged multi-process trace shows
                # labeled tracks (client / controller / volume_N) instead
                # of bare pids.
                chunk.insert(
                    0,
                    {
                        "name": "process_name",
                        "ph": "M",
                        "pid": os.getpid(),
                        "args": {"name": process_label()},
                    },
                )
            with open(path, "a") as f:
                for event in chunk:
                    f.write("[\n" if not self._wrote_header else ",\n")
                    self._wrote_header = True
                    json.dump(event, f)
        except OSError:
            pass

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def reinit_after_fork(self) -> None:
        """Re-arm in a freshly forked actor child. The forkserver imports
        this module at ITS start (preload), so children inherit a collector
        whose ``path`` snapshot predates the spawner's env — e.g. disabled
        even though TORCHSTORE_TPU_TRACE is set in the child's corrected
        env. Re-read the env and drop any inherited buffer/claim state so
        this process claims its own file."""
        with self._lock:
            self.path = os.environ.get(ENV_TRACE)
            self.events = []
            self._resolved_path = None
            self._resolved_for = None
            self._wrote_header = False


_collector = TraceCollector()


def collector() -> TraceCollector:
    return _collector


def trace_enabled() -> bool:
    return _collector.enabled


def flush_trace() -> None:
    _collector.flush()


class span:
    """Context manager recording one named span with attributes.

    Attrs are arbitrary small values (key, nbytes, transport, volume, shard
    coords); ``bytes``/``nbytes`` get a derived GBps in the trace. Nesting
    works naturally — Chrome's 'X' events on one tid stack by containment.

    When tracing is enabled each span also mints a ``span_id``, records the
    active ``trace_id``/``parent_id`` (see observability/context.py), and
    becomes the parent of anything opened — or any RPC issued — inside it,
    so per-process files merge into one cross-process tree.
    """

    __slots__ = ("name", "attrs", "_t0", "_span_id", "_token")

    def __init__(self, name: str, **attrs) -> None:
        self.name = name
        self.attrs = attrs
        self._t0 = 0.0
        self._span_id = None
        self._token = None

    def set(self, **attrs) -> "span":
        self.attrs.update(attrs)
        return self

    def __enter__(self) -> "span":
        self._t0 = time.perf_counter()
        if _collector.enabled:
            self._span_id = trace_context.new_id()
            self._token = trace_context.push_span(self._span_id)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        parent = None
        if self._token is not None:
            parent = trace_context.token_parent(self._token)
            trace_context.pop_span(self._token)
            self._token = None
        if not _collector.enabled:
            return
        dur = time.perf_counter() - self._t0
        args = {
            k: (v if isinstance(v, (int, float, bool, type(None))) else str(v))
            for k, v in self.attrs.items()
        }
        if "nbytes" in args and "bytes" not in args:
            args["bytes"] = args.pop("nbytes")
        if exc_type is not None:
            args["error"] = exc_type.__name__
        tid = trace_context.trace_id()
        if tid is not None:
            args["trace_id"] = tid
        if self._span_id is not None:
            args["span_id"] = self._span_id
        if parent is not None:
            args["parent_id"] = parent
        _collector.add_event(self.name, self._t0, dur, args or None)


# --------------------------------------------------------------------------
# cross-process trace merging
# --------------------------------------------------------------------------


def load_trace_events(path: str) -> list[dict]:
    """Events from one per-process trace file. The streaming writer leaves
    the closing ``]`` off (crash-safe JSON-array format) — repair it here."""
    try:
        with open(path) as f:
            content = f.read().strip()
    except OSError:
        return []
    if not content:
        return []
    if not content.endswith("]"):
        content += "\n]"
    try:
        events = json.loads(content)
    except ValueError:
        return []
    return [e for e in events if isinstance(e, dict)]


def trace_files(base: str) -> list[str]:
    """The per-process trace files belonging to one configured base path:
    the base itself (claimed by whichever process flushed first) plus every
    pid-suffixed sibling (``<root>.<pid><ext>``). Merged outputs and other
    non-numeric siblings are excluded."""
    root, ext = os.path.splitext(base)
    ext = ext or ".json"
    pid_re = re.compile(re.escape(root) + r"\.(\d+)" + re.escape(ext) + r"$")
    out = []
    if os.path.exists(base):
        out.append(base)
    for cand in sorted(_glob.glob(f"{root}.*{ext}")):
        if pid_re.match(cand):
            out.append(cand)
    return out


def merge_traces(paths: list[str], out_path: str) -> dict:
    """Merge per-process trace files into one Perfetto-loadable timeline.

    Events keep their originating pid (one track per process, labeled by
    each file's ``process_name`` metadata event) and are ordered by
    timestamp; the shared ``trace_id`` args stitch one logical operation
    across tracks. Returns ``{"path", "files", "events", "trace_ids"}``."""
    events: list[dict] = []
    for path in paths:
        events.extend(load_trace_events(path))
    meta = [e for e in events if e.get("ph") == "M"]
    rest = [e for e in events if e.get("ph") != "M"]
    rest.sort(key=lambda e: e.get("ts", 0))
    merged = meta + rest
    tmp = f"{out_path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(merged, f)
    os.replace(tmp, out_path)
    trace_ids = {
        e["args"]["trace_id"]
        for e in rest
        if isinstance(e.get("args"), dict) and "trace_id" in e["args"]
    }
    return {
        "path": out_path,
        "files": list(paths),
        "events": len(rest),
        "trace_ids": sorted(trace_ids),
    }


def collect_trace(out_path: Optional[str] = None) -> Optional[dict]:
    """Flush this process's collector and merge every sibling process's
    trace file (same configured base path) into one timeline. Returns the
    merge summary dict, or None when tracing is disabled. Call after the
    store is shut down so actor processes have flushed their atexit dumps;
    default output is ``<root>.merged<ext>``."""
    base = _collector.path or os.environ.get(ENV_TRACE)
    if not base:
        return None
    _collector.flush()
    files = trace_files(base)
    if not files:
        return None
    if out_path is None:
        root, ext = os.path.splitext(base)
        out_path = f"{root}.merged{ext or '.json'}"
    return merge_traces(files, out_path)
