"""Span tracing: public Chrome-trace emission for store operations.

Generalizes the private ``_TraceCollector`` that used to live in
``torchstore_tpu/logging.py`` into a public subsystem: set
``TORCHSTORE_TPU_TRACE=/path/trace.json`` and every ``span(...)`` — put/get
batches, per-volume fetches, transport transfers, resharding assembly,
weight-channel publishes — lands as a Chrome-trace complete event. The file
loads directly in Perfetto / chrome://tracing and aligns store phases with
jax profiler traces on one timeline.

Usage (sync context manager; works around ``await`` since it only brackets
wall time):

    from torchstore_tpu.observability import span

    with span("put_batch", keys=3, nbytes=total, transport="shm") as sp:
        ...
        sp.set(volume=vid)          # attrs may be added mid-span

Cost when disabled (no env var): one ``perf_counter`` call per span and an
attribute check — nothing is buffered.

Events stream to disk in the JSON *array* format, appending every
``FLUSH_EVERY`` events — the format's closing ``]`` is optional, so the file
is loadable after a crash and memory stays bounded in long-running loops.
One file per process: the path is claimed with O_EXCL (volume actors and the
client all trace) and losers take a pid-suffixed name.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from typing import Optional

ENV_TRACE = "TORCHSTORE_TPU_TRACE"


class TraceCollector:
    """Process-global Chrome-trace event buffer (enabled by env var)."""

    FLUSH_EVERY = 1000

    def __init__(self) -> None:
        self.path = os.environ.get(ENV_TRACE)
        self.events: list[dict] = []
        self._lock = threading.Lock()
        self._registered = False
        self._resolved_path: Optional[str] = None
        self._resolved_for: Optional[str] = None
        self._wrote_header = False

    @property
    def enabled(self) -> bool:
        return bool(self.path)

    def add_event(
        self,
        name: str,
        start_s: float,
        dur_s: float,
        args: Optional[dict] = None,
    ) -> None:
        """Record one complete ('X') event. ``args`` ride into the trace's
        ``args`` pane; a ``bytes`` entry gets a derived GBps alongside."""
        if not self.path:
            return
        event = {
            "name": name,
            "cat": "torchstore",
            "ph": "X",
            "ts": start_s * 1e6,
            "dur": dur_s * 1e6,
            "pid": os.getpid(),
            "tid": threading.get_ident() & 0xFFFF,
        }
        if args:
            args = dict(args)
            nbytes = args.get("bytes")
            if isinstance(nbytes, (int, float)) and "GBps" not in args:
                args["GBps"] = (
                    round(nbytes / dur_s / 1e9, 3) if dur_s > 0 else None
                )
            event["args"] = args
        with self._lock:
            self.events.append(event)
            if not self._registered:
                self._registered = True
                atexit.register(self.flush)
            if len(self.events) >= self.FLUSH_EVERY:
                self._flush_locked()

    def add(
        self,
        name: str,
        phase: str,
        start_s: float,
        dur_s: float,
        nbytes: Optional[int],
    ) -> None:
        """LatencyTracker-shaped entry point (``{name}/{phase}`` naming) —
        kept so the tracker's phases land in the same trace as spans."""
        args = {"bytes": nbytes} if nbytes is not None else None
        self.add_event(f"{name}/{phase}", start_s, dur_s, args)

    def _resolve_path(self) -> str:
        # Re-resolve if the target changed (tests swap it) — and CLAIM the
        # file with O_EXCL: two processes exists()-checking concurrently
        # would interleave appends into one corrupt file. The loser takes a
        # pid-suffixed name.
        if self._resolved_path is None or self._resolved_for != self.path:
            base = self.path
            root, ext = os.path.splitext(base)
            pid_path = f"{root}.{os.getpid()}{ext or '.json'}"
            chosen = pid_path
            for cand in (base, pid_path):
                try:
                    os.close(
                        os.open(cand, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644)
                    )
                    chosen = cand
                    break
                except FileExistsError:
                    continue
                except OSError:
                    break
            self._resolved_path = chosen
            self._resolved_for = self.path
            self._wrote_header = False
        return self._resolved_path

    def _flush_locked(self) -> None:
        if not self.path or not self.events:
            return
        chunk = self.events
        self.events = []
        try:
            with open(self._resolve_path(), "a") as f:
                for event in chunk:
                    f.write("[\n" if not self._wrote_header else ",\n")
                    self._wrote_header = True
                    json.dump(event, f)
        except OSError:
            pass

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()


_collector = TraceCollector()


def collector() -> TraceCollector:
    return _collector


def trace_enabled() -> bool:
    return _collector.enabled


def flush_trace() -> None:
    _collector.flush()


class span:
    """Context manager recording one named span with attributes.

    Attrs are arbitrary small values (key, nbytes, transport, volume, shard
    coords); ``bytes``/``nbytes`` get a derived GBps in the trace. Nesting
    works naturally — Chrome's 'X' events on one tid stack by containment.
    """

    __slots__ = ("name", "attrs", "_t0")

    def __init__(self, name: str, **attrs) -> None:
        self.name = name
        self.attrs = attrs
        self._t0 = 0.0

    def set(self, **attrs) -> "span":
        self.attrs.update(attrs)
        return self

    def __enter__(self) -> "span":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if not _collector.enabled:
            return
        dur = time.perf_counter() - self._t0
        args = {
            k: (v if isinstance(v, (int, float, bool, type(None))) else str(v))
            for k, v in self.attrs.items()
        }
        if "nbytes" in args and "bytes" not in args:
            args["bytes"] = args.pop("nbytes")
        if exc_type is not None:
            args["error"] = exc_type.__name__
        _collector.add_event(self.name, self._t0, dur, args or None)
