"""Hot-key / slow-op profiler: who is hammering the store, and what stalled.

Two process-local facilities fed from the client's logical ops and each
volume's data-plane RPCs:

- **Hot keys**: a rolling per-key tally of ops and bytes (bounded — when the
  table overflows ``MAX_KEYS`` the coldest half is dropped, so a key-churny
  workload can't grow it unboundedly). ``hot_keys(k)`` returns the top-K by
  bytes; volumes embed theirs in ``stats()`` and ``ts.fleet_snapshot()``
  collects the whole fleet's — the first question of any traffic
  investigation ("which key is 90% of the bytes?") answered without a trace.

- **Slow ops**: set ``TORCHSTORE_TPU_SLOW_OP_MS`` and any recorded operation
  whose wall time crosses the threshold is (1) logged with key/bytes/
  duration and the active trace id, (2) counted in ``ts_slow_ops_total``
  (labeled by op), and (3) emitted as a ``slow_op/<op>`` trace event when
  tracing is enabled — so outliers are findable in metrics, logs, AND the
  merged timeline. Unset, the check is one env read + a float compare.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from torchstore_tpu.observability import context as trace_context
from torchstore_tpu.observability import metrics as obs_metrics
from torchstore_tpu.observability import tracing

ENV_SLOW_OP_MS = "TORCHSTORE_TPU_SLOW_OP_MS"

_SLOW_OPS = obs_metrics.counter(
    "ts_slow_ops_total",
    "Operations slower than TORCHSTORE_TPU_SLOW_OP_MS, by op",
)


def slow_op_threshold_s() -> Optional[float]:
    """The configured slow-op threshold in seconds, or None when disabled.
    Read per call (not cached) so tests and live operators can retune a
    running process; one getenv is noise next to any op worth profiling."""
    raw = os.environ.get(ENV_SLOW_OP_MS)
    if not raw:
        return None
    try:
        return float(raw) / 1e3
    except ValueError:
        return None


class HotKeyTracker:
    """Rolling per-key op/byte tally (process-local, lock-protected)."""

    MAX_KEYS = 4096

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._keys: dict[str, list] = {}  # key -> [ops, bytes]

    def record(self, key: str, nbytes: int = 0) -> None:
        with self._lock:
            stat = self._keys.get(key)
            if stat is None:
                if len(self._keys) >= self.MAX_KEYS:
                    self._evict_cold_locked()
                stat = self._keys[key] = [0, 0]
            stat[0] += 1
            stat[1] += int(nbytes)

    def record_many(self, items, weight: int = 1) -> None:
        """Batch tally: ``[(key, nbytes), ...]`` under ONE lock acquisition
        — the zero-RPC one-sided read path records thousands of keys per
        warm batch, and a per-key lock round trip there would be the
        single biggest telemetry cost (bench ``ledger_overhead``).
        ``weight`` scales a SAMPLED feed back to expectation (the one-sided
        accounting records 1-in-N large batches at weight N)."""
        with self._lock:
            keys = self._keys
            for key, nbytes in items:
                stat = keys.get(key)
                if stat is None:
                    if len(keys) >= self.MAX_KEYS:
                        self._evict_cold_locked()
                        keys = self._keys
                    stat = keys[key] = [0, 0]
                stat[0] += weight
                stat[1] += int(nbytes) * weight

    def _evict_cold_locked(self) -> None:
        # Keep the hottest half by bytes (ops as tiebreak): the keys an
        # operator would ask about survive churn from one-shot keys.
        survivors = sorted(
            self._keys.items(), key=lambda kv: (kv[1][1], kv[1][0]), reverse=True
        )[: self.MAX_KEYS // 2]
        self._keys = dict(survivors)

    def top(self, k: int = 10, by: str = "bytes") -> list[dict]:
        idx = 1 if by == "bytes" else 0
        with self._lock:
            items = sorted(
                self._keys.items(), key=lambda kv: kv[1][idx], reverse=True
            )[:k]
        return [
            {"key": key, "ops": stat[0], "bytes": stat[1]}
            for key, stat in items
        ]

    def reset(self) -> None:
        with self._lock:
            self._keys.clear()


_tracker = HotKeyTracker()
# Labeled tracker for zero-RPC stamped reads (the PR-7 profiler blind
# spot): one-sided serves never touch a volume, so no volume's data-plane
# ``stats()["hot_keys"]`` can ever see them — and folding them into the
# client's LOGICAL tally would double-count (every logical get already
# records there). A separate labeled view keeps placement data complete
# without inflating either.
_one_sided_tracker = HotKeyTracker()


def hot_key_tracker(source: str = "ops") -> HotKeyTracker:
    return _one_sided_tracker if source == "one_sided" else _tracker


def hot_keys(k: int = 10, by: str = "bytes", source: str = "ops") -> list[dict]:
    """This process's top-K keys (``[{"key", "ops", "bytes"}, ...]``).
    ``source="one_sided"`` returns the zero-RPC stamped-read view (bytes
    served without any volume involvement — invisible to every volume's
    own hot-key tally)."""
    return hot_key_tracker(source).top(k, by=by)


def reset_hot_keys() -> None:
    _tracker.reset()
    _one_sided_tracker.reset()


def record_op(
    op: str,
    key: Optional[str],
    nbytes: int,
    start_s: float,
    dur_s: float,
    tally: bool = True,
    **attrs,
) -> None:
    """Record one completed operation: feeds the hot-key tally and, past the
    env threshold, the slow-op log/counter/trace annotation. ``start_s`` is
    the ``perf_counter`` start so the trace annotation lands at the right
    place on the timeline."""
    if tally and key is not None:
        _tracker.record(key, nbytes)
    threshold = slow_op_threshold_s()
    if threshold is None or dur_s < threshold:
        return
    _SLOW_OPS.inc(op=op)
    tid = trace_context.trace_id()
    from torchstore_tpu.logging import get_logger

    get_logger("torchstore_tpu.observability").warning(
        "slow op: %s key=%r %d bytes took %.1f ms (threshold %.1f ms)%s",
        op,
        key,
        nbytes,
        dur_s * 1e3,
        threshold * 1e3,
        f" [trace {tid}]" if tid else "",
    )
    if tracing.trace_enabled():
        args = {"op": op, "key": key, "bytes": nbytes, "slow": True, **attrs}
        if tid is not None:
            args["trace_id"] = tid
        tracing.collector().add_event(f"slow_op/{op}", start_s, dur_s, args)


def record_keys(op: str, items, start_s: float, dur_s: float) -> None:
    """Batch entry point: ``items`` is ``[(key, nbytes), ...]`` — every key
    feeds the hot-key tally; the slow-op check runs ONCE for the whole batch
    (one RPC, one stall) with the total bytes and a representative key."""
    total = 0
    first_key = None
    for key, nbytes in items:
        if first_key is None:
            first_key = key
        total += int(nbytes)
        _tracker.record(key, nbytes)
    record_op(
        op,
        first_key,
        total,
        start_s,
        dur_s,
        tally=False,  # keys already recorded above
        keys=len(items),
    )
