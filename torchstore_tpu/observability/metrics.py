"""Process-local metrics registry: counters, gauges, fixed-bucket histograms.

Zero dependencies, lock-protected, cheap enough for the data-plane hot path
(a counter inc is one dict get + add under a per-metric lock). Every process
in a store — clients, storage volumes, the controller — carries its own
registry; instruments are process-local by design (aggregation is the
scraper's job, exactly as with Prometheus client libraries). Volume/controller
registries are surfaced through their ``stats()`` endpoints, so
``controller.stats(include_volumes=True)`` collects the whole fleet.

Exporters:

- ``render_prometheus()`` — Prometheus text exposition format (v0.0.4).
- ``render_json()`` / ``snapshot()`` — machine-readable dict/JSON, the form
  ``ts.metrics_snapshot()`` returns and ``bench.py`` emits.

Env-gated periodic dumper: set ``TORCHSTORE_TPU_METRICS_DUMP=/path.json`` (or
``.prom`` for Prometheus text) and every process appends nothing — it
atomically REWRITES its own file (pid-suffixed when the base name is taken)
every ``TORCHSTORE_TPU_METRICS_INTERVAL_S`` seconds (default 60) and once at
exit, so a crashed run still leaves its last-known counters on disk.
"""

from __future__ import annotations

import atexit
import bisect
import json
import os
import threading
import time
from typing import Any, Optional

ENV_METRICS_DUMP = "TORCHSTORE_TPU_METRICS_DUMP"
ENV_METRICS_INTERVAL = "TORCHSTORE_TPU_METRICS_INTERVAL_S"

# (sorted (key, value) pairs) — the canonical identity of one labeled series.
LabelKey = "tuple[tuple[str, str], ...]"


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Metric:
    """Base: one named instrument holding one series per label-set."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "") -> None:
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._series: dict[tuple, Any] = {}

    def clear(self) -> None:
        with self._lock:
            self._series.clear()

    def _snapshot_series(self) -> list[dict]:
        with self._lock:
            return [
                {"labels": dict(key), "value": value}
                for key, value in self._series.items()
            ]

    def snapshot(self) -> dict:
        return {
            "kind": self.kind,
            "help": self.help,
            "series": self._snapshot_series(),
        }


class Counter(Metric):
    """Monotonic counter. ``inc(n)`` only; negative increments are rejected
    (that's what gauges are for)."""

    kind = "counter"

    def inc(self, n: float = 1, **labels) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease (n={n})")
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0) + n

    def value(self, **labels) -> float:
        with self._lock:
            return self._series.get(_label_key(labels), 0)

    def total(self) -> float:
        """Sum across every label-set (convenience for tests/benches)."""
        with self._lock:
            return sum(self._series.values())


class Gauge(Metric):
    """Point-in-time value; settable, incrementable, decrementable."""

    kind = "gauge"

    def set(self, v: float, **labels) -> None:
        with self._lock:
            self._series[_label_key(labels)] = v

    def inc(self, n: float = 1, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0) + n

    def dec(self, n: float = 1, **labels) -> None:
        self.inc(-n, **labels)

    def value(self, **labels) -> float:
        with self._lock:
            return self._series.get(_label_key(labels), 0)


# Spans from microseconds (colocated gets) to minutes (model-scale DCN sync).
DEFAULT_LATENCY_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)
# 1 KB .. 16 GB in decade-ish steps (weight-sync payloads).
DEFAULT_BYTES_BUCKETS = (
    1024.0, 16384.0, 65536.0, 1 << 20, 16 << 20, 64 << 20, 256 << 20,
    1 << 30, 4 << 30, 16 << 30,
)


class Histogram(Metric):
    """Fixed-bucket histogram (Prometheus semantics: cumulative ``le``
    buckets plus ``sum``/``count``). Buckets are chosen at creation and
    never change, so ``observe`` is a binary search + two adds."""

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str = "",
        buckets: Optional[tuple] = None,
    ) -> None:
        super().__init__(name, help)
        self.buckets = tuple(sorted(buckets or DEFAULT_LATENCY_BUCKETS))

    def observe(self, v: float, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            state = self._series.get(key)
            if state is None:
                state = self._series[key] = {
                    "counts": [0] * (len(self.buckets) + 1),
                    "sum": 0.0,
                    "count": 0,
                }
            state["counts"][bisect.bisect_left(self.buckets, v)] += 1
            state["sum"] += v
            state["count"] += 1

    def value(self, **labels) -> Optional[dict]:
        """{"sum", "count", "buckets": {le: cumulative_count}} or None."""
        with self._lock:
            state = self._series.get(_label_key(labels))
            if state is None:
                return None
            return self._cumulative(state)

    def _cumulative(self, state: dict) -> dict:
        out: dict[str, Any] = {"sum": state["sum"], "count": state["count"]}
        cum = 0
        buckets: dict[str, int] = {}
        for le, n in zip(self.buckets, state["counts"]):
            cum += n
            buckets[repr(le)] = cum
        buckets["+Inf"] = cum + state["counts"][-1]
        out["buckets"] = buckets
        return out

    def _snapshot_series(self) -> list[dict]:
        with self._lock:
            return [
                {"labels": dict(key), "value": self._cumulative(state)}
                for key, state in self._series.items()
            ]


class MetricsRegistry:
    """Named instruments, get-or-create. One per process (module singleton);
    tests may build private ones."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: dict[str, Metric] = {}

    def _get_or_create(self, cls, name: str, help: str, **kwargs) -> Metric:
        with self._lock:
            metric = self._metrics.get(name)
            if metric is None:
                metric = self._metrics[name] = cls(name, help, **kwargs)
            elif not isinstance(metric, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {metric.kind}, "
                    f"not {cls.kind}"
                )
            return metric

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(
        self, name: str, help: str = "", buckets: Optional[tuple] = None
    ) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def reset(self) -> None:
        """Zero every series. The Metric OBJECTS survive — instruments are
        cached in module globals all over the codebase, and reset (tests,
        bench warmup) must not orphan them from the registry."""
        with self._lock:
            metrics = list(self._metrics.values())
        for metric in metrics:
            metric.clear()

    # ---- exporters -------------------------------------------------------

    def snapshot(self) -> dict:
        """{metric_name: {"kind", "help", "series": [...]}} — plain data,
        JSON-serializable, stable field names."""
        with self._lock:
            metrics = dict(self._metrics)
        return {name: metric.snapshot() for name, metric in sorted(metrics.items())}

    def sample_values(self) -> list[tuple]:
        """Flat numeric view for the history sampler: one
        ``(name, kind, label_key, value)`` row per labeled series, where
        ``label_key`` is the canonical sorted ``((k, v), ...)`` tuple.
        Histograms are sampled as their ``<name>_count`` counter — the
        per-bucket vectors belong to scrapes, not 1 Hz retention."""
        with self._lock:
            metrics = list(self._metrics.values())
        rows: list[tuple] = []
        for metric in metrics:
            if isinstance(metric, Histogram):
                with metric._lock:
                    items = [
                        (key, float(state["count"]))
                        for key, state in metric._series.items()
                    ]
                name = metric.name + "_count"
                for key, count in items:
                    rows.append((name, "counter", key, count))
            else:
                with metric._lock:
                    items = [
                        (key, float(value))
                        for key, value in metric._series.items()
                    ]
                for key, value in items:
                    rows.append((metric.name, metric.kind, key, value))
        return rows

    def render_json(self) -> str:
        return json.dumps(
            {"ts": time.time(), "pid": os.getpid(), "metrics": self.snapshot()}
        )

    def render_prometheus(self) -> str:
        """Prometheus text exposition format (0.0.4)."""
        return render_prometheus_snapshot(self.snapshot())


def render_prometheus_snapshot(snapshot: dict) -> str:
    """Render any registry-shaped snapshot (``{name: {"kind", "help",
    "series"}}``) as Prometheus text — the local registry or a merged fleet
    snapshot (observability/aggregate.py) render identically."""
    lines: list[str] = []
    for name, snap in sorted(snapshot.items()):
        if snap.get("help"):
            lines.append(f"# HELP {name} {snap['help']}")
        lines.append(f"# TYPE {name} {snap['kind']}")
        for series in snap["series"]:
            labels = series["labels"]
            if snap["kind"] == "histogram":
                value = series["value"]
                for le, cum in value["buckets"].items():
                    lines.append(
                        f"{name}_bucket"
                        f"{_fmt_labels({**labels, 'le': le})} {cum}"
                    )
                lines.append(f"{name}_sum{_fmt_labels(labels)} {value['sum']}")
                lines.append(
                    f"{name}_count{_fmt_labels(labels)} {value['count']}"
                )
            else:
                lines.append(f"{name}{_fmt_labels(labels)} {series['value']}")
    return "\n".join(lines) + "\n"


def _fmt_labels(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _escape(v) -> str:
    return str(v).replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


# --------------------------------------------------------------------------
# process singleton + convenience accessors
# --------------------------------------------------------------------------

_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _registry


def counter(name: str, help: str = "") -> Counter:
    return _registry.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return _registry.gauge(name, help)


def histogram(
    name: str, help: str = "", buckets: Optional[tuple] = None
) -> Histogram:
    return _registry.histogram(name, help, buckets=buckets)


def metrics_snapshot() -> dict:
    """This process's full registry snapshot (see MetricsRegistry.snapshot)."""
    return _registry.snapshot()


def reset_metrics() -> None:
    _registry.reset()


# --------------------------------------------------------------------------
# env-gated periodic dumper
# --------------------------------------------------------------------------

# Fork story lives one level up: observability.reinit_after_fork() (called
# from actor children's _child_main) resets the started-flag and re-arms the
# dumper thread; the lock itself is never held across a spawn.
_dumper_lock = threading.Lock()  # tslint: disable=fork-safety
_dumper_started = False
_dumper_thread: Optional[threading.Thread] = None
_dump_path: Optional[str] = None


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except (PermissionError, OSError):
        return True  # exists (or unknowable) — treat the claim as live


def _resolve_dump_path(base: str) -> str:
    """Claim ``base`` for this process; concurrent processes (volume actors
    dump too) take a pid-suffixed sibling. Ownership is arbitrated through a
    ``<base>.owner`` sidecar recording the claimant's pid — NOT the dump
    file's existence: dumps persist across runs (tpu_watch reuses its
    OUTDIR), and a leftover file from a finished run must not divert a
    fresh run to a suffixed sibling while the base path serves stale data.
    A dead owner's claim is taken over; writes are atomic whole-file
    replaces, so even a (rare) double-takeover cannot interleave output."""
    root, ext = os.path.splitext(base)
    pid = os.getpid()
    pid_path = f"{root}.{pid}{ext or '.json'}"
    owner_path = f"{base}.owner"
    try:
        fd = os.open(owner_path, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644)
        os.write(fd, str(pid).encode())
        os.close(fd)
        return base
    except FileExistsError:
        try:
            with open(owner_path) as f:
                owner = int(f.read().strip() or 0)
        except (OSError, ValueError):
            owner = 0
        if owner == pid:
            return base
        if not owner or not _pid_alive(owner):
            try:
                tmp = f"{owner_path}.tmp.{pid}"
                with open(tmp, "w") as f:
                    f.write(str(pid))
                os.replace(tmp, owner_path)
                return base
            except OSError:
                pass
        return pid_path
    except OSError:
        return pid_path


def dump_metrics(path: Optional[str] = None) -> Optional[str]:
    """Atomically write this process's metrics to ``path`` (default: the
    claimed env-configured path). Format by extension: ``.prom`` gets
    Prometheus text, anything else JSON. Returns the path written or None."""
    global _dump_path
    if path is None:
        base = os.environ.get(ENV_METRICS_DUMP)
        if not base:
            return None
        if _dump_path is None:
            _dump_path = _resolve_dump_path(base)
        path = _dump_path
    payload = (
        _registry.render_prometheus()
        if path.endswith(".prom")
        else _registry.render_json()
    )
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            f.write(payload)
        os.replace(tmp, path)
    except OSError:
        return None
    return path


def maybe_start_dumper() -> bool:
    """Start the periodic dump thread once per process when
    ``TORCHSTORE_TPU_METRICS_DUMP`` is set. Idempotent; returns whether a
    dumper is running. Called from ``torchstore_tpu`` import."""
    global _dumper_started
    if not os.environ.get(ENV_METRICS_DUMP):
        return False
    with _dumper_lock:
        if _dumper_started:
            return True
        _dumper_started = True
    try:
        interval = float(os.environ.get(ENV_METRICS_INTERVAL, "60"))
    except ValueError:
        interval = 60.0
    interval = max(1.0, interval)

    def loop() -> None:
        while True:
            time.sleep(interval)
            dump_metrics()

    global _dumper_thread
    thread = threading.Thread(
        target=loop, name="torchstore-tpu-metrics-dump", daemon=True
    )
    thread.start()
    _dumper_thread = thread
    atexit.register(dump_metrics)
    return True


def reinit_dumper_after_fork() -> bool:
    """Re-arm the periodic dumper in an actor child. Under forkserver, fork
    copies the ``_dumper_started`` flag but NOT the dump thread (only the
    forking thread survives), so an inherited True flag means "claims to
    run, never dumps" — reset and start fresh. Under spawn, the child's own
    import already started a LIVE thread: starting another would double
    every dump; only the claimed path is dropped so the next tick
    re-resolves against the child's corrected env."""
    global _dumper_started, _dump_path, _dumper_thread
    with _dumper_lock:
        _dump_path = None
        if _dumper_thread is not None and _dumper_thread.is_alive():
            return True
        _dumper_started = False
        _dumper_thread = None
    return maybe_start_dumper()
