"""Fleet metrics aggregation: merge per-process registries into one document.

Metrics are process-local by design (Prometheus client-library semantics —
see observability/metrics.py): the client, the controller, and every storage
volume each hold their own registry, surfaced through ``stats()`` endpoints.
This module is the scrape side: :func:`merge_snapshots` takes those
per-process snapshots and produces ONE registry-shaped snapshot in which
every series carries identifying labels (``process="client" | "controller" |
"volume"`` plus ``volume_id=...``), renderable as a single Prometheus-text
or JSON document via ``metrics.render_prometheus_snapshot``.

Merge semantics:

- **Label injection**: each contributed series gains its process labels. A
  pre-existing label with the same name is preserved under an ``exported_``
  prefix (the Prometheus honor-labels convention) — the scraper's identity
  labels are authoritative, the original value is never lost.
- **Kind conflicts**: if two processes registered the same metric name with
  different kinds (which scripts/check_metric_names.py lints against), the
  first-seen kind wins and the conflicting contribution is dropped and
  recorded in the returned ``conflicts`` list — one bad process must not
  corrupt the whole fleet document.
- **Dead volumes**: scrape errors are the CALLER's to record (see
  ``api.fleet_snapshot``) — merge only ever sees snapshots that arrived.
"""

from __future__ import annotations

from typing import Optional


def _inject_labels(series_labels: dict, inject: dict) -> dict:
    out = dict(series_labels)
    for key, value in inject.items():
        if key in out and str(out[key]) != str(value):
            out[f"exported_{key}"] = out.pop(key)
        out[key] = str(value)
    return out


def merge_snapshots(
    entries: list[tuple[dict, dict]],
) -> tuple[dict, list[str]]:
    """Merge ``[(labels, snapshot), ...]`` into one snapshot.

    ``labels`` identify the contributing process (e.g. ``{"process":
    "volume", "volume_id": "0"}``) and are injected into every series;
    ``snapshot`` is a ``MetricsRegistry.snapshot()``-shaped dict. Returns
    ``(merged_snapshot, conflicts)`` where conflicts lists
    ``"metric_name (kind_a vs kind_b from <labels>)"`` strings for
    contributions dropped on kind mismatch."""
    merged: dict[str, dict] = {}
    conflicts: list[str] = []
    for labels, snapshot in entries:
        for name, snap in (snapshot or {}).items():
            target = merged.get(name)
            if target is None:
                target = merged[name] = {
                    "kind": snap.get("kind", "untyped"),
                    "help": snap.get("help", ""),
                    "series": [],
                }
            elif target["kind"] != snap.get("kind", "untyped"):
                conflicts.append(
                    f"{name} ({target['kind']} vs "
                    f"{snap.get('kind', 'untyped')} from {labels})"
                )
                continue
            if not target["help"] and snap.get("help"):
                target["help"] = snap["help"]
            for series in snap.get("series", ()):
                target["series"].append(
                    {
                        "labels": _inject_labels(
                            series.get("labels", {}), labels
                        ),
                        "value": series.get("value"),
                    }
                )
    return dict(sorted(merged.items())), conflicts


def render_prometheus(merged_snapshot: dict) -> str:
    """One Prometheus-text document for a merged fleet snapshot."""
    from torchstore_tpu.observability.metrics import (
        render_prometheus_snapshot,
    )

    return render_prometheus_snapshot(merged_snapshot)


def render_json(fleet_doc: dict) -> str:
    """JSON document for a full ``fleet_snapshot()`` result."""
    import json

    return json.dumps(fleet_doc)


def fleet_doc(
    entries: list[tuple[dict, dict]],
    errors: Optional[dict] = None,
    hot_keys: Optional[dict] = None,
    ledgers: Optional[dict] = None,
) -> dict:
    """Assemble the standard fleet-snapshot envelope around a merge.
    ``ledgers`` maps process labels to traffic-ledger snapshots
    (observability/ledger.py); ``ts.traffic_matrix()`` folds them."""
    import os
    import time

    merged, conflicts = merge_snapshots(entries)
    return {
        "ts": time.time(),
        "scraper_pid": os.getpid(),
        "processes": [labels for labels, _ in entries],
        "errors": dict(errors or {}),
        "conflicts": conflicts,
        "hot_keys": dict(hot_keys or {}),
        "ledgers": dict(ledgers or {}),
        "metrics": merged,
    }
