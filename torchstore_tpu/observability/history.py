"""Per-process time-series history: bounded multi-resolution metric rings.

Every other observability surface in the store is a point-in-time snapshot
(registry scrapes, ``slo_report()`` live values) or a two-bucket rolling
window (ledgers, stage digests). Nothing retains *history*, so "sustained
``ts_landing_inflight`` saturation" — the trigger the elastic autoscaler
(ROADMAP item 4) is specified against — is literally unobservable. This
module is the retention layer:

- A background :class:`SeriesStore` sampler sweeps every registry
  instrument every ``TORCHSTORE_TPU_HISTORY_INTERVAL_S`` seconds (default
  1) into RRD-style multi-resolution rings — 1s x 300 slots (5 min raw),
  10s x 360 (1 h), 60s x 360 (6 h). Each slot keeps min/max/last/sum/count
  so a one-sample spike SURVIVES downsampling (the 60s ring's ``max`` still
  shows it) and bucket means stay exact (``sum``/``count``).
- Counters additionally derive an instantaneous **rate** series
  (``<name>:rate{labels}``), reset-safe across process restarts
  (Prometheus semantics: a value below its predecessor is a restart, the
  new value IS the delta — rates never go negative).
- Everything is budget-bounded: rings are fixed preallocated arrays,
  series count is capped (``TORCHSTORE_TPU_HISTORY_MAX_SERIES``; overflow
  is counted in ``ts_history_series_dropped_total``, never allocated), and
  each sweep's measured cost gates the effective interval
  (``TORCHSTORE_TPU_HISTORY_BUDGET_PCT``: the sampler never spends more
  than that fraction of one core).

Fleet story: ``ts.history(series=..., since=...)`` rides the volume /
controller ``stats()`` endpoints the way ledgers and hot_keys do (the
history payload is request-gated — routine stats scrapes stay cheap), the
HTTP exporter serves ``/history.json``, flight-recorder post-mortems embed
the last five minutes of curated vitals
(``TORCHSTORE_TPU_HISTORY_DUMP_SERIES``), and detectors
(observability/detect.py) turn the rings into ``slo_report()["trends"]``
and the control plane's ``sustained_overload`` signal.
"""

from __future__ import annotations

import fnmatch
import os
import threading
import time
from array import array
from typing import Any, Iterable, Optional, Union

from torchstore_tpu.observability import metrics as obs_metrics

ENV_HISTORY = "TORCHSTORE_TPU_HISTORY"
ENV_HISTORY_INTERVAL = "TORCHSTORE_TPU_HISTORY_INTERVAL_S"
ENV_HISTORY_MAX_SERIES = "TORCHSTORE_TPU_HISTORY_MAX_SERIES"
ENV_HISTORY_BUDGET_PCT = "TORCHSTORE_TPU_HISTORY_BUDGET_PCT"
ENV_HISTORY_DUMP_SERIES = "TORCHSTORE_TPU_HISTORY_DUMP_SERIES"

# Ring levels as (step_s, slots): 5 minutes at 1s, an hour at 10s, six
# hours at 60s. ~48 bytes/slot -> ~49 KB per series, fully preallocated.
LEVELS: tuple[tuple[float, int], ...] = ((1.0, 300), (10.0, 360), (60.0, 360))

# Default lookback for history()/dump queries when the caller gives none.
DEFAULT_SINCE_S = 300.0

# ``since`` values below this are relative lookbacks in seconds; at or
# above it they are absolute wall timestamps (the year-2001 boundary — no
# real scrape wants a 31-year lookback).
_ABS_TS_FLOOR = 1e9

# Curated vitals embedded in flight-recorder post-mortems when
# TORCHSTORE_TPU_HISTORY_DUMP_SERIES is unset: the series an operator
# reads first in any incident (op tails, landing pressure, op rates,
# doorbell residency, metadata queue depth, SLO breach counts).
DEFAULT_DUMP_SERIES = (
    "ts_op_p99_seconds*",
    "ts_op_p50_seconds*",
    "ts_landing_inflight*",
    "ts_client_ops_total*",
    "ts_doorbell_plans_resident*",
    "ts_meta_rpc_inflight*",
    "ts_slo_violations_total*",
)

_SAMPLE_COST = obs_metrics.gauge(
    "ts_history_sample_seconds",
    "Wall-clock cost of the last history sampling sweep",
)
_SWEEPS = obs_metrics.counter(
    "ts_history_sweeps_total", "History sampling sweeps completed"
)
_SERIES_GAUGE = obs_metrics.gauge(
    "ts_history_series", "Time-series tracked by this process's SeriesStore"
)
_DROPPED = obs_metrics.counter(
    "ts_history_series_dropped_total",
    "Distinct series refused by the TORCHSTORE_TPU_HISTORY_MAX_SERIES cap",
)


def _env_enabled() -> bool:
    return os.environ.get(ENV_HISTORY, "1").strip().lower() not in (
        "0", "false", "no", "off", "",
    )


def _env_interval_s() -> float:
    try:
        return max(
            0.01, float(os.environ.get(ENV_HISTORY_INTERVAL, "1") or "1")
        )
    except ValueError:
        return 1.0


def _env_max_series() -> int:
    try:
        return max(
            16, int(os.environ.get(ENV_HISTORY_MAX_SERIES, "256") or "256")
        )
    except ValueError:
        return 256


def _env_budget_frac() -> float:
    """Fraction of one core the sampler may spend (default 1%)."""
    try:
        pct = float(os.environ.get(ENV_HISTORY_BUDGET_PCT, "1") or "1")
    except ValueError:
        pct = 1.0
    return max(0.0, pct) / 100.0


def _env_dump_series() -> tuple[str, ...]:
    raw = os.environ.get(ENV_HISTORY_DUMP_SERIES)
    if not raw:
        return DEFAULT_DUMP_SERIES
    globs = tuple(g.strip() for g in raw.split(",") if g.strip())
    return globs or DEFAULT_DUMP_SERIES


def render_series_id(
    name: str, label_key: Iterable[tuple[str, str]] = ()
) -> str:
    """The canonical series identity: ``name`` or ``name{k="v",...}`` over
    the registry's sorted label-key tuples — one stable string per labeled
    series, merge-safe across processes."""
    pairs = list(label_key)
    if not pairs:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in pairs)
    return name + "{" + inner + "}"


class _Ring:
    """One resolution level of one series: fixed parallel arrays indexed by
    ``bucket_id % slots``. A slot whose stored bucket id differs from the
    incoming sample's is stale retention — it is overwritten, never merged
    — so the ring always holds the LAST ``slots`` buckets with no shifting
    and no per-sample allocation."""

    __slots__ = (
        "step", "slots", "bucket", "vmin", "vmax", "vlast", "vsum", "count",
    )

    def __init__(self, step: float, slots: int) -> None:
        self.step = float(step)
        self.slots = int(slots)
        self.bucket = array("q", [-1]) * self.slots
        self.vmin = array("d", [0.0]) * self.slots
        self.vmax = array("d", [0.0]) * self.slots
        self.vlast = array("d", [0.0]) * self.slots
        self.vsum = array("d", [0.0]) * self.slots
        self.count = array("q", [0]) * self.slots

    def add(self, ts: float, value: float) -> None:
        b = int(ts // self.step)
        i = b % self.slots
        if self.bucket[i] != b:
            self.bucket[i] = b
            self.vmin[i] = self.vmax[i] = self.vlast[i] = value
            self.vsum[i] = value
            self.count[i] = 1
            return
        if value < self.vmin[i]:
            self.vmin[i] = value
        if value > self.vmax[i]:
            self.vmax[i] = value
        self.vlast[i] = value
        self.vsum[i] += value
        self.count[i] += 1

    def points(self, since_ts: float) -> list[list]:
        """``[[bucket_start_ts, min, max, last, sum, count], ...]`` for
        every retained bucket at or after ``since_ts``, oldest first."""
        since_b = int(since_ts // self.step)
        rows = [
            [
                self.bucket[i] * self.step,
                self.vmin[i],
                self.vmax[i],
                self.vlast[i],
                self.vsum[i],
                self.count[i],
            ]
            for i in range(self.slots)
            if self.bucket[i] >= since_b
        ]
        rows.sort(key=lambda r: r[0])
        return rows


class Series:
    """One tracked series: a ring per level plus the previous raw sample
    (counters only — the rate derivation's state)."""

    __slots__ = ("sid", "kind", "rings", "prev_value", "prev_ts")

    def __init__(
        self, sid: str, kind: str, levels: Iterable[tuple[float, int]]
    ) -> None:
        self.sid = sid
        self.kind = kind
        self.rings = tuple(_Ring(step, slots) for step, slots in levels)
        self.prev_value: Optional[float] = None
        self.prev_ts: Optional[float] = None

    def add(self, ts: float, value: float) -> None:
        for ring in self.rings:
            ring.add(ts, value)


class SeriesStore:
    """Every series this process retains, behind one lock (the sampler is
    the single writer; queries copy points out under the lock — sweeps are
    a few hundred series and both sides are O(slots))."""

    def __init__(
        self,
        levels: Iterable[tuple[float, int]] = LEVELS,
        max_series: Optional[int] = None,
    ) -> None:
        self._lock = threading.Lock()
        self.levels = tuple(levels)
        self._max_series = max_series
        self._series: dict[str, Series] = {}
        self._dropped: set[str] = set()
        self.enabled = _env_enabled()
        self.last_cost_s = 0.0

    @property
    def max_series(self) -> int:
        return (
            self._max_series
            if self._max_series is not None
            else _env_max_series()
        )

    def set_enabled(self, enabled: bool) -> None:
        self.enabled = bool(enabled)

    def __len__(self) -> int:
        with self._lock:
            return len(self._series)

    def clear(self) -> None:
        with self._lock:
            self._series.clear()
            self._dropped.clear()

    def _get_or_create_locked(self, sid: str, kind: str) -> Optional[Series]:
        series = self._series.get(sid)
        if series is not None:
            return series
        if len(self._series) >= self.max_series:
            if sid not in self._dropped:
                self._dropped.add(sid)
                _DROPPED.inc()
            return None
        series = self._series[sid] = Series(sid, kind, self.levels)
        return series

    def observe(
        self,
        sid: str,
        kind: str,
        value: float,
        now: Optional[float] = None,
    ) -> None:
        """Feed one sample directly (tests, non-registry sources). The
        background sweep uses :meth:`sample`."""
        now = time.time() if now is None else now
        with self._lock:
            series = self._get_or_create_locked(sid, kind)
            if series is not None:
                series.add(now, value)

    def sample(
        self,
        registry: Optional[obs_metrics.MetricsRegistry] = None,
        now: Optional[float] = None,
    ) -> float:
        """One sweep over every registry instrument; returns the sweep's
        wall cost in seconds (the budget gate's input). Counters feed
        their raw cumulative series AND a derived ``:rate`` series; a
        counter value below its predecessor is a process restart — the
        new value is the whole delta, so rates never go negative."""
        if not self.enabled:
            return 0.0
        registry = registry if registry is not None else obs_metrics.get_registry()
        now = time.time() if now is None else now
        t0 = time.perf_counter()
        rows = registry.sample_values()
        with self._lock:
            for name, kind, label_key, value in rows:
                sid = render_series_id(name, label_key)
                series = self._get_or_create_locked(sid, kind)
                if series is None:
                    continue
                series.add(now, value)
                if kind != "counter":
                    continue
                prev_v, prev_t = series.prev_value, series.prev_ts
                series.prev_value, series.prev_ts = value, now
                if prev_t is None or now <= prev_t:
                    continue
                delta = value - prev_v if value >= prev_v else value
                rate_sid = render_series_id(f"{name}:rate", label_key)
                rate = self._get_or_create_locked(rate_sid, "rate")
                if rate is not None:
                    rate.add(now, delta / (now - prev_t))
            n_series = len(self._series)
        cost = time.perf_counter() - t0
        self.last_cost_s = cost
        _SAMPLE_COST.set(round(cost, 6))
        _SERIES_GAUGE.set(n_series)
        _SWEEPS.inc()
        return cost

    def _pick_level(
        self, lookback_s: float, level: Optional[Union[int, float]]
    ) -> int:
        if level is not None:
            if isinstance(level, int) and 0 <= level < len(self.levels):
                return level
            for i, (step, _slots) in enumerate(self.levels):
                if step == float(level):
                    return i
            raise ValueError(
                f"unknown history level {level!r}; levels: {self.levels}"
            )
        for i, (step, slots) in enumerate(self.levels):
            if step * slots >= lookback_s:
                return i
        return len(self.levels) - 1

    def query(
        self,
        series: Optional[Union[str, Iterable[str]]] = None,
        since: Optional[float] = None,
        level: Optional[Union[int, float]] = None,
        now: Optional[float] = None,
    ) -> dict:
        """Retained points as plain data.

        ``series`` is a glob (or list of globs) over series ids; a
        selector without a label part also matches every labeled variant
        of that name (``"ts_landing_inflight"`` matches
        ``ts_landing_inflight{volume="v0"}``). ``since`` is a lookback in
        seconds when small, an absolute wall timestamp when it looks like
        one (>= 1e9); default 300 s. ``level`` pins a ring (index or step
        seconds); by default the finest ring that covers the lookback
        serves the query.

        Returns ``{"generated_ts", "interval_s", "step_s", "levels",
        "series": {sid: {"kind", "points": [[ts, min, max, last, sum,
        count], ...]}}}``.
        """
        now = time.time() if now is None else now
        if since is None:
            since_ts = now - DEFAULT_SINCE_S
        elif since >= _ABS_TS_FLOOR:
            since_ts = since
        else:
            since_ts = now - max(0.0, since)
        lookback = max(1.0, now - since_ts)
        idx = self._pick_level(lookback, level)
        if series is None:
            globs: Optional[tuple[str, ...]] = None
        elif isinstance(series, str):
            globs = (series,)
        else:
            globs = tuple(series)
        out: dict[str, dict] = {}
        with self._lock:
            for sid, ser in self._series.items():
                if globs is not None and not series_matches(sid, globs):
                    continue
                points = ser.rings[idx].points(since_ts)
                if points:
                    out[sid] = {"kind": ser.kind, "points": points}
        return {
            "generated_ts": now,
            "interval_s": _env_interval_s(),
            "step_s": self.levels[idx][0],
            "levels": [list(lv) for lv in self.levels],
            "series": out,
        }


def series_matches(sid: str, globs: Iterable[str]) -> bool:
    """Whether ``sid`` matches any selector glob. A bare selector (no
    ``{``, no trailing ``*``) additionally matches its labeled variants —
    so detector catalogs and lint rules can name the registered instrument
    without knowing its label sets."""
    for g in globs:
        if fnmatch.fnmatchcase(sid, g):
            return True
        if "{" not in g and not g.endswith("*") and fnmatch.fnmatchcase(
            sid, g + "{*"
        ):
            return True
    return False


# --------------------------------------------------------------------------
# process singleton + background sampler
# --------------------------------------------------------------------------

_store = SeriesStore()
# Fork story matches the metrics dumper: observability.reinit_after_fork()
# resets the started-flag and re-arms the sampler thread in actor children;
# the lock is never held across a spawn.
_sampler_lock = threading.Lock()  # tslint: disable=fork-safety
_sampler_thread: Optional[threading.Thread] = None
_sampler_stop: Optional[threading.Event] = None


def series_store() -> SeriesStore:
    return _store


def history(
    series: Optional[Union[str, Iterable[str]]] = None,
    since: Optional[float] = None,
    level: Optional[Union[int, float]] = None,
) -> dict:
    """This process's retained history (see :meth:`SeriesStore.query`).
    ``ts.history()`` merges this view with the controller's and every
    reachable volume's."""
    return _store.query(series=series, since=since, level=level)


def dump_vitals() -> dict:
    """The curated last-five-minutes payload flight-recorder post-mortems
    embed (``TORCHSTORE_TPU_HISTORY_DUMP_SERIES`` globs, default
    :data:`DEFAULT_DUMP_SERIES`)."""
    return _store.query(series=_env_dump_series(), since=DEFAULT_SINCE_S)


def _sampler_loop(stop: threading.Event) -> None:
    while True:
        cost = 0.0
        try:
            cost = _store.sample()
            if _store.enabled:
                # Keep ts_trend_active and the cached trend results fresh
                # even when nobody is polling slo_report().
                from torchstore_tpu.observability import detect as obs_detect

                obs_detect.evaluate_trends(_store)
        except Exception:  # noqa: BLE001 - the sampler must never die
            pass
        interval = _env_interval_s()
        budget = _env_budget_frac()
        if budget > 0 and cost > 0:
            # Cost gate: a sweep that took C seconds forces the effective
            # interval up to C/budget so sampling never exceeds its CPU
            # fraction, however many series the registry grows.
            interval = max(interval, cost / budget)
        if stop.wait(interval):
            return


def maybe_start_history() -> bool:
    """Start the background sampler once per process unless
    ``TORCHSTORE_TPU_HISTORY=0``. Idempotent; returns whether a sampler is
    running. Called from ``torchstore_tpu`` import."""
    global _sampler_thread, _sampler_stop
    if not _env_enabled():
        return False
    with _sampler_lock:
        if _sampler_thread is not None and _sampler_thread.is_alive():
            return True
        _store.enabled = True
        stop = _sampler_stop = threading.Event()
        thread = threading.Thread(
            target=_sampler_loop,
            args=(stop,),
            name="torchstore-tpu-history",
            daemon=True,
        )
        thread.start()
        _sampler_thread = thread
    return True


def stop_history() -> None:
    """Stop the sampler thread (tests; idempotent). Retained rings stay —
    history outlives its collector by design."""
    global _sampler_thread, _sampler_stop
    with _sampler_lock:
        stop, _sampler_stop = _sampler_stop, None
        thread, _sampler_thread = _sampler_thread, None
    if stop is not None:
        stop.set()
    if thread is not None:
        thread.join(timeout=5.0)


def reset_history() -> None:
    """Drop every retained point (tests, bench warmup). The store object
    and sampler survive — exactly the registry-reset contract."""
    _store.clear()


def reinit_after_fork() -> bool:
    """Re-arm in an actor child. Forked children inherit the parent's
    rings (another process's history) and a sampler flag whose thread died
    in the fork: drop the points, re-read the env, start fresh. Under
    spawn the child's own import already started a live sampler — keep
    it (the rings are genuinely this process's)."""
    with _sampler_lock:
        alive = _sampler_thread is not None and _sampler_thread.is_alive()
        if not alive:
            _store.clear()
            _store.enabled = _env_enabled()
    if alive:
        return True
    stop_history()
    return maybe_start_history()


# --------------------------------------------------------------------------
# fleet merge helpers (ts.history / loadgen report / ts-top)
# --------------------------------------------------------------------------


def merge_points(
    point_lists: Iterable[Iterable[Iterable[float]]], how: str = "sum"
) -> list[list]:
    """Merge ``[ts, min, max, last, sum, count]`` rows from several
    processes by timestamp bucket. ``how="sum"`` adds min/max/last/sum
    across processes per bucket (rates, counts); ``how="max"`` keeps the
    worst (gauges like p99). Rows come back oldest first."""
    if how not in ("sum", "max"):
        raise ValueError(f"merge_points: how={how!r} (want 'sum' or 'max')")
    merged: dict[float, list] = {}
    for rows in point_lists:
        for row in rows or ():
            ts, vmin, vmax, vlast, vsum, count = row
            cur = merged.get(ts)
            if cur is None:
                merged[ts] = [ts, vmin, vmax, vlast, vsum, count]
            elif how == "sum":
                cur[1] += vmin
                cur[2] += vmax
                cur[3] += vlast
                cur[4] += vsum
                cur[5] += count
            else:
                cur[1] = min(cur[1], vmin)
                cur[2] = max(cur[2], vmax)
                cur[3] = max(cur[3], vlast)
                cur[4] = max(cur[4], vsum)
                cur[5] = max(cur[5], count)
    return [merged[ts] for ts in sorted(merged)]


def counter_rate_points(rows: Iterable[Iterable[float]]) -> list[list]:
    """Exact per-bucket rates from a CUMULATIVE counter series' points:
    successive ``last`` diffs over successive bucket timestamps —
    bucket-true ops/s with none of the instantaneous-rate sampling noise.
    A drop between buckets is a restart (the new value is the delta).
    Returns ``[[ts, rate], ...]``; the first bucket has no predecessor and
    is skipped."""
    out: list[list] = []
    prev_ts: Optional[float] = None
    prev_v: Optional[float] = None
    for row in rows:
        ts, vlast = row[0], row[3]
        if prev_ts is not None and ts > prev_ts:
            delta = vlast - prev_v if vlast >= prev_v else vlast
            out.append([ts, delta / (ts - prev_ts)])
        prev_ts, prev_v = ts, vlast
    return out
