"""Trend/anomaly detectors over history series: burst vs regime change.

The control plane's instantaneous gates (SLO thresholds, the solver's
hysteresis ratios) cannot tell a two-second burst from a sustained regime
change — both look identical in a point-in-time snapshot. These detectors
read the :mod:`~torchstore_tpu.observability.history` rings instead and
answer the question the PR 16 solver and the future elastic autoscaler
actually ask: *has this signal been bad for a while, and which way is it
heading?*

Three detector kinds, all **pure functions over point rows** (injectable
clocks, no hidden state — every evaluation recomputes from the ring):

- ``sustained`` — value ≥ threshold for ≥ N consecutive samples (the
  autoscaler trigger ROADMAP item 4 is specified against).
- ``drift`` — EWMA-baseline z-score: the latest sample against the
  exponentially-weighted mean/variance of its own past (catches a p99
  quietly leaving its historical band long before an absolute SLO trips).
- ``ramp`` — least-squares slope over the window (catches "heading for the
  cliff" while still under every threshold).

``evaluate_trends()`` runs the catalog against the local
:class:`~torchstore_tpu.observability.history.SeriesStore`, publishes
``ts_trend_active{detector=...}``, and is surfaced as
``ts.slo_report()["trends"]`` and — via volume ``stats()`` — the control
snapshot's ``sustained_overload`` field.

Detector ``series`` selectors MUST name a registered instrument literally:
tslint rule ``history-discipline`` checks the literal against the same
registration scan that powers ``--regen-metric-docs``, so a renamed metric
cannot silently orphan its detector.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass
from typing import Any, Iterable, Optional

from torchstore_tpu.observability import history as obs_history
from torchstore_tpu.observability import metrics as obs_metrics

ENV_TREND_SUSTAIN_SAMPLES = "TORCHSTORE_TPU_TREND_SUSTAIN_SAMPLES"
ENV_TREND_INFLIGHT = "TORCHSTORE_TPU_TREND_INFLIGHT"
# The control plane's instantaneous gate — the sustained detector defaults
# to the same threshold so "sustained_overload" means "the solver's own
# overload line, held".
_ENV_CONTROL_INFLIGHT = "TORCHSTORE_TPU_CONTROL_OVERLOAD_INFLIGHT"

# How far back an evaluation reads (level-0 ring, 1s buckets): three
# minutes gives drift a baseline without ever touching coarser rings.
EVAL_LOOKBACK_S = 180.0

# z-scores are clamped here: a flat baseline (zero variance) makes any
# deviation infinitely surprising, which serializes as Infinity and breaks
# JSON consumers.
MAX_Z = 99.0

_TREND_ACTIVE = obs_metrics.gauge(
    "ts_trend_active",
    "Whether this trend detector is currently firing (1) or quiet (0)",
)


@dataclass(frozen=True)
class Detector:
    """One catalog entry: a detector kind bound to a series selector.

    ``series`` must be a literal registered-instrument selector (see
    module docstring). ``kind`` is ``"sustained"``, ``"drift"`` or
    ``"ramp"``; the remaining fields parameterize whichever kind is
    chosen and are ignored by the others.
    """

    name: str
    series: str
    kind: str
    threshold: float = 0.0
    min_samples: int = 5
    z: float = 3.0
    min_slope: float = 0.0


def _last_values(points: Iterable[Iterable[float]]) -> list[tuple[float, float]]:
    """``(ts, last)`` per bucket — detectors read the bucket's closing
    value; spikes are the ``max`` column's job and stay visible there."""
    return [(row[0], row[3]) for row in points]


def sustained(
    points: Iterable[Iterable[float]],
    threshold: float,
    min_samples: int,
) -> dict:
    """Value ≥ threshold for the trailing ≥ ``min_samples`` consecutive
    buckets. Returns ``{"active", "samples", "value", "since_ts",
    "duration_s"}`` where ``samples`` is the trailing run length (0 when
    the latest bucket is under threshold)."""
    vals = _last_values(points)
    run = 0
    since_ts = None
    for ts, v in reversed(vals):
        if v < threshold:
            break
        run += 1
        since_ts = ts
    active = run >= max(1, min_samples)
    last_ts, last_v = vals[-1] if vals else (None, 0.0)
    return {
        "active": active,
        "samples": run,
        "value": last_v,
        "since_ts": since_ts if run else None,
        "duration_s": (last_ts - since_ts) if (run and last_ts is not None) else 0.0,
    }


def ewma_drift(
    points: Iterable[Iterable[float]],
    z: float = 3.0,
    min_samples: int = 8,
    alpha: float = 0.3,
) -> dict:
    """z-score of the latest bucket against the EWMA mean/variance of every
    earlier bucket. Returns ``{"active", "z", "value", "baseline",
    "samples"}``. Needs ``min_samples`` buckets of baseline before it can
    fire (a two-sample history has no notion of 'normal')."""
    vals = [v for _ts, v in _last_values(points)]
    n = len(vals)
    if n < max(2, min_samples):
        return {
            "active": False, "z": 0.0,
            "value": vals[-1] if vals else 0.0,
            "baseline": vals[-1] if vals else 0.0,
            "samples": n,
        }
    mean = vals[0]
    var = 0.0
    for v in vals[1:-1]:
        d = v - mean
        mean += alpha * d
        var = (1 - alpha) * (var + alpha * d * d)
    last = vals[-1]
    std = math.sqrt(var)
    if std > 0:
        score = (last - mean) / std
        score = max(-MAX_Z, min(MAX_Z, score))
    else:
        score = 0.0 if last == mean else math.copysign(MAX_Z, last - mean)
    return {
        "active": abs(score) >= z,
        "z": score,
        "value": last,
        "baseline": mean,
        "samples": n,
    }


def ramp(
    points: Iterable[Iterable[float]],
    min_slope: float,
    min_samples: int = 5,
) -> dict:
    """Least-squares slope (value units per second) over the window.
    Active only when ``min_slope > 0`` and the fitted slope reaches it.
    Returns ``{"active", "slope", "value", "samples"}``."""
    vals = _last_values(points)
    n = len(vals)
    if n < max(2, min_samples):
        return {
            "active": False, "slope": 0.0,
            "value": vals[-1][1] if vals else 0.0, "samples": n,
        }
    t0 = vals[0][0]
    xs = [ts - t0 for ts, _v in vals]
    ys = [v for _ts, v in vals]
    mx = sum(xs) / n
    my = sum(ys) / n
    denom = sum((x - mx) ** 2 for x in xs)
    slope = (
        sum((x - mx) * (y - my) for x, y in zip(xs, ys)) / denom
        if denom > 0
        else 0.0
    )
    return {
        "active": bool(min_slope > 0 and slope >= min_slope),
        "slope": slope,
        "value": ys[-1],
        "samples": n,
    }


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def default_detectors() -> tuple[Detector, ...]:
    """The stock catalog. Thresholds re-read env on every call so tests
    (and operators mid-incident) can retune without restarting anything."""
    inflight = _env_int(
        ENV_TREND_INFLIGHT, _env_int(_ENV_CONTROL_INFLIGHT, 16)
    )
    sustain = max(1, _env_int(ENV_TREND_SUSTAIN_SAMPLES, 5))
    return (
        Detector(
            name="landing_inflight_sustained",
            series="ts_landing_inflight",
            kind="sustained",
            threshold=float(inflight),
            min_samples=sustain,
        ),
        Detector(
            name="landing_inflight_ramp",
            series="ts_landing_inflight",
            kind="ramp",
            min_slope=max(1.0, inflight / 4.0),
            min_samples=sustain,
        ),
        Detector(
            name="get_p99_drift",
            series='ts_op_p99_seconds{op="get"}',
            kind="drift",
            z=3.0,
        ),
        Detector(
            name="put_p99_drift",
            series='ts_op_p99_seconds{op="put"}',
            kind="drift",
            z=3.0,
        ),
    )


def evaluate_detector(
    det: Detector, points: Iterable[Iterable[float]]
) -> dict:
    if det.kind == "sustained":
        return sustained(points, det.threshold, det.min_samples)
    if det.kind == "drift":
        return ewma_drift(points, z=det.z, min_samples=max(2, det.min_samples))
    if det.kind == "ramp":
        return ramp(points, det.min_slope, det.min_samples)
    raise ValueError(f"unknown detector kind {det.kind!r} ({det.name})")


def evaluate_trends(
    store: Optional["obs_history.SeriesStore"] = None,
    detectors: Optional[Iterable[Detector]] = None,
    now: Optional[float] = None,
) -> dict:
    """Run the catalog against the local history rings.

    Each detector's selector may match several labeled series (a volume
    process has one ``ts_landing_inflight`` series per hosted volume id);
    the WORST match wins — worst = active first, then highest value /
    |z| / slope — and its series id is reported so the operator knows
    which label-set fired. Returns ``{detector_name: {"kind", "series",
    "active", ...result...}}`` and publishes ``ts_trend_active``.
    """
    store = store if store is not None else obs_history.series_store()
    dets = tuple(detectors) if detectors is not None else default_detectors()
    view = store.query(
        series=[d.series for d in dets],
        since=EVAL_LOOKBACK_S,
        level=0,
        now=now,
    )
    all_series: dict[str, Any] = view["series"]
    out: dict[str, dict] = {}
    for det in dets:
        best: Optional[dict] = None
        for sid, entry in all_series.items():
            if not obs_history.series_matches(sid, (det.series,)):
                continue
            result = evaluate_detector(det, entry["points"])
            result["series"] = sid
            if best is None or _worse(result, best):
                best = result
        if best is None:
            best = {"active": False, "series": det.series, "samples": 0}
        best["kind"] = det.kind
        out[det.name] = best
        _TREND_ACTIVE.set(1.0 if best["active"] else 0.0, detector=det.name)
    return out


def _worse(a: dict, b: dict) -> bool:
    """Whether result ``a`` outranks ``b`` for the same detector."""
    if a["active"] != b["active"]:
        return a["active"]
    for field in ("duration_s", "slope"):
        if field in a and field in b and a[field] != b[field]:
            return a[field] > b[field]
    if "z" in a and "z" in b and abs(a["z"]) != abs(b["z"]):
        return abs(a["z"]) > abs(b["z"])
    return a.get("value", 0.0) > b.get("value", 0.0)


def active_sustained(trends: dict) -> dict:
    """The subset of trend results the control snapshot folds in as
    ``sustained_overload``: active ``sustained``-kind detections only —
    drift/ramp inform operators, but only a *held* overload may relax the
    solver's migration hysteresis."""
    return {
        name: result
        for name, result in (trends or {}).items()
        if result.get("active") and result.get("kind") == "sustained"
    }
