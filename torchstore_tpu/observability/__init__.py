"""Store-wide observability: metrics registry + span tracing.

Two independent, zero-dependency substrates every layer of the store emits
into (the reference has neither — SURVEY §5 "no counters/prometheus, no
profiler integration"):

- **Metrics** (``observability.metrics``): process-local counters, gauges,
  and fixed-bucket histograms with Prometheus-text and JSON exporters.
  ``ts.metrics_snapshot()`` returns the calling process's registry; volume
  and controller processes expose theirs through their ``stats()``
  endpoints; ``TORCHSTORE_TPU_METRICS_DUMP=/path`` makes every process
  periodically rewrite a machine-readable dump
  (``TORCHSTORE_TPU_METRICS_INTERVAL_S``, default 60).

- **Tracing** (``observability.tracing``): ``span(name, **attrs)`` context
  manager emitting Chrome-trace complete events when
  ``TORCHSTORE_TPU_TRACE=/path/trace.json`` is set — put/get/reshard/
  publish spans carry key, nbytes, transport, and shard coordinates, and
  the file loads directly in Perfetto next to jax profiler traces.

Instrumented layers: ``client.py``/``api.py`` (per-op latency + bytes),
``transport/*`` (per-transport bytes moved, buffer-pool hit/miss,
registration counts), ``controller.py``/``storage_volume.py`` (keys,
resident bytes, write generations, pending reclaims), and
``weight_channel.py`` (publish/acquire versions and subscriber lag).

The distributed layer (PR 2) turns those per-process substrates into one
operable plane:

- **Trace-context propagation** (``observability.context``): a contextvars
  ``trace_id``/``parent_span_id`` carried in every actor-RPC frame, so
  client, controller, and volume spans share one trace id;
  ``ts.collect_trace()`` / ``scripts/merge_traces.py`` stitch the
  per-process files into one Perfetto timeline with labeled process tracks.
- **Fleet aggregation** (``observability.aggregate``): ``ts.fleet_snapshot()``
  scrapes every process's registry through the controller and merges them
  into one process-labeled snapshot / Prometheus document.
- **Live HTTP scrape** (``observability.http_exporter``):
  ``TORCHSTORE_TPU_METRICS_PORT`` serves ``/metrics`` + ``/healthz`` from
  any process (ephemeral-port fallback on sibling conflicts; the bound port
  rides the ``ts_metrics_http_port`` gauge).
- **Hot-key/slow-op profiling** (``observability.profile``): rolling top-K
  keys by bytes/ops per process, and a ``TORCHSTORE_TPU_SLOW_OP_MS``
  threshold that turns outliers into logs, ``ts_slow_ops_total`` counts,
  and trace annotations.
- **Time-series history + trends** (``observability.history`` /
  ``observability.detect``): every process retains bounded multi-resolution
  rings of its own instruments (1s/10s/60s, spikes survive via per-bucket
  max), merged fleet-wide by ``ts.history()``; pure drift/sustained/ramp
  detectors turn the rings into ``slo_report()["trends"]`` and the control
  snapshot's ``sustained_overload`` signal.
"""

from torchstore_tpu.observability import (
    aggregate,
    context,
    detect,
    history,
    ledger,
    profile,
    recorder,
    timeline,
)
from torchstore_tpu.observability.detect import (
    Detector,
    default_detectors,
    evaluate_trends,
)
from torchstore_tpu.observability.history import (
    ENV_HISTORY,
    ENV_HISTORY_INTERVAL,
    SeriesStore,
    maybe_start_history,
    series_store,
)
from torchstore_tpu.observability.http_exporter import (
    ENV_METRICS_PORT,
    MetricsHTTPExporter,
    maybe_start_http_exporter,
    start_http_exporter,
    stop_http_exporter,
)
from torchstore_tpu.observability.metrics import (
    ENV_METRICS_DUMP,
    ENV_METRICS_INTERVAL,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    counter,
    dump_metrics,
    gauge,
    get_registry,
    histogram,
    maybe_start_dumper,
    metrics_snapshot,
    render_prometheus_snapshot,
    reset_metrics,
)
from torchstore_tpu.observability.profile import (
    ENV_SLOW_OP_MS,
    hot_keys,
    record_op,
)
from torchstore_tpu.observability.tracing import (
    ENV_TRACE,
    TraceCollector,
    collect_trace,
    collector,
    flush_trace,
    merge_traces,
    span,
    trace_enabled,
)


def reinit_after_fork() -> None:
    """Re-arm every env-gated observability facility in a freshly forked
    actor child (called from the actor runtime's child bootstrap AFTER the
    child's env is corrected). Forked children inherit the forkserver's
    module state — a trace collector whose path snapshot predates the
    spawner's env, and dumper/exporter 'started' flags whose threads died
    in the fork — so each facility re-reads the env and starts fresh."""
    from torchstore_tpu.observability import http_exporter as _http
    from torchstore_tpu.observability import metrics as _metrics

    collector().reinit_after_fork()
    _metrics.reinit_dumper_after_fork()
    _http.reinit_after_fork()
    recorder.reinit_after_fork()
    history.reinit_after_fork()

__all__ = [
    "ENV_HISTORY",
    "ENV_HISTORY_INTERVAL",
    "ENV_METRICS_DUMP",
    "ENV_METRICS_INTERVAL",
    "ENV_METRICS_PORT",
    "ENV_SLOW_OP_MS",
    "ENV_TRACE",
    "Counter",
    "Detector",
    "Gauge",
    "Histogram",
    "MetricsHTTPExporter",
    "MetricsRegistry",
    "SeriesStore",
    "TraceCollector",
    "aggregate",
    "collect_trace",
    "collector",
    "context",
    "counter",
    "default_detectors",
    "detect",
    "dump_metrics",
    "evaluate_trends",
    "flush_trace",
    "gauge",
    "get_registry",
    "histogram",
    "history",
    "hot_keys",
    "ledger",
    "maybe_start_dumper",
    "maybe_start_history",
    "maybe_start_http_exporter",
    "merge_traces",
    "metrics_snapshot",
    "profile",
    "record_op",
    "recorder",
    "series_store",
    "render_prometheus_snapshot",
    "reset_metrics",
    "span",
    "start_http_exporter",
    "stop_http_exporter",
    "timeline",
    "trace_enabled",
]
