"""Store-wide observability: metrics registry + span tracing.

Two independent, zero-dependency substrates every layer of the store emits
into (the reference has neither — SURVEY §5 "no counters/prometheus, no
profiler integration"):

- **Metrics** (``observability.metrics``): process-local counters, gauges,
  and fixed-bucket histograms with Prometheus-text and JSON exporters.
  ``ts.metrics_snapshot()`` returns the calling process's registry; volume
  and controller processes expose theirs through their ``stats()``
  endpoints; ``TORCHSTORE_TPU_METRICS_DUMP=/path`` makes every process
  periodically rewrite a machine-readable dump
  (``TORCHSTORE_TPU_METRICS_INTERVAL_S``, default 60).

- **Tracing** (``observability.tracing``): ``span(name, **attrs)`` context
  manager emitting Chrome-trace complete events when
  ``TORCHSTORE_TPU_TRACE=/path/trace.json`` is set — put/get/reshard/
  publish spans carry key, nbytes, transport, and shard coordinates, and
  the file loads directly in Perfetto next to jax profiler traces.

Instrumented layers: ``client.py``/``api.py`` (per-op latency + bytes),
``transport/*`` (per-transport bytes moved, buffer-pool hit/miss,
registration counts), ``controller.py``/``storage_volume.py`` (keys,
resident bytes, write generations, pending reclaims), and
``weight_channel.py`` (publish/acquire versions and subscriber lag).
"""

from torchstore_tpu.observability.metrics import (
    ENV_METRICS_DUMP,
    ENV_METRICS_INTERVAL,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    counter,
    dump_metrics,
    gauge,
    get_registry,
    histogram,
    maybe_start_dumper,
    metrics_snapshot,
    reset_metrics,
)
from torchstore_tpu.observability.tracing import (
    ENV_TRACE,
    TraceCollector,
    collector,
    flush_trace,
    span,
    trace_enabled,
)

__all__ = [
    "ENV_METRICS_DUMP",
    "ENV_METRICS_INTERVAL",
    "ENV_TRACE",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "TraceCollector",
    "collector",
    "counter",
    "dump_metrics",
    "flush_trace",
    "gauge",
    "get_registry",
    "histogram",
    "maybe_start_dumper",
    "metrics_snapshot",
    "reset_metrics",
    "span",
    "trace_enabled",
]
