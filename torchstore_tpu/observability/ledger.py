"""Traffic ledger: who moves which bytes where — decision-grade accounting.

The metrics registry answers "how many bytes did transport X move" as one
process-global counter; placement decisions (ROADMAP item 5) and the O(1)-
egress acceptance of broadcast trees (item 1) need the full matrix: per
(peer host, volume, transport, direction) byte/op cells, plus per-key
rolling windows so "which key is hot RIGHT NOW" is answerable without a
process-lifetime tally. This module is that ledger:

- **Cells**: ``(peer_host, volume, transport, direction)`` -> [ops, bytes].
  ``direction`` is relative to the RECORDING process (``egress`` = bytes
  this process sent, ``ingress`` = bytes it received). Client-side choke
  points (transport/buffers.py, the one-sided stamped-read path, the bulk
  doorbell) know both endpoints and record with ``peer_host`` set; volume-
  side recordings (put/get serves, doorbell packs) know only themselves
  and record with ``peer_host=""`` — the matrix builder uses peer-aware
  cells so every transfer is counted exactly ONCE, at the side that can
  attribute it.
- **Per-key rolling windows**: two rotating buckets (current/previous, each
  ``window_s`` wide, bounded like the hot-key tracker) so the top-K view
  decays — a key that stopped moving falls out within two windows instead
  of dominating forever.

Snapshots ride each process's ``stats()`` endpoint exactly like hot keys;
``ts.fleet_snapshot()`` collects them fleet-wide under ``"ledgers"`` and
``ts.traffic_matrix()`` folds them into ``{src_host: {dst_host: bytes}}``
plus per-host egress/ingress totals — the placement solver's input.

Cost: one lock acquisition per recorded transfer (a put/get BATCH is one
record), plus one dict add per key for the rolling window. Disable with
``TORCHSTORE_TPU_LEDGER=0``; the bench's ``ledger_overhead`` section
measures the always-on cost on the warm many-keys legs.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Iterable, Optional

ENV_LEDGER = "TORCHSTORE_TPU_LEDGER"
ENV_LEDGER_WINDOW = "TORCHSTORE_TPU_LEDGER_WINDOW_S"

EGRESS = "egress"
INGRESS = "ingress"

# Disk spill-tier transfers (torchstore_tpu/tiering/spill.py) ride this
# transport label: they are local I/O, not wire traffic, so the matrix
# builder folds them into their own "disk" section — a placement solver
# reading "edges" must never mistake spill churn for network load.
DISK = "disk"

# Metadata-plane accounting (torchstore_tpu/metadata/router.py): cells
# whose transport is METADATA count controller RPCs (direction "rpc") and
# one-sided stamped reads (direction "stamped") per op — ``peer_host``
# carries the OP name and ``volume`` the shard label ("coord"/"s<i>").
# The matrix folds them into a "metadata" section, never into edges: the
# acceptance "zero metadata RPCs on the warm path" is read right off it.
METADATA = "metadata"

# Quantized wire-tier accounting (state_dict_utils): direction "logical"
# carries the full-precision bytes a publish REPRESENTS, "wire" the fused
# blob bytes that actually shipped. The matrix folds them into a "quant"
# section with the effective compression ratio — never into edges (the
# wire bytes are already counted there by the transports).
QUANT = "quant"


def _hostname() -> str:
    # utils.get_hostname is THE host identity (env-overridable) shared by
    # transports, volume registration, and relay membership — ledger host
    # labels must never diverge from it or edges stop matching volumes.
    from torchstore_tpu.utils import get_hostname

    return get_hostname()


def local_host() -> str:
    """This process's host label (what same-host transfers record as their
    peer: a one-sided read's 'remote' end is a volume on this machine)."""
    return _hostname()


def _env_enabled() -> bool:
    return os.environ.get(ENV_LEDGER, "1").strip().lower() not in (
        "0", "false", "no", "off", "",
    )


def _env_window_s() -> float:
    try:
        return max(1.0, float(os.environ.get(ENV_LEDGER_WINDOW, "300")))
    except ValueError:
        return 300.0


class TrafficLedger:
    """Process-local traffic accounting (lock-light; one lock per record)."""

    MAX_KEYS = 4096
    MAX_CELLS = 4096

    def __init__(self, window_s: Optional[float] = None) -> None:
        self.enabled = _env_enabled()
        # An explicit window is pinned; the env-derived default is re-read
        # at every rotation check so TORCHSTORE_TPU_LEDGER_WINDOW_S can be
        # retuned after the process singleton is constructed (the module
        # imports — and so builds the singleton — before tests and bench
        # sections get a chance to set their knobs).
        self._pinned = window_s is not None
        self.window_s = window_s if window_s is not None else _env_window_s()
        self._lock = threading.Lock()
        # (peer_host, volume, transport, direction) -> [ops, bytes]
        self._cells: dict[tuple, list] = {}
        # Rolling per-key windows: two rotating buckets, key -> [ops, bytes].
        self._win_started = time.monotonic()
        self._cur: dict[str, list] = {}
        self._prev: dict[str, list] = {}
        # Whole-process rolling totals (every record, keyed or not), same
        # two-bucket rotation: the "how loaded is this process RIGHT NOW"
        # signal ts.slo_report folds per volume — a lifetime cell total
        # can't answer that.
        self._cur_totals = [0, 0]
        self._prev_totals = [0, 0]

    def set_enabled(self, enabled: bool) -> None:
        self.enabled = bool(enabled)

    def record(
        self,
        transport: str,
        direction: str,
        nbytes: int,
        peer_host: str = "",
        volume: str = "",
        items: Optional[Iterable[tuple]] = None,
        ops: int = 1,
        weight: int = 1,
    ) -> None:
        """Account one transfer (a whole batch is ONE record). ``items`` is
        an optional ``[(key, nbytes), ...]`` feed for the per-key rolling
        window; keys may be None (skipped). A SAMPLED caller (the one-sided
        path records 1-in-N large batches) passes ``weight=N`` with
        pre-scaled ``nbytes``/``ops`` so cell totals and window tallies
        both stay expectation-exact."""
        if not self.enabled:
            return
        cell_key = (peer_host or "", str(volume or ""), transport, direction)
        with self._lock:
            cell = self._cells.get(cell_key)
            if cell is None:
                if len(self._cells) >= self.MAX_CELLS:
                    self._cells.clear()  # unbounded peer churn: restart cheap
                cell = self._cells[cell_key] = [0, 0]
            cell[0] += ops
            cell[1] += int(nbytes)
            self._maybe_rotate_locked()
            self._cur_totals[0] += ops
            self._cur_totals[1] += int(nbytes)
            if items is not None:
                cur = self._cur
                for key, kbytes in items:
                    if key is None:
                        continue
                    stat = cur.get(key)
                    if stat is None:
                        if len(cur) >= self.MAX_KEYS:
                            continue  # window full: totals still account
                        stat = cur[key] = [0, 0]
                    stat[0] += weight
                    stat[1] += int(kbytes) * weight

    def _maybe_rotate_locked(self) -> None:
        """Advance the rolling window (caller holds the lock). Run on both
        writes AND reads: an idle process's snapshot must not keep serving
        hour-old keys as "hot right now" — after one idle window the stale
        bucket slides to previous, after two both are dropped."""
        if not self._pinned:
            self.window_s = _env_window_s()
        now = time.monotonic()
        elapsed = now - self._win_started
        if elapsed < self.window_s:
            return
        if elapsed >= 2 * self.window_s:
            self._prev = {}
            self._prev_totals = [0, 0]
        else:
            self._prev = self._cur
            self._prev_totals = self._cur_totals
        self._cur = {}
        self._cur_totals = [0, 0]
        self._win_started = now

    def top_keys(self, k: int = 20) -> list[dict]:
        """Top-K keys by bytes over the last one-to-two rolling windows."""
        with self._lock:
            self._maybe_rotate_locked()
            merged: dict[str, list] = {
                key: list(stat) for key, stat in self._prev.items()
            }
            for key, stat in self._cur.items():
                agg = merged.get(key)
                if agg is None:
                    merged[key] = list(stat)
                else:
                    agg[0] += stat[0]
                    agg[1] += stat[1]
        items = sorted(merged.items(), key=lambda kv: kv[1][1], reverse=True)
        return [
            {"key": key, "ops": stat[0], "bytes": stat[1]}
            for key, stat in items[:k]
        ]

    def snapshot(self) -> dict:
        """JSON-serializable ledger view (rides ``stats()`` endpoints and
        ``ts.fleet_snapshot()["ledgers"]``)."""
        with self._lock:
            cells = [
                {
                    "peer_host": peer_host,
                    "volume": volume,
                    "transport": transport,
                    "direction": direction,
                    "ops": cell[0],
                    "bytes": cell[1],
                }
                for (peer_host, volume, transport, direction), cell
                in self._cells.items()
            ]
            self._maybe_rotate_locked()
            window = {
                "ops": self._cur_totals[0] + self._prev_totals[0],
                "bytes": self._cur_totals[1] + self._prev_totals[1],
            }
        return {
            "host": _hostname(),
            "pid": os.getpid(),
            "window_s": self.window_s,
            "cells": cells,
            # Transfers this process accounted over the last one-to-two
            # rolling windows (decays like the per-key view): the recent-
            # throughput overload signal, vs the lifetime cell totals.
            "window": window,
            "keys": self.top_keys(20),
        }

    def reset(self) -> None:
        with self._lock:
            self._cells.clear()
            self._cur.clear()
            self._prev.clear()
            self._cur_totals = [0, 0]
            self._prev_totals = [0, 0]
            self._win_started = time.monotonic()


_ledger = TrafficLedger()


def ledger() -> TrafficLedger:
    return _ledger


def record(
    transport: str,
    direction: str,
    nbytes: int,
    peer_host: str = "",
    volume: str = "",
    items: Optional[Iterable[tuple]] = None,
    ops: int = 1,
    weight: int = 1,
) -> None:
    """Module-level convenience over the process singleton."""
    _ledger.record(
        transport,
        direction,
        nbytes,
        peer_host=peer_host,
        volume=volume,
        items=items,
        ops=ops,
        weight=weight,
    )


def snapshot() -> dict:
    return _ledger.snapshot()


def reset_ledger() -> None:
    _ledger.reset()


def traffic_matrix(ledgers: dict[str, dict]) -> dict:
    """Fold fleet-collected ledger snapshots into the placement solver's
    input. ``ledgers`` maps a process label (``"client"``,
    ``"volume:<vid>"``, ...) to that process's :func:`snapshot`.

    Every transfer is counted exactly once: only PEER-AWARE cells (the
    recording side knew both endpoints — client-side choke points, which
    see every put, get, one-sided read, and doorbell) contribute edges;
    peer-less volume-side cells are reported under ``"unattributed"`` so
    their bytes are visible but never double-counted against the client's
    view of the same transfer.

    Disk spill-tier cells (``transport == DISK``) are folded into their
    own ``"disk"`` section per volume — spill/fault-in I/O stays visible
    without ever being mistaken for wire bytes on an edge. Metadata cells
    (``transport == METADATA``) fold into a ``"metadata"`` section:
    controller RPC counts per op (plus per shard) next to the stamped
    zero-RPC reads that replaced them on the warm path.

    Returns ``{"edges": {src_host: {dst_host: {"bytes", "ops"}}},
    "egress": {host: bytes}, "ingress": {host: bytes},
    "volumes": {volume_id: {"bytes_in", "bytes_out"}},
    "disk": {volume_id: {"spill_bytes", "fault_in_bytes"}},
    "unattributed": {host: {"bytes_in", "bytes_out"}}}``."""
    edges: dict[str, dict[str, dict]] = {}
    egress: dict[str, int] = {}
    ingress: dict[str, int] = {}
    volumes: dict[str, dict] = {}
    disk: dict[str, dict] = {}
    quant = {"bytes_logical": 0, "bytes_wire": 0}
    metadata: dict[str, dict] = {"rpcs": {}, "stamped": {}, "rpcs_by_shard": {}}
    unattributed: dict[str, dict] = {}

    def _edge(src: str, dst: str, nbytes: int, ops: int) -> None:
        cell = edges.setdefault(src, {}).setdefault(
            dst, {"bytes": 0, "ops": 0}
        )
        cell["bytes"] += nbytes
        cell["ops"] += ops
        egress[src] = egress.get(src, 0) + nbytes
        ingress[dst] = ingress.get(dst, 0) + nbytes

    for snap in ledgers.values():
        host = snap.get("host", "")
        for cell in snap.get("cells", ()):
            nbytes = int(cell.get("bytes", 0))
            ops = int(cell.get("ops", 0))
            peer = cell.get("peer_host") or ""
            direction = cell.get("direction")
            vid = cell.get("volume") or ""
            if cell.get("transport") == DISK:
                d = disk.setdefault(
                    vid or host, {"spill_bytes": 0, "fault_in_bytes": 0}
                )
                d[
                    "spill_bytes" if direction == EGRESS else "fault_in_bytes"
                ] += nbytes
                continue
            if cell.get("transport") == METADATA:
                op = peer or "?"
                if direction == "stamped":
                    metadata["stamped"][op] = (
                        metadata["stamped"].get(op, 0) + ops
                    )
                else:
                    metadata["rpcs"][op] = metadata["rpcs"].get(op, 0) + ops
                    shard = vid or "coord"
                    metadata["rpcs_by_shard"][shard] = (
                        metadata["rpcs_by_shard"].get(shard, 0) + ops
                    )
                continue
            if cell.get("transport") == QUANT:
                quant[
                    "bytes_wire" if direction == "wire" else "bytes_logical"
                ] += nbytes
                continue
            if vid and peer:
                # Per-volume totals from peer-aware cells ONLY (same
                # count-once rule as the edges): an RPC get is recorded
                # both client-side (peer-aware) and volume-side (peer-less)
                # — counting both would double the volume's served bytes.
                vol = volumes.setdefault(
                    vid, {"bytes_in": 0, "bytes_out": 0}
                )
                if direction == EGRESS:
                    vol["bytes_in"] += nbytes  # this process sent TO it
                else:
                    vol["bytes_out"] += nbytes  # it served this process
            if peer:
                if direction == EGRESS:
                    _edge(host, peer, nbytes, ops)
                else:
                    _edge(peer, host, nbytes, ops)
            else:
                un = unattributed.setdefault(
                    host, {"bytes_in": 0, "bytes_out": 0}
                )
                un["bytes_out" if direction == EGRESS else "bytes_in"] += (
                    nbytes
                )
    if quant["bytes_wire"]:
        quant["compression_ratio"] = round(
            quant["bytes_logical"] / quant["bytes_wire"], 3
        )
    return {
        "edges": edges,
        "egress": egress,
        "ingress": ingress,
        "volumes": volumes,
        "disk": disk,
        "quant": quant,
        "metadata": metadata,
        "unattributed": unattributed,
    }
