"""Flight recorder: an always-on, bounded ring of recent ops/spans/faults.

"test_repair flaked" usually means five seconds of fleet history nobody
recorded: which ops were in flight, which faults fired, which volume went
quiet first. Each store process keeps a bounded ring buffer
(``collections.deque(maxlen=...)`` — appends are O(1), atomic under the
GIL, no lock on the hot path) of recent events:

    op          completed logical client ops (op, keys, bytes, ms)
    transfer    transport-level moves (transport, volume, direction, bytes)
    volume_op   volume-side put/get serves
    fault       every faultpoint firing (point, action)
    error       failures worth a post-mortem line (op errors, fallbacks)
    stream      streamed-sync lifecycle (begin/restart/seal/ack)
    health      supervisor transitions (quarantine/probation/reinstate)
    slo         SLO threshold breaches

**Auto-dump**: on quarantine (controller, MERGED with every reachable
volume's ring), on ``ts.repair()``, on a wedged/mixed stream (acquire
exhausts its retries), on a ``die``-action fault (the ring is flushed in
the doomed process before ``os._exit``), and — via :func:`arm_exit_dump` —
at interpreter exit IF the ring recorded errors/faults since the last dump
(an unclean exit leaves its last seconds on disk; a clean one writes
nothing). Dumps are atomic whole-file JSON under
``TORCHSTORE_TPU_FLIGHT_DIR`` (default ``<tmpdir>/torchstore_tpu_flight``),
one file per (trigger, pid) so repeats overwrite instead of accumulating.

**On demand**: ``ts.flight_record()`` merges the local ring with the
controller's and every reachable volume's (``flight_record`` endpoints)
into one time-sorted timeline.

Overhead: one deque append per recorded event; events are recorded per
BATCH/op, never per key. ``TORCHSTORE_TPU_FLIGHT_RECORDER=0`` disables
recording entirely; the bench's ``ledger_overhead`` section measures the
always-on cost.
"""

from __future__ import annotations

import collections
import json
import os
import tempfile
import threading
import time
from typing import Any, Optional

from torchstore_tpu.observability import metrics as obs_metrics

ENV_FLIGHT = "TORCHSTORE_TPU_FLIGHT_RECORDER"
ENV_FLIGHT_EVENTS = "TORCHSTORE_TPU_FLIGHT_EVENTS"
ENV_FLIGHT_DIR = "TORCHSTORE_TPU_FLIGHT_DIR"
ENV_FLIGHT_MIN_INTERVAL = "TORCHSTORE_TPU_FLIGHT_MIN_INTERVAL_S"

# Event kinds a post-mortem exists for: their presence since the last dump
# makes an interpreter exit "unclean" (arm_exit_dump writes the ring).
ALERT_KINDS = frozenset({"fault", "error", "health", "slo"})

_DUMPS = obs_metrics.counter(
    "ts_flight_dumps_total", "Flight-recorder post-mortems written, by reason"
)
_DROPPED = obs_metrics.counter(
    "ts_flight_dumps_dropped_total",
    "Post-mortems suppressed by the per-kind rate limit, by reason",
)


def _hostname() -> str:
    """The shared env-overridable host identity (utils.get_hostname) —
    post-mortem host labels must match ledger/volume/relay labels. Lazy
    import: the recorder loads before most of the package."""
    from torchstore_tpu.utils import get_hostname

    return get_hostname()


def _env_enabled() -> bool:
    return os.environ.get(ENV_FLIGHT, "1").strip().lower() not in (
        "0", "false", "no", "off", "",
    )


def _env_events() -> int:
    try:
        return max(64, int(os.environ.get(ENV_FLIGHT_EVENTS, "4096")))
    except ValueError:
        return 4096


def flight_dir() -> str:
    return os.environ.get(ENV_FLIGHT_DIR) or os.path.join(
        tempfile.gettempdir(), "torchstore_tpu_flight"
    )


def _min_interval_s() -> float:
    """Per-trigger-kind dump rate limit (seconds). A sustained fault storm
    (a chaos-heavy loadgen run: every die-fault, wedge, and quarantine
    wants a post-mortem) must not fill ``TORCHSTORE_TPU_FLIGHT_DIR`` —
    one dump per kind per interval keeps the freshest history on disk and
    counts the rest in ``ts_flight_dumps_dropped_total``. 0 disables the
    limit."""
    try:
        return max(
            0.0, float(os.environ.get(ENV_FLIGHT_MIN_INTERVAL, "30"))
        )
    except ValueError:
        return 30.0


class FlightRecorder:
    """Bounded per-process event ring. ``record`` is the hot path: build a
    small tuple, append to a deque — no lock (GIL-atomic), no I/O."""

    def __init__(self, maxlen: Optional[int] = None) -> None:
        self.enabled = _env_enabled()
        self._ring: collections.deque = collections.deque(
            maxlen=maxlen or _env_events()
        )
        # Alert events recorded since the last dump (drives the unclean-
        # exit heuristic); plain int updates are GIL-atomic enough for a
        # heuristic counter.
        self._alerts_since_dump = 0
        self._exit_armed = False
        # trigger kind -> monotonic ts of its last WRITTEN dump (the
        # per-kind rate-limit state; see _min_interval_s).
        self._last_dump: dict[str, float] = {}

    def set_enabled(self, enabled: bool) -> None:
        self.enabled = bool(enabled)

    def record(self, kind: str, name: str, **detail: Any) -> None:
        if not self.enabled:
            return
        self._ring.append((time.time(), kind, name, detail or None))
        if kind in ALERT_KINDS:
            self._alerts_since_dump += 1

    def snapshot(self) -> list[dict]:
        """The ring as JSON-serializable events, oldest first."""
        pid = os.getpid()
        return [
            {
                "ts": ts,
                "pid": pid,
                "kind": kind,
                "name": name,
                **({"detail": detail} if detail else {}),
            }
            for ts, kind, name, detail in list(self._ring)
        ]

    def clear(self) -> None:
        self._ring.clear()
        self._alerts_since_dump = 0

    def dump(
        self, trigger: str, extra_events: Optional[list[dict]] = None
    ) -> Optional[str]:
        """Write an atomic post-mortem JSON (this process's ring plus any
        ``extra_events`` a merging caller collected) and return its path;
        None when recording is disabled, the ring is empty, or the write
        fails (a post-mortem must never take the process down with it)."""
        if not self.enabled:
            return None
        # Per-kind rate limit FIRST — before the ring is even copied:
        # under a fault storm every die/quarantine/wedge wants its own
        # post-mortem, and a suppressed trigger must cost O(1), not an
        # O(ring) collect+sort on the victim's event loop. One dump per
        # kind per TORCHSTORE_TPU_FLIGHT_MIN_INTERVAL_S; the rest are
        # counted. Distinct kinds never shadow each other (a quarantine
        # still dumps while die-faults are storming).
        reason = trigger.split(":", 1)[0]
        interval = _min_interval_s()
        now = time.monotonic()
        if interval > 0:
            last = self._last_dump.get(reason)
            if last is not None and now - last < interval:
                _DROPPED.inc(reason=reason)
                return None
        events = self.snapshot() + list(extra_events or ())
        if not events:
            return None
        events.sort(key=lambda e: e.get("ts") or 0)
        safe = "".join(
            ch if ch.isalnum() or ch in "-_" else "_" for ch in trigger
        )[:80]
        path = os.path.join(
            flight_dir(), f"flight_{safe}_{os.getpid()}.json"
        )
        payload = {
            "trigger": trigger,
            "ts": time.time(),
            "pid": os.getpid(),
            "host": _hostname(),
            "events": events,
        }
        # A post-mortem should carry the last five minutes of this
        # process's vitals (op tails, landing pressure, op rates), not
        # just events — the ring answers "what was it doing" while the
        # history answers "what was it trending toward". Never let a
        # history failure cost the dump itself.
        try:
            from torchstore_tpu.observability import history as obs_history

            payload["history"] = obs_history.dump_vitals()
        except Exception:  # noqa: BLE001 - post-mortem survives regardless
            pass
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            os.makedirs(flight_dir(), exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, path)
        except OSError:
            return None
        self._alerts_since_dump = 0
        self._last_dump[reason] = now
        _DUMPS.inc(reason=reason)
        from torchstore_tpu.logging import get_logger

        get_logger("torchstore_tpu.observability").warning(
            "flight recorder post-mortem (%s): %d event(s) -> %s",
            trigger,
            len(events),
            path,
        )
        return path

    def arm_exit_dump(self) -> None:
        """Register an atexit hook that dumps the ring IF alert events
        (faults/errors/health/slo) were recorded since the last dump — an
        unclean exit leaves its last seconds on disk, a clean one writes
        nothing. Idempotent per process."""
        if self._exit_armed:
            return
        self._exit_armed = True
        import atexit

        def _maybe_dump() -> None:
            if self._alerts_since_dump:
                self.dump("unclean_exit")

        atexit.register(_maybe_dump)


_recorder = FlightRecorder()
_reinit_lock = threading.Lock()  # tslint: disable=fork-safety


def recorder() -> FlightRecorder:
    return _recorder


def record(kind: str, name: str, **detail: Any) -> None:
    """Module-level convenience over the process singleton."""
    _recorder.record(kind, name, **detail)


def snapshot() -> list[dict]:
    return _recorder.snapshot()


def dump_postmortem(
    trigger: str, extra_events: Optional[list[dict]] = None
) -> Optional[str]:
    return _recorder.dump(trigger, extra_events)


def reset_recorder() -> None:
    _recorder.clear()


def reinit_after_fork() -> None:
    """Forked actor children inherit the parent ring's copied events and a
    possibly stale enabled flag: start the child's history fresh from its
    corrected env."""
    with _reinit_lock:
        _recorder.clear()
        _recorder.enabled = _env_enabled()
        _recorder._exit_armed = False
        _recorder._last_dump.clear()
