"""SPMD bootstrap: bring up one store across a torchrun-style world.

TPU-native equivalent of /root/reference/torchstore/spmd.py:43-365. Every
rank reads the standard launcher env (RANK/WORLD_SIZE/LOCAL_RANK/
LOCAL_WORLD_SIZE/MASTER_ADDR/MASTER_PORT — the same vars a jax multi-host
pod launcher exports), rendezvouses on a KV service hosted by rank 0, and:

- each host's LOCAL_RANK-0 spawns that host's storage volumes (per-rank for
  LocalRankStrategy, one for HostStrategy) and publishes their refs — this
  generalizes the reference's rank-0-spawns-everything to multi-host without
  a remote-spawn dependency;
- global rank 0 collects all volume refs, spawns the controller, runs
  ``Controller.init``, and broadcasts the pickled controller handle;
- every rank builds its LocalClient from the broadcast handle.

Shutdown is two-phase with a status broadcast so non-primary ranks learn of
primary failure (reference _SPMDSession, spmd.py:106-203).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional

from torchstore_tpu import api
from torchstore_tpu.config import StoreConfig, default_config
from torchstore_tpu.controller import Controller
from torchstore_tpu.logging import get_logger
from torchstore_tpu.runtime import ActorMesh, get_or_spawn_singleton, spawn_actors, stop_singleton
from torchstore_tpu.runtime.rendezvous import (
    RendezvousClient,
    RendezvousServer,
    pickle_handle,
    unpickle_handle,
)
from torchstore_tpu.storage_volume import StorageVolume
from torchstore_tpu.strategy import HostStrategy, LocalRankStrategy, StoreStrategy

logger = get_logger("torchstore_tpu.spmd")


@dataclass(frozen=True)
class SPMDEnv:
    rank: int
    world_size: int
    local_rank: int
    local_world_size: int
    master_addr: str
    master_port: int

    @classmethod
    def from_env(cls) -> "SPMDEnv":
        missing = [
            name
            for name in ("RANK", "WORLD_SIZE", "MASTER_ADDR", "MASTER_PORT")
            if name not in os.environ
        ]
        if missing:
            raise RuntimeError(
                f"SPMD env incomplete: missing {missing}; launch via a "
                "torchrun-style launcher or export them manually"
            )
        rank = int(os.environ["RANK"])
        world = int(os.environ["WORLD_SIZE"])
        local_world = int(os.environ.get("LOCAL_WORLD_SIZE", world))
        local_rank = int(os.environ.get("LOCAL_RANK", rank % max(local_world, 1)))
        if not (0 <= rank < world):
            raise ValueError(f"RANK {rank} out of range for WORLD_SIZE {world}")
        if not (0 <= local_rank < local_world):
            raise ValueError(
                f"LOCAL_RANK {local_rank} out of range for "
                f"LOCAL_WORLD_SIZE {local_world}"
            )
        if world % local_world != 0:
            raise ValueError(
                f"WORLD_SIZE {world} not divisible by LOCAL_WORLD_SIZE {local_world}"
            )
        return cls(
            rank=rank,
            world_size=world,
            local_rank=local_rank,
            local_world_size=local_world,
            master_addr=os.environ["MASTER_ADDR"],
            master_port=int(os.environ["MASTER_PORT"]),
        )

    @property
    def num_hosts(self) -> int:
        return self.world_size // self.local_world_size

    @property
    def host_rank(self) -> int:
        return self.rank // self.local_world_size


class _SPMDSession:
    def __init__(
        self,
        env: SPMDEnv,
        store_name: str,
        server: Optional[RendezvousServer],
        client: RendezvousClient,
        volume_mesh: Optional[ActorMesh],
        controller_is_local: bool,
    ):
        self.env = env
        self.store_name = store_name
        self.server = server
        self.client = client
        self.volume_mesh = volume_mesh
        self.controller_is_local = controller_is_local

    async def shutdown(self) -> None:
        """Two-phase: everyone signals done; rank 0 tears down and broadcasts
        status; the rest read it (so a primary failure is observable)."""
        env = self.env
        key = f"spmd/{self.store_name}/shutdown"
        try:
            await self.client.add(f"{key}/ready", 1)
            if env.rank == 0:
                await self.client.wait_counter(f"{key}/ready", env.world_size)
                status = "ok"
                try:
                    handle = api._stores.get(self.store_name)
                    if handle is not None:
                        await handle.controller.teardown.call_one()
                except Exception as exc:
                    status = f"controller teardown failed: {exc!r}"
                await self.client.set(f"{key}/status", status)
            status = await self.client.get(f"{key}/status")
            if status != "ok":
                logger.warning("spmd shutdown status: %s", status)
            # Final ack: rank 0 must not stop the rendezvous server until
            # every rank has read the status (a force-closed connection would
            # turn a clean shutdown into ConnectionError on slow ranks).
            await self.client.add(f"{key}/acked", 1)
            if env.rank == 0:
                await self.client.wait_counter(f"{key}/acked", env.world_size)
        finally:
            handle = api._stores.get(self.store_name)
            if handle is not None and handle.client is not None:
                from torchstore_tpu import state_dict_utils

                await state_dict_utils.close_direct_caches(handle.client)
            if self.volume_mesh is not None:
                await self.volume_mesh.stop()
            if self.controller_is_local:
                await stop_singleton(f"ts_{self.store_name}_controller")
            await self.client.close()
            if self.server is not None:
                await self.server.stop()
            api._stores.pop(self.store_name, None)
            os.environ.pop(api.ENV_STORE_PREFIX + self.store_name, None)


# Per-rank session registry; actor children are never SPMD ranks, and ranks
# themselves are started by torchrun, not forked from each other.
_spmd_sessions: dict[str, _SPMDSession] = {}  # tslint: disable=fork-safety


async def initialize(
    strategy: Optional[StoreStrategy] = None,
    store_name: str = api.DEFAULT_STORE,
    config: Optional[StoreConfig] = None,
    storage_dir: Optional[str] = None,
    recover: bool = False,
) -> None:
    """Collective store bootstrap — call from every rank of the world. With
    ``storage_dir`` each host's volumes persist under
    ``<dir>/<volume_id>`` (a shared filesystem or per-host path);
    ``recover=True`` rebuilds the index from disk on rank 0."""
    env = SPMDEnv.from_env()
    if recover and not storage_dir:
        raise ValueError("recover=True requires storage_dir")
    config = config or default_config()
    if strategy is None:
        strategy = LocalRankStrategy()
    if not isinstance(strategy, (LocalRankStrategy, HostStrategy)):
        raise ValueError(
            "SPMD initialization supports LocalRankStrategy and HostStrategy "
            f"only (got {type(strategy).__name__})"
        )
    total_volumes = (
        env.world_size
        if isinstance(strategy, LocalRankStrategy)
        else env.num_hosts
    )
    if strategy.replication > total_volumes:
        # Fail at bootstrap on every rank, not at the first put mid-training.
        raise ValueError(
            f"replication={strategy.replication} needs at least that many "
            f"storage volumes (this SPMD world provides {total_volumes})"
        )
    if store_name in _spmd_sessions:
        raise RuntimeError(f"SPMD store {store_name!r} already initialized")

    # --- rendezvous -------------------------------------------------------
    def _loopback_bind_addr(addr: str) -> Optional[str]:
        """The RESOLVED loopback IP when ``addr`` is loopback-only, else
        None. Binding the resolved IP (not a hardcoded 127.0.0.1) matters:
        Debian-style /etc/hosts maps $(hostname) to 127.0.1.1 — clients
        connect to whatever MASTER_ADDR resolves to, so the listener must
        bind exactly that."""
        import socket as _socket

        try:
            ips = {info[4][0] for info in _socket.getaddrinfo(addr, None)}
        except OSError:
            return None
        if ips and all(ip.startswith("127.") or ip == "::1" for ip in ips):
            return next(iter(ips))
        return None

    server = None
    if env.rank == 0:
        server = RendezvousServer()
        # Loopback-resolved MASTER_ADDR means every rank is local: bind that
        # exact loopback IP so the (pickle-speaking) rendezvous port stays
        # private. Anything else binds all interfaces — binding a
        # non-loopback MASTER_ADDR itself can pick an interface peers
        # cannot actually route to (container NAT).
        loop_ip = _loopback_bind_addr(env.master_addr)
        await server.start(loop_ip or "0.0.0.0", env.master_port)
        from torchstore_tpu.runtime.auth import get_secret

        if env.num_hosts > 1 and not get_secret():
            logger.warning(
                "multi-host SPMD without TORCHSTORE_TPU_AUTH_SECRET: the "
                "rendezvous/actor/bulk listeners accept any host that can "
                "reach them (and unpickle peer payloads). Set the same "
                "secret on every host to enable connection auth."
            )
    client = RendezvousClient(env.master_addr, env.master_port)
    await client.connect()
    ns = f"spmd/{store_name}"

    multi_host = env.num_hosts > 1
    volume_mesh: Optional[ActorMesh] = None

    async def _spawn_local_volumes() -> ActorMesh:
        if isinstance(strategy, LocalRankStrategy):
            num_local = env.local_world_size
            base_rank = env.host_rank * env.local_world_size

            def env_fn(i: int) -> dict[str, str]:
                extra = {
                    "RANK": str(base_rank + i),
                    "LOCAL_RANK": str(i),
                    "WORLD_SIZE": str(env.world_size),
                    "LOCAL_WORLD_SIZE": str(env.local_world_size),
                }
                if multi_host:
                    extra["TORCHSTORE_TPU_BIND_HOST"] = "0.0.0.0"
                if storage_dir:
                    extra["TORCHSTORE_TPU_STORAGE_DIR"] = storage_dir
                return extra

        else:  # HostStrategy: one volume per host
            num_local = 1

            def env_fn(i: int) -> dict[str, str]:
                extra = {}
                if multi_host:
                    extra["TORCHSTORE_TPU_BIND_HOST"] = "0.0.0.0"
                if storage_dir:
                    extra["TORCHSTORE_TPU_STORAGE_DIR"] = storage_dir
                return extra

        return await spawn_actors(
            num_local,
            StorageVolume,
            f"ts_{store_name}_volume_h{env.host_rank}",
            strategy,
            env_fn=env_fn,
        )

    # --- volumes + controller, failure-broadcasting -----------------------
    # Rank 0 ALWAYS publishes a status (ok + handle, or error) covering the
    # WHOLE bootstrap from volume spawn onward: a rank-0 failure must fail
    # every rank promptly, not leave them blocked on a never-set key with
    # spawned volume processes leaked.
    try:
        if env.rank == 0:
            try:
                volume_mesh = await _spawn_local_volumes()
                await client.set(
                    f"{ns}/volumes/{env.host_rank}",
                    pickle_handle(volume_mesh.refs),
                )
                all_refs = []
                for host in range(env.num_hosts):
                    raw = await client.get(f"{ns}/volumes/{host}")
                    all_refs.extend(unpickle_handle(raw))
                controller = await get_or_spawn_singleton(
                    f"ts_{store_name}_controller", Controller
                )
                await controller.init.call_one(strategy, all_refs)
                if recover:
                    recovered = await controller.rebuild_index.call_one()
                    logger.info(
                        "spmd recovered %d entries from %s", recovered, storage_dir
                    )
            except BaseException as exc:
                await client.set(
                    f"{ns}/controller_status", ("error", repr(exc))
                )
                raise
            await client.set(
                f"{ns}/controller_status", ("ok", pickle_handle(controller))
            )
        elif env.local_rank == 0:
            volume_mesh = await _spawn_local_volumes()
            await client.set(
                f"{ns}/volumes/{env.host_rank}", pickle_handle(volume_mesh.refs)
            )
        status, payload = await client.get(f"{ns}/controller_status")
        if status != "ok":
            raise RuntimeError(f"SPMD bootstrap failed on rank 0: {payload}")
        controller = unpickle_handle(payload)
    except BaseException:
        # Local cleanup on any bootstrap failure: spawned volumes must not
        # outlive a failed initialize (parity with api.initialize).
        if volume_mesh is not None:
            await volume_mesh.stop()
        if env.rank == 0:
            await stop_singleton(f"ts_{store_name}_controller")
        await client.close()
        if server is not None:
            await server.stop()
        raise

    api._publish_handle(store_name, controller)
    api._stores[store_name] = api._StoreHandle(
        controller=controller,
        volume_mesh=volume_mesh,
        client=None,
        config=config,
        owner=False,  # teardown is the SPMD session's job, not api.shutdown's
    )
    _spmd_sessions[store_name] = _SPMDSession(
        env=env,
        store_name=store_name,
        server=server,
        client=client,
        volume_mesh=volume_mesh,
        controller_is_local=(env.rank == 0),
    )
    await client.barrier(f"{ns}/ready", env.world_size)


async def shutdown(store_name: str = api.DEFAULT_STORE) -> bool:
    """Collective shutdown; returns False when no SPMD session exists (the
    caller falls back to plain api.shutdown — reference routing,
    /root/reference/torchstore/api.py:100-109)."""
    session = _spmd_sessions.pop(store_name, None)
    if session is None:
        return False
    await session.shutdown()
    return True
