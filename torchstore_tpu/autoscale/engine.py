"""The autoscale engine: scrape the fleet, solve, scale — audited.

One ``AutoscaleEngine`` lives inside the Controller (coordinator)
process next to the control engine and mirrors its phased round:
SNAPSHOT (a stats-only fan-out folded into the same frozen
:class:`TelemetrySnapshot` the placement solver reads, plus the
engine-side :class:`FleetView` — draining set, size envelope, the
consecutive-idle-round counter, the blob tier's spilled backlog), SOLVE
(the pure policy in ``autoscale/solver.py``), ACT.

Actuation split (the one asymmetry vs. the control engine): the
coordinator cannot spawn actors — the owner process that called
``initialize()`` does. A ``scale_out`` action is therefore surfaced as a
``deferred`` decision and executed by ``ts.autoscale()`` client-side
(spawn via the initialize spawn path + ``volume_env_fn``, adopt via the
controller's ``attach_volume`` endpoint, then a control reconcile seeds
placement onto the empty volume). Drain, retire, and blob demotion ARE
coordinator-reachable and apply inline: drain marks the volume draining
(clients route puts around it, reads keep serving) and migrates resident
keys batch-by-batch through ``idx.migrate_key`` — the same online-move
actuator as control migrations — and retire detaches the empty volume
from the index and the fleet maps.

Every applied, deferred, refused, or failed action lands in the flight
recorder as a ``decision`` event (``autoscale/<kind>``) and in the
``ts_autoscale_*`` metrics; ``plan()`` is the dry-run half
``ts.autoscale_plan()`` serves. ``checkpoint()`` is the scale-to-zero
half: every volume archives its committed payloads into the blob tier
and the engine writes the durable fleet manifest a cold restore replays.
"""

from __future__ import annotations

import os
import time
from collections import deque
from typing import Any, Mapping, Optional

from torchstore_tpu import faults
from torchstore_tpu.autoscale.solver import (
    BLOB_DEMOTE,
    DRAIN,
    RETIRE,
    SCALE_OUT,
    AutoscaleAction,
    AutoscalePolicy,
    FleetView,
    _fleet_idle,
    solve,
)
from torchstore_tpu.control.snapshot import TelemetrySnapshot, build_snapshot
from torchstore_tpu.control.solver import ActionRecord
from torchstore_tpu.logging import get_logger
from torchstore_tpu.observability import metrics as obs_metrics
from torchstore_tpu.observability import recorder as obs_recorder
from torchstore_tpu.tiering import blob as blob_mod

logger = get_logger("torchstore_tpu.autoscale.engine")

_DECISIONS = obs_metrics.counter(
    "ts_autoscale_decisions_total",
    "Autoscale decisions, by action kind and outcome",
)
_ROUNDS = obs_metrics.counter(
    "ts_autoscale_rounds_total",
    "Autoscale reconcile rounds, by trigger",
)
_LAST_ACTIONS = obs_metrics.gauge(
    "ts_autoscale_last_actions",
    "Actions the last autoscale round decided",
)
_FLEET_VOLUMES = obs_metrics.gauge(
    "ts_fleet_volumes",
    "Storage volumes currently attached to the fleet",
)
_FLEET_DRAINING = obs_metrics.gauge(
    "ts_fleet_draining",
    "Storage volumes currently draining toward retirement",
)

# Engine-only action kind: not solver-emitted — ``checkpoint()`` routes
# the manual scale-to-zero archive through the same audit chokepoint.
BLOB_CHECKPOINT = "blob_checkpoint"

# Same damping-memory depth as the control engine.
_HISTORY = 256


def policy_from_env() -> AutoscalePolicy:
    """Solver thresholds with ``TORCHSTORE_TPU_AUTOSCALE_*`` overrides
    (raw-environ pattern: the engine lives in the controller process,
    not behind StoreConfig)."""

    def _f(name: str, default: float) -> float:
        raw = os.environ.get(name)
        return float(raw) if raw not in (None, "") else default

    base = AutoscalePolicy()
    return AutoscalePolicy(
        min_volumes=int(
            _f("TORCHSTORE_TPU_AUTOSCALE_MIN_VOLUMES", base.min_volumes)
        ),
        max_volumes=int(
            _f("TORCHSTORE_TPU_AUTOSCALE_MAX_VOLUMES", base.max_volumes)
        ),
        out_inflight=int(
            _f("TORCHSTORE_TPU_AUTOSCALE_OUT_INFLIGHT", base.out_inflight)
        ),
        out_window_bytes=int(
            _f(
                "TORCHSTORE_TPU_AUTOSCALE_OUT_WINDOW_BYTES",
                base.out_window_bytes,
            )
        ),
        idle_window_bytes=int(
            _f(
                "TORCHSTORE_TPU_AUTOSCALE_IDLE_WINDOW_BYTES",
                base.idle_window_bytes,
            )
        ),
        idle_rounds=int(
            _f("TORCHSTORE_TPU_AUTOSCALE_IDLE_ROUNDS", base.idle_rounds)
        ),
        drain_keys_per_round=int(
            _f(
                "TORCHSTORE_TPU_AUTOSCALE_DRAIN_KEYS_PER_ROUND",
                base.drain_keys_per_round,
            )
        ),
        blob_keys_per_round=int(
            _f(
                "TORCHSTORE_TPU_AUTOSCALE_BLOB_KEYS_PER_ROUND",
                base.blob_keys_per_round,
            )
        ),
        cooldown_s=_f("TORCHSTORE_TPU_AUTOSCALE_COOLDOWN_S", base.cooldown_s),
        max_actions=int(
            _f("TORCHSTORE_TPU_AUTOSCALE_MAX_ACTIONS", base.max_actions)
        ),
    )


async def _maybe_await(value: Any) -> Any:
    """``host.idx`` is the in-process IndexCore or the sharded
    RemoteIndex; ``export_entries`` is sync on one, async on the other."""
    if hasattr(value, "__await__"):
        return await value
    return value


class AutoscaleEngine:
    """Controller-side executor for the scale policy (see module doc).

    ``host`` is the Controller actor instance — the engine reaches the
    fleet only through its surface (``volume_refs``, ``idx``, the
    ``_draining`` set and health/epoch helpers), never through raw
    index structures."""

    def __init__(self, host: Any, policy: Optional[AutoscalePolicy] = None):
        self.host = host
        self.policy = policy or policy_from_env()
        self.history: deque[ActionRecord] = deque(maxlen=_HISTORY)
        self._rounds = 0
        self._idle_rounds = 0

    # ---- SNAPSHOT --------------------------------------------------------

    async def snapshot(
        self,
        traffic: Optional[Mapping[str, Any]] = None,
        overload: Optional[Mapping[str, Any]] = None,
    ) -> tuple[TelemetrySnapshot, dict[str, int]]:
        """Freeze the fleet load view the scale solver reads: a
        stats-only fan-out (no key placement / cold-key / relay legs —
        the scale solver never reads them), folded through the same
        ``build_snapshot`` normalizer as the control engine. Also
        returns the per-volume disk-spilled key counts (the blob
        demotion backlog the TelemetrySnapshot doesn't carry)."""
        import asyncio

        host = self.host
        quarantined = host.quarantined_ids()
        live = {
            vid: ref
            for vid, ref in host.volume_refs.items()
            if vid not in quarantined
        }

        async def one_stats(vid: str, ref: Any):
            try:
                return vid, await asyncio.wait_for(
                    ref.stats.call_one(), timeout=10.0
                )
            except Exception as exc:  # noqa: BLE001 - a dark volume is the
                # supervisor's problem; the solver plans around it
                logger.debug(
                    "autoscale snapshot: stats(%s) failed: %s", vid, exc
                )
                return vid, None

        results = await asyncio.gather(
            *(one_stats(vid, ref) for vid, ref in live.items())
        )
        volume_stats = {vid: st for vid, st in results if st is not None}
        spilled = {
            vid: int((st.get("tier") or {}).get("spilled_keys", 0) or 0)
            for vid, st in volume_stats.items()
        }
        snap = build_snapshot(
            traffic=traffic,
            overload=overload,
            volume_stats=volume_stats,
            # Only volumes that ANSWERED: build_snapshot backfills
            # placement-only vids as zero-load rows, and a zero-load
            # phantom (dark or quarantined) would look like the ideal
            # drain victim and re-enter the draining set forever.
            placement={
                vid: hostname
                for vid, hostname in host.volume_hostnames.items()
                if vid in volume_stats
            },
            n_shards=len(host._shard_refs) or 1,
            generated_ts=time.monotonic(),
        )
        self.publish_fleet_gauges()
        return snap, spilled

    def publish_fleet_gauges(self) -> None:
        """Refresh the fleet-size gauges (the PR 17 history sampler
        retains them, feeding the ts_top fleet pane) — called on every
        snapshot and on every attach/drain/retire transition."""
        _FLEET_VOLUMES.set(len(self.host.volume_refs))
        _FLEET_DRAINING.set(len(self.host._draining))

    def _fleet_view(self, spilled: Mapping[str, int]) -> FleetView:
        return FleetView(
            draining=frozenset(self.host._draining),
            min_volumes=self.policy.min_volumes,
            max_volumes=self.policy.max_volumes,
            idle_rounds=self._idle_rounds,
            blob_enabled=blob_mod.enabled(),
            spilled_keys=dict(spilled),
        )

    # ---- SOLVE -----------------------------------------------------------

    async def plan(
        self,
        traffic: Optional[Mapping[str, Any]] = None,
        overload: Optional[Mapping[str, Any]] = None,
    ) -> dict[str, Any]:
        """Dry run: what the engine WOULD do, touching nothing and
        recording nothing (``ts.autoscale_plan()``). The idle-round
        hysteresis counter is not advanced — planning is side-effect
        free."""
        snap, spilled = await self.snapshot(traffic=traffic, overload=overload)
        fleet = self._fleet_view(spilled)
        actions = solve(snap, fleet, self.policy, self.history)
        return {
            "actions": [a.describe() for a in actions],
            "snapshot": snap.describe(),
            "fleet": {
                "volumes": len(self.host.volume_refs),
                "draining": sorted(fleet.draining),
                "idle_rounds": fleet.idle_rounds,
                "blob_enabled": fleet.blob_enabled,
                "spilled_keys": dict(spilled),
            },
            "history": len(self.history),
        }

    # ---- ACT -------------------------------------------------------------

    async def reconcile(
        self,
        traffic: Optional[Mapping[str, Any]] = None,
        overload: Optional[Mapping[str, Any]] = None,
        trigger: str = "interval",
    ) -> dict[str, Any]:
        """One full round: snapshot, advance the idle hysteresis
        counter, solve, apply. Returns the per-action outcomes (also
        recorded as ``decision`` events)."""
        _ROUNDS.inc(trigger=trigger)
        self._rounds += 1
        snap, spilled = await self.snapshot(traffic=traffic, overload=overload)
        live = {
            vid: v
            for vid, v in snap.volumes.items()
            if vid not in self.host._draining
        }
        if live and _fleet_idle(snap, live, self.policy):
            self._idle_rounds += 1
        else:
            self._idle_rounds = 0
        fleet = self._fleet_view(spilled)
        actions = solve(snap, fleet, self.policy, self.history)
        _LAST_ACTIONS.set(len(actions))
        outcomes = []
        for action in actions:
            outcome = await self._apply(snap, action)
            outcomes.append({**action.describe(), "outcome": outcome})
            # Failed actions enter history too: a drain that errored must
            # cool down, not retry every round.
            self.history.append(
                ActionRecord(
                    ts=snap.generated_ts,
                    kind=action.kind,
                    subject=action.subject,
                    src_volume=action.volume,
                )
            )
        return {
            "round": self._rounds,
            "trigger": trigger,
            "actions": outcomes,
            "snapshot": snap.describe(),
            "fleet": {
                "volumes": len(self.host.volume_refs),
                "draining": sorted(self.host._draining),
                "idle_rounds": self._idle_rounds,
            },
        }

    async def _apply(
        self, snap: TelemetrySnapshot, action: AutoscaleAction
    ) -> str:
        import asyncio

        try:
            if action.kind == SCALE_OUT:
                return self._apply_scale_out(snap, action)
            if action.kind == DRAIN:
                return await self._apply_drain(snap, action)
            if action.kind == RETIRE:
                return await self._apply_retire(snap, action)
            if action.kind == BLOB_DEMOTE:
                return await self._apply_blob_demote(snap, action)
            return self._decision(snap, action, "skipped: unknown kind")
        except asyncio.CancelledError:
            raise
        except Exception as exc:  # noqa: BLE001 - one action's failure
            # must not abort the round; the outcome says it failed
            logger.warning(
                "autoscale action %s/%s failed: %s",
                action.kind,
                action.subject,
                exc,
            )
            return self._decision(
                snap, action, f"error: {type(exc).__name__}: {exc}"
            )

    def _apply_scale_out(
        self, snap: TelemetrySnapshot, action: AutoscaleAction
    ) -> str:
        """The engine cannot spawn volume actors (the owner process
        does); a scale-out decision is surfaced — loudly — for
        ``ts.autoscale()`` to execute via the spawn + ``attach_volume``
        path. The decision event IS the actuation here, mirroring the
        control engine's reshard deferral."""
        return self._decision(
            snap, action, "deferred: run ts.autoscale() to spawn %d" % action.count
        )

    def _migration_target(
        self, snap: TelemetrySnapshot, src: str
    ) -> Optional[str]:
        """Least-loaded live volume to receive a draining volume's keys
        (excluding draining and quarantined peers)."""
        host = self.host
        quarantined = host.quarantined_ids()
        candidates = [
            v
            for vid, v in snap.volumes.items()
            if vid != src
            and vid not in host._draining
            and vid not in quarantined
            and vid in host.volume_refs
        ]
        if not candidates:
            return None
        best = min(
            candidates,
            key=lambda v: (v.window_bytes, v.stored_bytes, v.volume_id),
        )
        return best.volume_id

    async def _apply_drain(
        self, snap: TelemetrySnapshot, action: AutoscaleAction
    ) -> str:
        """Graceful drain, one batch per round: mark the volume draining
        (clients exclude it from NEW placements while reads keep
        serving), then migrate up to ``action.count`` resident keys onto
        live volumes through ``idx.migrate_key`` — the same online-move
        actuator (pull_from + write-generation race check) as control
        migrations and auto-repair."""
        await faults.afire("autoscale.drain")
        host = self.host
        vid = action.volume
        if vid not in host.volume_refs:
            host.clear_draining(vid)
            return self._decision(snap, action, "abandoned: volume gone")
        newly = host.mark_draining(vid)
        dst = self._migration_target(snap, vid)
        if dst is None:
            return self._decision(
                snap, action, "abandoned: no migration target", marked=newly
            )
        entries = await _maybe_await(host.idx.export_entries())
        resident = sorted({meta.key for evid, meta, _gen in entries if evid == vid})
        moved = abandoned = 0
        nbytes = 0
        for key in resident[: max(1, action.count)]:
            result = await host.idx.migrate_key(key, vid, dst, drop_src=True)
            status = result.get("status", "error")
            if status == "ok":
                moved += 1
                nbytes += int(result.get("nbytes", 0) or 0)
            elif status == "present":
                # Another replica already lives on dst; dropping the
                # draining copy is still required — detach happens when
                # migrate_key sees it, so count it as progress.
                moved += 1
            else:
                abandoned += 1
        return self._decision(
            snap,
            action,
            "applied",
            marked=newly,
            dst_volume=dst,
            moved=moved,
            abandoned=abandoned,
            nbytes=nbytes,
            remaining=max(0, len(resident) - moved),
        )

    async def _apply_retire(
        self, snap: TelemetrySnapshot, action: AutoscaleAction
    ) -> str:
        """Terminal drain state: verify the index really holds nothing on
        the volume (the stats-derived snapshot may lag), then detach it
        from the index and every fleet map. The volume actor itself is
        stopped by the owner process (``ts.autoscale()``) — the engine
        only removes it from service."""
        await faults.afire("autoscale.drain")
        host = self.host
        vid = action.volume
        entries = await _maybe_await(host.idx.export_entries())
        remaining = sorted({meta.key for evid, meta, _gen in entries if evid == vid})
        if remaining:
            return self._decision(
                snap,
                action,
                "abandoned: %d entries remain" % len(remaining),
            )
        report = await host.idx.detach_volume(vid)
        await host.drop_volume(vid)
        return self._decision(
            snap,
            action,
            "applied",
            lost=len(report.get("lost", ())),
            volumes=len(host.volume_refs),
        )

    async def _apply_blob_demote(
        self, snap: TelemetrySnapshot, action: AutoscaleAction
    ) -> str:
        """Push up to ``action.count`` of the volume's disk-spilled keys
        one rung down into the blob tier (the volume picks the coldest
        version groups by its LRU clock; index tier state is unchanged —
        the keys stay TIERED, only the backing store moves)."""
        ref = self.host.volume_refs.get(action.volume)
        if ref is None:
            return self._decision(snap, action, "abandoned: volume gone")
        rep = await ref.blob_sweep.call_one(max(1, action.count))
        if not rep.get("enabled"):
            return self._decision(snap, action, "abandoned: blob tier disabled")
        return self._decision(
            snap,
            action,
            "applied",
            archived=len(rep.get("archived", ())),
            nbytes=int(rep.get("nbytes", 0) or 0),
        )

    # ---- scale-to-zero ---------------------------------------------------

    async def checkpoint(self) -> dict[str, Any]:
        """Archive every live volume's committed payloads into the blob
        tier and write the durable fleet manifest — the prerequisite for
        scale-to-zero (``ts.blob_checkpoint()``). Returns the manifest
        summary; the archive itself is audited as a ``blob_checkpoint``
        decision."""
        import asyncio

        host = self.host
        action = AutoscaleAction(
            kind=BLOB_CHECKPOINT,
            subject="fleet",
            reason="archive committed payloads for scale-to-zero",
        )
        snap, _spilled = await self.snapshot()
        if not blob_mod.enabled():
            outcome = self._decision(
                snap, action, "abandoned: blob tier disabled"
            )
            return {"outcome": outcome, "keys": 0}
        quarantined = host.quarantined_ids()
        live = {
            vid: ref
            for vid, ref in host.volume_refs.items()
            if vid not in quarantined
        }

        # The actuator fan-out stays in THIS scope (not a closure) so the
        # control-discipline rule sees it beside its _decision audit.
        vids = list(live)
        results = await asyncio.gather(
            *(live[vid].blob_archive.call_one() for vid in vids),
            return_exceptions=True,
        )
        merged: dict[str, dict[str, Any]] = {}
        errors = 0
        for vid, rep in zip(vids, results):
            if isinstance(rep, BaseException):
                if isinstance(rep, asyncio.CancelledError):
                    raise rep
                # A failed archive shows up as missing keys in the manifest
                # count; the decision outcome carries the error tally.
                logger.warning("blob_archive(%s) failed: %s", vid, rep)
                errors += 1
                continue
            for key, entry in (rep.get("objects") or {}).items():
                known = merged.get(key)
                if known is None or entry.get("write_gen", 0) >= known.get(
                    "write_gen", 0
                ):
                    merged[key] = dict(entry)
        store = blob_mod.BlobStore()
        blob_mod.write_fleet_manifest(
            store, merged, extra={"volumes": sorted(live)}
        )
        outcome = self._decision(
            snap,
            action,
            "applied" if not errors else "applied: %d volume(s) errored" % errors,
            keys=len(merged),
            volumes=len(live),
        )
        return {
            "outcome": outcome,
            "keys": len(merged),
            "volumes": len(live),
            "errors": errors,
        }

    # ---- audit -----------------------------------------------------------

    def _decision(
        self,
        snap: TelemetrySnapshot,
        action: AutoscaleAction,
        outcome: str,
        **extra: Any,
    ) -> str:
        """The ONE decision-audit chokepoint: inputs (the snapshot
        summary the solver saw), the chosen action, and what happened."""
        _DECISIONS.inc(kind=action.kind, outcome=outcome.split(":")[0])
        obs_recorder.record(
            "decision",
            f"autoscale/{action.kind}",
            subject=action.subject,
            reason=action.reason,
            outcome=outcome,
            action=action.describe(),
            inputs=snap.describe(),
            **extra,
        )
        return outcome
