"""Elastic fleet autoscaling: capacity follows load.

The subsystem that closes ROADMAP item 4 — the fleet size was fixed at
``initialize()`` while traffic is diurnal. It mirrors the control plane's
layering exactly:

- ``autoscale/solver.py`` — a pure function over the control plane's
  frozen :class:`~torchstore_tpu.control.snapshot.TelemetrySnapshot`
  (extended with the engine-side fleet view: draining set, fleet
  bounds). Scale OUT on sustained landing-inflight saturation / SLO
  overload trends, scale IN on sustained fleet-wide idle, with the same
  hysteresis/cooldown discipline as ``control/solver.py``.
- ``autoscale/engine.py`` — the controller-side executor
  (:class:`AutoscaleEngine`): periodic loop behind
  ``TORCHSTORE_TPU_AUTOSCALE_INTERVAL_S``, manual ``ts.autoscale()``
  trigger, ``ts.autoscale_plan()`` dry run. Every action — spawn
  deferral, drain, retire, blob demotion, checkpoint — flows through
  its ``_decision()`` audit chokepoint (tslint ``control-discipline``
  enforces this for every actuator call site in this package).

Spawn itself happens CLIENT-side (``ts.autoscale()`` in the process
that initialized the store, which owns actor spawning — the same split
as ``ts.rebalance(shards=N)``); the engine surfaces scale-out as a
``deferred`` decision and adopts the new volume via the controller's
``attach_volume`` endpoint.
"""

from torchstore_tpu.autoscale.engine import AutoscaleEngine, policy_from_env
from torchstore_tpu.autoscale.solver import (
    AutoscaleAction,
    AutoscalePolicy,
    solve,
)

__all__ = [
    "AutoscaleAction",
    "AutoscaleEngine",
    "AutoscalePolicy",
    "policy_from_env",
    "solve",
]
