"""The autoscale policy solver: a pure function over a frozen snapshot.

``solve(snapshot, fleet, policy, history)`` returns the typed actions
the engine (autoscale/engine.py) should apply. No clock, no I/O, no
fleet access — the same inputs always produce the same plan, so every
scaling behavior (saturation → scale out, sustained idle → drain, drain
→ retire, anti-flap damping) is unit-testable over hand-built
snapshots, exactly like ``control/solver.py``.

Decision families, in priority order:

1. ``retire_volume`` — a draining volume the index shows EMPTY: drop it
   from the fleet (the terminal drain state).
2. ``drain_volume`` (continuation) — a draining volume still holding
   entries: migrate the next batch of resident keys onto live volumes.
3. ``scale_out`` — sustained ``ts_landing_inflight`` saturation (the
   PR 17 trend detectors' ``sustained_overload`` fold), point-in-time
   landing-bracket saturation past ``out_inflight``, or fleet-mean
   window bytes past ``out_window_bytes``, with room under
   ``max_volumes``: add one volume (the engine defers the spawn to
   ``ts.autoscale()`` — the owner process holds the spawner).
4. ``drain_volume`` (entry) — the WHOLE fleet idle (every volume under
   ``idle_window_bytes`` with an empty landing bracket, no sustained
   overload) for ``idle_rounds`` consecutive engine rounds, with room
   above ``min_volumes``: gracefully drain the emptiest volume.
5. ``blob_demote`` — blob tier enabled, fleet not overloaded, and a
   volume holds disk-spilled keys: push the cold tail one rung further
   down (disk → blob) so an eventual scale-to-zero has everything
   durable.

Hysteresis / damping (the flap tests pin these):

- One scale direction per round, and never a new drain while another
  volume is still draining.
- Cooldown: ``scale_out`` cools fleet-wide, ``drain_volume`` /
  ``blob_demote`` per volume — within ``cooldown_s`` of the snapshot a
  subject is never re-acted.
- Reversal damping: a recent drain/retire suppresses scale-out and a
  recent scale-out suppresses drain entry, regardless of the signals —
  diurnal edges must not saw-tooth the fleet.
- Budget: at most ``max_actions`` actions per round, priority order.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Iterable, Mapping

from torchstore_tpu.control.snapshot import TelemetrySnapshot
from torchstore_tpu.control.solver import ActionRecord

# Action kinds, in priority order (solve() emits them in this order and
# truncates at policy.max_actions).
RETIRE = "retire_volume"
DRAIN = "drain_volume"
SCALE_OUT = "scale_out"
BLOB_DEMOTE = "blob_demote"

KINDS = (RETIRE, DRAIN, SCALE_OUT, BLOB_DEMOTE)


@dataclass(frozen=True)
class AutoscaleAction:
    """One decided scale action. ``subject`` is the hysteresis identity
    (``"fleet"`` for scale-out, the volume id otherwise)."""

    kind: str
    subject: str
    reason: str
    volume: str = ""
    count: int = 0
    detail: dict = field(default_factory=dict)

    def describe(self) -> dict[str, Any]:
        out = {
            "kind": self.kind,
            "subject": self.subject,
            "reason": self.reason,
        }
        if self.volume:
            out["volume"] = self.volume
        if self.count:
            out["count"] = self.count
        if self.detail:
            out["detail"] = dict(self.detail)
        return out


@dataclass(frozen=True)
class FleetView:
    """The engine-side fleet state the TelemetrySnapshot doesn't carry:
    what is mid-drain, the configured size envelope, how long the fleet
    has been idle (the engine's consecutive-idle-round counter — the
    cheap "sustained" fold for a signal with no per-process history
    ring), and the blob tier's per-volume spilled backlog."""

    draining: frozenset[str] = frozenset()
    min_volumes: int = 1
    max_volumes: int = 8
    idle_rounds: int = 0
    blob_enabled: bool = False
    spilled_keys: Mapping[str, int] = field(default_factory=dict)


@dataclass(frozen=True)
class AutoscalePolicy:
    """Solver thresholds. Defaults are deliberately conservative: a
    healthy steady fleet must solve to an empty plan."""

    min_volumes: int = 1
    max_volumes: int = 8
    # Scale-out triggers: any volume's open landing brackets at/past
    # this depth, or fleet-mean rolling-window bytes past this size.
    out_inflight: int = 8
    out_window_bytes: int = 32 << 20
    # Scale-in entry: EVERY live volume under this window with an empty
    # landing bracket, for this many consecutive engine rounds.
    idle_window_bytes: int = 1 << 16
    idle_rounds: int = 3
    # Work quanta per applied action.
    drain_keys_per_round: int = 64
    blob_keys_per_round: int = 32
    # Damping.
    cooldown_s: float = 60.0
    max_actions: int = 4


def _recent(
    history: Iterable[ActionRecord], now: float, cooldown_s: float
) -> list[ActionRecord]:
    return [r for r in history if now - r.ts < cooldown_s]


def _cooled(recent: list[ActionRecord], kind: str, subject: str) -> bool:
    return any(r.kind == kind and r.subject == subject for r in recent)


def _fleet_idle(
    snapshot: TelemetrySnapshot, live: dict, policy: AutoscalePolicy
) -> bool:
    if snapshot.sustained_overload:
        return False
    return all(
        v.window_bytes <= policy.idle_window_bytes
        and v.landing_inflight == 0
        for v in live.values()
    )


def solve(
    snapshot: TelemetrySnapshot,
    fleet: FleetView,
    policy: AutoscalePolicy,
    history: Iterable[ActionRecord] = (),
) -> list[AutoscaleAction]:
    """The pure scale plan (see module doc for the decision families)."""
    now = snapshot.generated_ts
    history = list(history)
    recent = _recent(history, now, policy.cooldown_s)
    live = {
        vid: v
        for vid, v in snapshot.volumes.items()
        if vid not in fleet.draining
    }
    actions: list[AutoscaleAction] = []

    # 1/2. Draining volumes first: retire the empty ones, keep migrating
    # the rest. Continuation is not cooldown-gated — a started drain must
    # converge, not stall a cooldown window per batch.
    for vid in sorted(fleet.draining):
        v = snapshot.volumes.get(vid)
        if v is not None and v.entries == 0:
            actions.append(
                AutoscaleAction(
                    kind=RETIRE,
                    subject=vid,
                    volume=vid,
                    reason="drained volume holds no index entries",
                )
            )
        else:
            actions.append(
                AutoscaleAction(
                    kind=DRAIN,
                    subject=vid,
                    volume=vid,
                    count=policy.drain_keys_per_round,
                    reason=(
                        "drain in progress: %d entries remain"
                        % (v.entries if v is not None else -1)
                    ),
                )
            )

    # 3. Scale out on saturation/overload.
    saturated = sorted(
        vid
        for vid, v in live.items()
        if v.landing_inflight >= policy.out_inflight
    )
    mean_window = (
        sum(v.window_bytes for v in live.values()) / len(live)
        if live
        else 0.0
    )
    sustained = sorted(snapshot.sustained_overload)
    want_out = bool(sustained or saturated) or (
        mean_window >= policy.out_window_bytes
    )
    recently_in = any(r.kind in (DRAIN, RETIRE) for r in recent)
    if (
        want_out
        and not fleet.draining
        and not recently_in  # reversal damping: no saw-tooth
        and len(live) < fleet.max_volumes
        and not _cooled(recent, SCALE_OUT, "fleet")
    ):
        if sustained:
            reason = "sustained overload trend on %s" % ", ".join(sustained)
        elif saturated:
            reason = "landing brackets saturated on %s" % ", ".join(saturated)
        else:
            reason = "fleet-mean window %d B >= %d B" % (
                int(mean_window),
                policy.out_window_bytes,
            )
        actions.append(
            AutoscaleAction(
                kind=SCALE_OUT,
                subject="fleet",
                count=1,
                reason=reason,
                detail={"volumes": len(live)},
            )
        )

    # 4. Scale in on sustained idle (never in the same round as an out).
    recently_out = any(r.kind == SCALE_OUT for r in recent)
    if (
        not want_out
        and not fleet.draining
        and not recently_out  # reversal damping, other direction
        and fleet.idle_rounds >= policy.idle_rounds
        and len(live) > fleet.min_volumes
        and _fleet_idle(snapshot, live, policy)
        and live
    ):
        victim = min(
            live.values(), key=lambda v: (v.stored_bytes, v.volume_id)
        )
        if not _cooled(recent, DRAIN, victim.volume_id):
            actions.append(
                AutoscaleAction(
                    kind=DRAIN,
                    subject=victim.volume_id,
                    volume=victim.volume_id,
                    count=policy.drain_keys_per_round,
                    reason=(
                        "fleet idle %d round(s); %d live > min %d"
                        % (fleet.idle_rounds, len(live), fleet.min_volumes)
                    ),
                )
            )

    # 5. Blob demotion: push the disk-spilled cold tail down a rung.
    if fleet.blob_enabled and not want_out:
        for vid in sorted(fleet.spilled_keys):
            if not fleet.spilled_keys[vid] or vid not in live:
                continue
            if _cooled(recent, BLOB_DEMOTE, vid):
                continue
            actions.append(
                AutoscaleAction(
                    kind=BLOB_DEMOTE,
                    subject=vid,
                    volume=vid,
                    count=policy.blob_keys_per_round,
                    reason=(
                        "%d spilled key(s) eligible for the blob tier"
                        % fleet.spilled_keys[vid]
                    ),
                )
            )

    return actions[: policy.max_actions]
