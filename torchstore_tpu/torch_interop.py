"""Transparent torch.Tensor interop.

Reference users hold ``torch.Tensor`` state dicts (every API in
/root/reference/torchstore takes/returns them). This build's data plane is
numpy/jax, but a migrating user should not have to hand-convert: any CPU
torch tensor is accepted wherever an array is (put/put_batch/put_state_dict
leaves, get ``like=`` targets, ``user_state_dict`` leaves, direct-sync
sources/destinations) and conversion is ZERO-COPY — the numpy view shares
the tensor's memory, so in-place gets land bytes directly in the caller's
torch storage and the original tensor objects are returned.

torch is never imported by this module: if the user has not imported torch,
no value can be a torch tensor and every check short-circuits via
``sys.modules``. bfloat16 (no numpy native dtype) round-trips through a
uint16 view reinterpreted as ``ml_dtypes.bfloat16``.
"""

from __future__ import annotations

import sys
from typing import Any

import numpy as np


def is_torch_tensor(value: Any) -> bool:
    torch = sys.modules.get("torch")
    return torch is not None and isinstance(value, torch.Tensor)


def to_numpy_view(tensor: Any, allow_copy: bool = True) -> np.ndarray:
    """Zero-copy numpy view of a CPU torch tensor (shares memory; writes to
    the view are visible through the tensor). Raises for non-CPU tensors —
    this image's torch is CPU-only, and device arrays belong on the jax
    path. Non-contiguous tensors stay zero-copy (strided view); autograd
    leaves are detached (the store moves bytes, not graphs).

    ``allow_copy=False`` (in-place get targets): raises instead of falling
    back to a copy in the one case a copy is unavoidable (non-contiguous
    bfloat16, whose uint16 reinterpretation needs a contiguous layout) —
    a silent copy there would fill the copy, not the caller's tensor."""
    import torch

    if tensor.device.type != "cpu":
        raise TypeError(
            f"torch tensor on device {tensor.device} is not supported; "
            "move it to CPU (.cpu()) or use a jax.Array for device-resident "
            "values"
        )
    t = tensor.detach()
    if t.dtype == torch.bfloat16:
        import ml_dtypes

        if not t.is_contiguous():
            if not allow_copy:
                raise TypeError(
                    "non-contiguous bfloat16 torch tensors cannot be viewed "
                    "zero-copy; pass a .contiguous() tensor as the in-place "
                    "target"
                )
            t = t.contiguous()
        return t.view(torch.uint16).numpy().view(ml_dtypes.bfloat16)
    return t.numpy()


def astype_numpy(tensor: Any, dtype: Any) -> np.ndarray:
    """Cast a torch tensor to a numpy array of ``dtype`` (always copies —
    used by transfer_dtype casting where a copy is inherent)."""
    return to_numpy_view(tensor).astype(dtype)


def _shard_cls():
    # Lazy: client.py imports this module at load time; the reverse import
    # must happen at call time.
    from torchstore_tpu.client import Shard

    return Shard


def convert_tree(value: Any, allow_copy: bool = True) -> Any:
    """Recursively replace torch-tensor leaves (bare or inside ``Shard``)
    with zero-copy numpy views (dict/list/tuple/NamedTuple containers
    preserved; everything else untouched). Returns the input object itself
    when no torch leaf exists, so non-torch callers pay one isinstance walk
    and zero allocation. ``allow_copy=False`` for in-place get targets: a
    leaf whose view would require a copy (non-contiguous bf16) raises
    instead of silently filling the copy."""
    if not has_torch_leaves(value):
        return value
    return _convert_rec(value, allow_copy)


def _convert_rec(value: Any, allow_copy: bool) -> Any:
    if is_torch_tensor(value):
        return to_numpy_view(value, allow_copy)
    Shard = _shard_cls()
    if isinstance(value, Shard) and is_torch_tensor(value.data):
        return Shard(
            data=to_numpy_view(value.data, allow_copy),
            tensor_slice=value.tensor_slice,
        )
    if isinstance(value, dict):
        return {k: _convert_rec(v, allow_copy) for k, v in value.items()}
    if isinstance(value, tuple) and hasattr(value, "_fields"):
        return type(value)(*(_convert_rec(v, allow_copy) for v in value))
    if isinstance(value, (list, tuple)):
        converted = [_convert_rec(v, allow_copy) for v in value]
        return converted if isinstance(value, list) else tuple(converted)
    return value


def restore_torch_results(original: Any, converted: Any, result: Any) -> Any:
    """After a pull into ``converted`` (the numpy-view image of ``original``
    produced by :func:`convert_tree`): make every torch leaf of ``original``
    hold the pulled bytes and return ``original``'s structure with the torch
    tensors back in leaf position. A pull that landed in the shared view
    needs nothing; one that produced a fresh array (non-contiguous target,
    assembled region) is copied into the view — which IS the tensor's
    storage. ``result`` must be structure-congruent with ``converted`` (it
    is: both come from the same flatten mapping)."""
    if is_torch_tensor(original):
        if result is not converted:
            np.copyto(converted, result)
        return original
    Shard = _shard_cls()
    if isinstance(original, Shard) and is_torch_tensor(original.data):
        res_data = result.data if isinstance(result, Shard) else result
        if res_data is not converted.data and isinstance(res_data, np.ndarray):
            np.copyto(converted.data, res_data)
        return original
    if isinstance(original, dict):
        return {
            k: restore_torch_results(original[k], converted[k], result[k])
            for k in original
        }
    if isinstance(original, (list, tuple)):
        out = [
            restore_torch_results(o, c, r)
            for o, c, r in zip(original, converted, result)
        ]
        if isinstance(original, tuple):
            if hasattr(original, "_fields"):
                return type(original)(*out)
            return tuple(out)
        return out
    return result


def has_torch_leaves(value: Any) -> bool:
    if sys.modules.get("torch") is None:
        return False
    return _has_torch_rec(value)


def _has_torch_rec(value: Any) -> bool:
    if is_torch_tensor(value):
        return True
    if isinstance(value, _shard_cls()):
        return is_torch_tensor(value.data)
    if isinstance(value, dict):
        return any(_has_torch_rec(v) for v in value.values())
    if isinstance(value, (list, tuple)):
        return any(_has_torch_rec(v) for v in value)
    return False
