"""Direct (one-hop) weight sync: dest pulls straight from the source's
registered buffers — the store carries only metadata handles.

TPU re-architecture of /root/reference/torchstore/direct_weight_sync.py
(:46-350). The reference rides ibverbs one-sided RDMA reads of source GPU
memory; TPUs expose no such primitive (SURVEY §7.3), so the same API —
register -> publish handles -> cached transfer plan -> concurrent pull ->
refresh — is kept, with the data path re-based on a source-side **peer
buffer engine**:

- same host: staging buffers live in /dev/shm segments; the dest attaches
  and copies directly (true one-hop, zero intermediary).
- cross host: the source process runs a tiny read server; dests issue
  ranged reads over cached TCP connections (DCN path).

Handles published under ``{key}/rank_{r}`` + ``{key}/num_ranks`` exactly like
the reference (state_dict_utils.py:217-275), so discovery flows through the
normal store.
"""

from __future__ import annotations

import asyncio
import struct
from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

from torchstore_tpu import sharding as shd
from torchstore_tpu.logging import LatencyTracker, get_logger
from torchstore_tpu.native import copy_into
from torchstore_tpu.observability import metrics as obs_metrics
from torchstore_tpu.state_dict_utils import flatten_state_dict
from torchstore_tpu.transport import shared_memory as shm
from torchstore_tpu.transport.types import TensorMeta, TensorSlice
from torchstore_tpu.utils import (
    Box,
    boxes_cover,
    get_destination_view,
    get_hostname,
    intersect_boxes,
)

logger = get_logger("torchstore_tpu.direct")

# Cold-start observability: a first pull that reuses a plan built by
# ``ts.prewarm`` (DirectWeightSyncDest.preplan) counts here — the signal
# that iteration 0 skipped plan construction.
_PLAN_PREWARM_HITS = obs_metrics.counter(
    "ts_prewarm_plan_cache_hits_total",
    "Direct-sync pulls that hit a prewarm-built transfer plan",
)


class PullRaceError(RuntimeError):
    """A direct pull lost its race with concurrent source activity (seqlock
    generation never settled, or tore on both attempts). Transient by
    nature — the state-dict layer retries once with fresh handles."""

_READ_REQ = struct.Struct("<QQQ")  # buffer_id, offset, length
_READ_RESP = struct.Struct("<Q")  # length (0xFFFF.. = error)
_ERR = (1 << 64) - 1
# buffer_id sentinel: "stage the registered device arrays for one pull and
# reply with the transfer uuid" (the ICI rung's control op — each staging
# serves exactly one jax.experimental.transfer pull).
_STAGE_DEVICE = (1 << 64) - 2
# buffer_id sentinel: "materialize the current device arrays into host
# buffers and reply with pickled WeightHandles" — the graceful-degradation
# rung for dests that cannot reconstruct our device shardings (disjoint jax
# worlds / non-coinciding device ids).
_STAGE_HOST = (1 << 64) - 3
# buffer_id sentinel: "reply with the source's current weight generation"
# (seqlock: ODD while a refresh is overwriting the staging buffers, even at
# rest; bumped +2 per publish). Dests read it before and after a host-path
# pull and retry once on change — tear detection for pulls concurrent with
# refreshes (VERDICT r2 item 4).
_GET_GEN = (1 << 64) - 4
_U64 = struct.Struct("<Q")
_2U64 = struct.Struct("<QQ")


# --------------------------------------------------------------------------
# handles
# --------------------------------------------------------------------------


@dataclass
class WeightHandle:
    """Picklable pointer to one registered source shard (the reference's
    RDMAWeightHandle, direct_weight_sync.py:46-58)."""

    buffer_id: int
    hostname: str
    port: int
    shm_name: Optional[str]
    meta: TensorMeta
    tensor_slice: TensorSlice
    source_rank: int


@dataclass
class DeviceEntry:
    """One staged device array in a rank's device-mode publication: where it
    sits in the global tensor (``tensor_slice``) plus how to pull it
    (``spec``). The per-rank analog of the reference's per-rank RDMA handle
    list (/root/reference/torchstore/state_dict_utils.py:217-275) with the
    handle re-based on the XLA transfer engine."""

    flat_key: str
    spec: Any  # transport.device_transfer.DeviceSpec
    tensor_slice: TensorSlice


# --------------------------------------------------------------------------
# source side
# --------------------------------------------------------------------------


class _PeerReadServer:
    """Serves ranged reads of registered buffers over TCP (cross-host path)
    and the device-staging control op (ICI rung)."""

    def __init__(self) -> None:
        self.buffers: dict[int, np.ndarray] = {}
        # Set by the source when device mode is on: () -> transfer uuid.
        self.stage_device_fn = None
        # Set alongside: () -> pickled {flat_key: [WeightHandle]} after
        # materializing current device arrays into host buffers (fallback
        # for dests outside this source's jax world).
        self.stage_host_fn = None
        # () -> current weight generation (seqlock; see _GET_GEN).
        self.gen_fn = lambda: 0
        self._server: Optional[asyncio.AbstractServer] = None
        self.port: Optional[int] = None
        self._writers: set = set()

    async def ensure_started(self) -> int:
        if self._server is None:
            import os

            # Loopback by default; cross-host deployments set
            # TORCHSTORE_TPU_BIND_HOST=0.0.0.0 (+ ADVERTISE_HOST).
            bind = os.environ.get("TORCHSTORE_TPU_BIND_HOST", "127.0.0.1")
            self._server = await asyncio.start_server(self._handle, bind, 0)
            self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def _handle(self, reader, writer) -> None:
        from torchstore_tpu.runtime.auth import server_authenticate

        if not await server_authenticate(reader, writer):
            try:
                writer.close()
            except Exception:
                pass
            return
        self._writers.add(writer)
        try:
            while True:
                req = await reader.readexactly(_READ_REQ.size)
                buffer_id, offset, length = _READ_REQ.unpack(req)
                if buffer_id == _GET_GEN:
                    writer.write(
                        _READ_RESP.pack(_U64.size) + _U64.pack(self.gen_fn())
                    )
                    await writer.drain()
                    continue
                if buffer_id == _STAGE_DEVICE:
                    if self.stage_device_fn is None:
                        writer.write(_READ_RESP.pack(_ERR))
                    else:
                        try:
                            uid = self.stage_device_fn()
                        except Exception:
                            # Stage-time failures (e.g. resharded republish
                            # guard) must reach the dest as a refusal, not
                            # a dropped connection.
                            logger.exception("device staging failed")
                            writer.write(_READ_RESP.pack(_ERR))
                        else:
                            # uid + the generation the staged snapshot was
                            # taken at (cross-rank consistency check).
                            writer.write(
                                _READ_RESP.pack(_2U64.size)
                                + _2U64.pack(uid, self.gen_fn())
                            )
                    await writer.drain()
                    continue
                if buffer_id == _STAGE_HOST:
                    if self.stage_host_fn is None:
                        writer.write(_READ_RESP.pack(_ERR))
                    else:
                        try:
                            # D2H of a whole model: off the event loop, or
                            # it would stall every concurrent read/stage op.
                            payload = await asyncio.get_running_loop().run_in_executor(
                                None, self.stage_host_fn
                            )
                        except Exception:
                            logger.exception("host-fallback staging failed")
                            writer.write(_READ_RESP.pack(_ERR))
                        else:
                            writer.write(_READ_RESP.pack(len(payload)))
                            writer.write(payload)
                    await writer.drain()
                    continue
                arr = self.buffers.get(buffer_id)
                if arr is None:
                    writer.write(_READ_RESP.pack(_ERR))
                    await writer.drain()
                    continue
                flat = arr.reshape(-1).view(np.uint8)
                chunk = flat[offset : offset + length]
                writer.write(_READ_RESP.pack(chunk.nbytes))
                writer.write(memoryview(chunk))
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            self._writers.discard(writer)
            try:
                writer.close()
            except Exception:
                pass

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # Close live client connections first: py3.12's wait_closed()
            # waits for handlers, which would otherwise block forever.
            for writer in list(self._writers):
                try:
                    writer.close()
                except Exception:
                    pass
            try:
                await asyncio.wait_for(self._server.wait_closed(), timeout=2.0)
            except asyncio.TimeoutError:
                pass
            self._server = None


class DirectWeightSyncSource:
    """Registers a state dict's shards into pull-able staging buffers.

    ``register`` stages every shard once (device->host copy + optional dtype
    cast, reference staging-buffer pattern direct_weight_sync.py:99-156);
    ``refresh`` re-copies current values into the SAME buffers so published
    handles stay valid across training steps (direct_weight_sync.py:158-169).
    """

    def __init__(self, use_shm: bool = True, config=None, device: Optional[bool] = None):
        from torchstore_tpu.config import default_config

        self.use_shm = use_shm and shm.is_available()
        self.config = config or default_config()
        # None = auto (device path when eligible); False pins the host path.
        self.device = device
        self.server = _PeerReadServer()
        self.segments: dict[int, shm.ShmSegment] = {}
        self.handles: dict[str, list[WeightHandle]] = {}
        self._sources: dict[str, Any] = {}  # flat_key -> live array/jax ref
        self._transfer_dtype = None
        self._next_id = 0
        self._registered = False
        self._mapping: Optional[dict] = None
        self._flat_template: dict[str, Any] = {}
        # Device (ICI) mode state: ordered flat keys + current jax arrays.
        self.device_info: Optional[dict] = None
        self._device_keys: list[str] = []
        self._device_arrays: dict[str, Any] = {}
        self._device_counts: dict[str, int] = {}
        # entry index -> reusable host-fallback buffer id (_stage_host_handles).
        self._host_fallback_ids: dict[int, int] = {}
        self._advertise: tuple[str, int] = ("", 0)
        # _stage_host_handles runs in the server's executor (off the event
        # loop); concurrent fallback pulls must not race id allocation or
        # buffer refreshes.
        import threading

        self._host_fallback_lock = threading.Lock()
        # Weight generation (seqlock). _gen is the CONTENT generation: even
        # always, +2 per publish (refresh). _busy counts in-flight buffer
        # overwrites (host refresh, fallback staging); gen_fn reports
        # _gen+1 (odd) while any overwrite runs, so dests wait out
        # overwrites and retry when content moved mid-pull. Fallback
        # staging itself never advances _gen — N dests pulling the same
        # content concurrently see one stable generation (no spurious
        # retries / "torn twice"). Mutated from the event loop (refresh)
        # AND the server executor (_stage_host_handles): every access goes
        # through _gen_lock — an unsynchronized `_gen += n` can lose a
        # bump and wedge the parity.
        self._gen = 0
        self._busy = 0
        self._gen_lock = threading.Lock()
        # Host-fallback staging cache: the pickled handle payload + the
        # content generation it materialized. Re-materialization happens
        # only when _gen advanced — concurrent cross-world dests share one
        # D2H staging per publish instead of re-copying the model per pull.
        self._staged_gen: Optional[int] = None
        self._staged_payload: Optional[bytes] = None
        self.server.gen_fn = self._read_gen_locked

    def _read_gen_locked(self) -> int:
        with self._gen_lock:
            return self._gen + 1 if self._busy else self._gen

    def _bump_gen(self, n: int = 2) -> None:
        with self._gen_lock:
            self._gen += n

    def _set_busy(self, on: bool) -> None:
        with self._gen_lock:
            self._busy += 1 if on else -1

    def _device_mode_eligible(self, flat: dict) -> bool:
        """Device path engages when every tensor leaf lives on device: plain
        jax arrays, or rank-local ``Shard`` wrappers whose data is a jax
        array. Rank-independent: each rank of a multi-rank SPMD source
        registers its own per-shard device entries (``register``'s rank
        param — the reference's per-rank handle publication pattern,
        state_dict_utils.py:217-275)."""
        if self.device is False:
            return False
        if not self.config.ici_enabled:
            return False
        from torchstore_tpu.transport import device_transfer as dt

        if not dt.is_available():
            return False
        tensorish = [v for v in flat.values() if _is_tensor_leaf(v)]
        return bool(tensorish) and all(
            shd.is_jax_array(_unwrap_shard(v)) for v in tensorish
        )

    async def register(
        self,
        state_dict: Any,
        rank: int = 0,
        transfer_dtype=None,
        num_ranks: int = 1,
    ) -> dict[str, list[WeightHandle]]:
        import os

        port = await self.server.ensure_started()
        self._transfer_dtype = transfer_dtype
        flat, mapping = flatten_state_dict(state_dict)
        self._mapping = mapping
        # Only NON-tensor leaves are kept (staging_state_dict fills tensor
        # keys from the registered buffers); keeping tensor leaves would pin
        # a full copy of the registration-time weights forever.
        self._flat_template = {
            k: v for k, v in flat.items() if not _is_tensor_leaf(v)
        }
        # Advertise the same reachable name the actor runtime uses.
        hostname = os.environ.get("TORCHSTORE_TPU_ADVERTISE_HOST", get_hostname())
        if self._device_mode_eligible(flat):
            return self._register_device(flat, hostname, port, transfer_dtype, rank)
        for flat_key, value in flat.items():
            if (
                transfer_dtype is not None
                and shd.is_jax_array(value)
                and _is_floating(value)
            ):
                # Cast on device (ops.device_cast: fused XLA / pallas kernel)
                # so the HBM->host copy moves the transfer dtype's bytes.
                from torchstore_tpu.ops import device_cast

                value = device_cast(value, transfer_dtype)
            shards = self._shards_of(value)
            if shards is None:
                continue  # non-tensor leaves don't take the direct path
            self._sources[flat_key] = value
            handle_list: list[WeightHandle] = []
            for ts_slice, host_arr in shards:
                if (
                    transfer_dtype is not None
                    and _is_floating(host_arr)
                    and host_arr.dtype != np.dtype(transfer_dtype)
                ):
                    host_arr = host_arr.astype(transfer_dtype)
                host_arr = np.ascontiguousarray(host_arr)
                buffer_id = self._next_id
                self._next_id += 1
                shm_name = None
                if self.use_shm:
                    # Prewarmed staging: an exact-size pre-faulted segment
                    # from the client-local pool (ts.prewarm direct=True)
                    # skips the cold create+zero on the first publish.
                    from torchstore_tpu.provision.pool import local_pool

                    seg = local_pool().take(max(host_arr.nbytes, 1))
                    if seg is None:
                        seg = shm.ShmSegment.create(max(host_arr.nbytes, 1))
                    # WRITER side: this module publishes the generation
                    # seqlock that brackets these staging writes (readers
                    # validate against it) — not an unstamped read.
                    staged = seg.view(TensorMeta.of(host_arr))  # tslint: disable=one-sided-discipline
                    copy_into(staged, host_arr)
                    self.segments[buffer_id] = seg
                    self.server.buffers[buffer_id] = staged
                    shm_name = seg.name
                else:
                    self.server.buffers[buffer_id] = host_arr.copy()
                handle_list.append(
                    WeightHandle(
                        buffer_id=buffer_id,
                        hostname=hostname,
                        port=port,
                        shm_name=shm_name,
                        meta=TensorMeta.of(host_arr),
                        tensor_slice=ts_slice,
                        source_rank=rank,
                    )
                )
            self.handles[flat_key] = handle_list
        self._registered = True
        return self.handles

    def _register_device(
        self, flat: dict, hostname: str, port: int, transfer_dtype, rank: int
    ) -> dict:
        """ICI rung registration: no host staging at all. Arrays stay on
        device; every dest pull stages the CURRENT arrays through the XLA
        transfer server (device-to-device over ICI/DCN — the reference's
        one-sided GPU read, monarch_rdma.py:158-219, without host bounce).
        Each rank of a multi-rank SPMD source registers independently and
        publishes its own entries under ``key/rank_{r}``; the dest's plan
        merges all ranks' parts."""
        from torchstore_tpu.transport import device_transfer as dt

        engine = dt.DeviceTransferEngine.get()
        self._device_keys = []
        self._device_arrays = {}
        self._device_counts = {}
        entries: list[DeviceEntry] = []
        for flat_key, value in flat.items():
            if not _is_tensor_leaf(value):
                continue
            self._device_keys.append(flat_key)
            self._device_arrays[flat_key] = value  # uncast; cast at stage time
            parts = _device_parts(_cast_device_value(value, transfer_dtype))
            self._device_counts[flat_key] = len(parts)
            for ts_slice, arr in parts:
                entries.append(
                    DeviceEntry(
                        flat_key=flat_key,
                        spec=dt.DeviceSpec.of(arr),
                        tensor_slice=ts_slice,
                    )
                )
        address = engine.ensure_server()
        self.server.stage_device_fn = self._stage_current
        self.server.stage_host_fn = self._stage_host_handles
        self._advertise = (hostname, port)
        self.device_info = {
            "address": address,
            "hostname": hostname,
            "control_port": port,
            "keys": list(self._device_keys),
            "entries": entries,
            "source_rank": rank,
        }
        self._registered = True
        self.handles = {}
        logger.info(
            "direct sync rank %d registered %d tensors (%d device entries) "
            "on the device (ICI) path",
            rank,
            len(self._device_keys),
            len(entries),
        )
        return self.handles

    def _current_device_parts(self) -> list[tuple[str, TensorSlice, Any]]:
        """(flat_key, global slice, device array) for the CURRENT values, in
        registration order — validated one-to-one against the PUBLISHED
        entries (spec AND placement, not just count): a republish that
        reshards a param without re-registering would otherwise stage
        arrays the dest lands at stale offsets — silent corruption."""
        from torchstore_tpu.transport import device_transfer as dt

        out: list[tuple[str, TensorSlice, Any]] = []
        entries = self.device_info["entries"]
        idx = 0
        # Local ref: update_sources swaps the dict atomically; holding one
        # reference keeps this pass consistent even from an executor thread.
        arrays = self._device_arrays
        for key in self._device_keys:
            parts = _device_parts(
                _cast_device_value(arrays[key], self._transfer_dtype)
            )
            if len(parts) != self._device_counts[key]:
                raise ValueError(
                    f"device refresh of {key!r}: value now decomposes into "
                    f"{len(parts)} parts but {self._device_counts[key]} were "
                    "registered — re-register after changing a param's "
                    "sharding"
                )
            for ts_slice, arr in parts:
                reg = entries[idx]
                idx += 1
                if (
                    reg.tensor_slice != ts_slice
                    or reg.spec != dt.DeviceSpec.of(arr)
                ):
                    raise ValueError(
                        f"device refresh of {key!r}: current value's "
                        "sharding/placement differs from the published "
                        "entries — re-register (publish under a fresh key "
                        "or restart the source) after changing a param's "
                        "sharding"
                    )
                out.append((key, ts_slice, arr))
        return out

    def _stage_current(self) -> int:
        from torchstore_tpu.transport import device_transfer as dt

        engine = dt.DeviceTransferEngine.get()
        return engine.stage([arr for _, _, arr in self._current_device_parts()])

    def _stage_host_handles(self) -> bytes:
        """Materialize the current device arrays into host buffers and return
        pickled ``{flat_key: [WeightHandle]}`` — serves dests whose jax world
        does not contain our device ids (they then read over the normal host
        TCP path). Runs in the server's executor; _host_fallback_lock
        serializes concurrent fallback pulls (unlocked, two threads could
        allocate the same buffer id for different tensors — silent weight
        swaps for same-shape params).

        The staging is cached per content generation: concurrent dests at
        the same generation share ONE D2H materialization and observe a
        stable (even) generation throughout — staging never bumps _gen, so
        N generators fanning out over one source cannot trip each other's
        tear detection. Buffers are only overwritten after a publish
        advanced _gen; a dest mid-read then sees the busy (odd) marker or
        the new generation and retries, exactly as for a host-path
        refresh."""
        with self._host_fallback_lock:
            for _ in range(3):
                with self._gen_lock:
                    gen0 = self._gen
                if self._staged_gen == gen0 and self._staged_payload is not None:
                    return self._staged_payload
                self._set_busy(True)
                try:
                    payload = self._materialize_host_handles()
                finally:
                    self._set_busy(False)
                with self._gen_lock:
                    settled = self._gen == gen0
                self._staged_gen = gen0
                self._staged_payload = payload
                if settled:
                    return payload
                # A publish landed mid-materialization: the staged snapshot
                # is a consistent view of SOME step but tagged stale — loop
                # to restage the fresh content (bounded; a publisher hotter
                # than the loop still gets a consistent, slightly stale
                # payload, which the dest-side gen check resolves).
            return payload

    def _materialize_host_handles(self) -> bytes:
        import pickle

        hostname, port = self._advertise
        handles: dict[str, list[WeightHandle]] = {}
        for idx, (flat_key, ts_slice, arr) in enumerate(
            self._current_device_parts()
        ):
            host_arr = np.ascontiguousarray(np.asarray(arr))
            buffer_id = self._host_fallback_ids.get(idx)
            if buffer_id is None:
                buffer_id = self._next_id
                self._next_id += 1
                self._host_fallback_ids[idx] = buffer_id
            # Staging-buffer reuse across generations: land the new bytes in
            # the SAME published buffer when layout is unchanged — its pages
            # are already faulted and any warm reader connection keeps
            # serving one stable address (refresh-in-place, like the host
            # path's registered buffers). Seqlock busy/gen markers already
            # fence readers during the overwrite.
            staged = self.server.buffers.get(buffer_id)
            if (
                staged is not None
                and staged.shape == host_arr.shape
                and staged.dtype == host_arr.dtype
            ):
                copy_into(staged, host_arr)
                host_arr = staged
            else:
                self.server.buffers[buffer_id] = host_arr
            handles.setdefault(flat_key, []).append(
                WeightHandle(
                    buffer_id=buffer_id,
                    hostname=hostname,
                    port=port,
                    shm_name=None,
                    meta=TensorMeta.of(host_arr),
                    tensor_slice=ts_slice,
                    source_rank=self.device_info["source_rank"],
                )
            )
        return pickle.dumps(handles)

    @staticmethod
    def _shards_of(value) -> Optional[list[tuple[TensorSlice, np.ndarray]]]:
        from torchstore_tpu.client import Shard as _Shard

        if isinstance(value, _Shard):
            # Rank-local shard with explicit global placement (SPMD sources):
            # decompose the data, then re-base its slices into the global
            # space the wrapper describes.
            inner = DirectWeightSyncSource._shards_of(value.data)
            if inner is None:
                return None
            return [
                (_rebase_slice(ts_slice, value.tensor_slice), arr)
                for ts_slice, arr in inner
            ]
        if shd.is_jax_array(value):
            reqs = shd.put_requests("_", value)
            out = []
            for req in reqs:
                if req.tensor_slice is not None:
                    out.append((req.tensor_slice, np.asarray(req.tensor_val)))
                else:
                    arr = np.asarray(req.tensor_val)
                    out.append((_full_slice(arr.shape), arr))
            return out
        if isinstance(value, np.ndarray):
            return [(_full_slice(value.shape), value)]
        return None

    async def refresh(self) -> None:
        """Re-stage current param values into the registered buffers.

        Device (ICI) mode needs no work here: staging happens per pull, so
        dests always read the arrays ``update_sources`` last installed."""
        if not self._registered:
            raise RuntimeError("register() must run before refresh()")
        if self.device_info is not None:
            # Device staging snapshots per pull; publish = one stable bump
            # (which also invalidates the host-fallback staging cache).
            self._bump_gen(2)
            return
        self._set_busy(True)  # reported odd while buffers are overwritten
        try:
            await self._refresh_host()
        finally:
            self._bump_gen(2)
            self._set_busy(False)

    async def _refresh_host(self) -> None:
        for flat_key, value in self._sources.items():
            if (
                self._transfer_dtype is not None
                and shd.is_jax_array(value)
                and _is_floating(value)
            ):
                from torchstore_tpu.ops import device_cast

                value = device_cast(value, self._transfer_dtype)
            shards = self._shards_of(value)
            handles = self.handles[flat_key]
            if shards is None or len(shards) != len(handles):
                raise ValueError(
                    f"refresh of {flat_key!r}: value now produces "
                    f"{0 if shards is None else len(shards)} shards but "
                    f"{len(handles)} buffers were registered — re-register "
                    "after changing a param's sharding"
                )
            for (_, host_arr), handle in zip(shards, handles):
                if (
                    self._transfer_dtype is not None
                    and _is_floating(host_arr)
                    and host_arr.dtype != np.dtype(self._transfer_dtype)
                ):
                    host_arr = host_arr.astype(self._transfer_dtype)
                staged = self.server.buffers[handle.buffer_id]
                if _aliases(staged, host_arr):
                    # Registered-buffer sources (staging_state_dict) write
                    # weights straight into the published buffers — the
                    # refresh copy vanishes, matching RDMA's register-once
                    # read-live semantics.
                    continue
                copy_into(staged, np.ascontiguousarray(host_arr))

    def staging_state_dict(self) -> Optional[Any]:
        """The registered staging buffers in the ORIGINAL state-dict
        structure (host path, unsharded sources only). A trainer that
        writes its weights directly into these arrays makes every
        subsequent direct put a pure metadata publish — zero source-side
        copies, the host analog of RDMA registered memory
        (/root/reference/torchstore/direct_weight_sync.py:99-156 registers
        buffers once; here the caller may adopt them as its own weight
        storage). Returns None when any source is sharded/device-resident
        (device sources already sync copy-free via the ICI path)."""
        if (
            not self._registered
            or self.device_info is not None
            or self._mapping is None
        ):
            return None
        from torchstore_tpu.state_dict_utils import unflatten_state_dict

        flat = dict(self._flat_template)  # non-tensor leaves as registered
        for flat_key, handles in self.handles.items():
            if len(handles) != 1 or not handles[0].tensor_slice.is_full():
                return None
            flat[flat_key] = self.server.buffers[handles[0].buffer_id]
        return unflatten_state_dict(flat, self._mapping)

    def update_sources(self, state_dict: Any) -> None:
        """Point refresh() at new param objects (jax arrays are immutable, so
        each train step produces fresh arrays — functional-update analog of
        the reference's in-place staging refresh)."""
        flat, _ = flatten_state_dict(state_dict)
        for key in self._sources:
            self._sources[key] = flat[key]
        if self._device_keys:
            # Atomic whole-dict swap: _stage_host_handles reads this from an
            # executor thread; per-key mutation could hand it a torn
            # old/new mix across keys.
            self._device_arrays = {
                key: flat[key] for key in self._device_keys
            }

    async def close(self) -> None:
        await self.server.stop()
        for seg in self.segments.values():
            seg.unlink()
        self.segments.clear()
        self.server.buffers.clear()


def _full_slice(shape) -> TensorSlice:
    return TensorSlice(
        offsets=(0,) * len(shape),
        local_shape=tuple(shape),
        global_shape=tuple(shape),
        coordinates=(),
        mesh_shape=(),
    )


def _rebase_slice(inner: TensorSlice, base: TensorSlice) -> TensorSlice:
    """``inner`` (a slice of the rank-local data) re-based into the global
    space ``base`` places that data in."""
    return TensorSlice(
        offsets=tuple(o + bo for o, bo in zip(inner.offsets, base.offsets)),
        local_shape=inner.local_shape,
        global_shape=base.global_shape,
        coordinates=inner.coordinates,
        mesh_shape=inner.mesh_shape,
    )


def _unwrap_shard(value):
    from torchstore_tpu.client import Shard as _Shard

    return value.data if isinstance(value, _Shard) else value


def _cast_device_value(value, transfer_dtype):
    """On-device cast of a device-mode leaf (or its Shard data) to the
    transfer dtype; identity when no cast applies."""
    if transfer_dtype is None:
        return value
    from torchstore_tpu.client import Shard as _Shard

    if isinstance(value, _Shard):
        data = _cast_device_value(value.data, transfer_dtype)
        return value if data is value else _Shard(data, value.tensor_slice)
    if shd.is_jax_array(value) and _is_floating(value):
        from torchstore_tpu.ops import device_cast

        return device_cast(value, transfer_dtype)
    return value


def _device_parts(value) -> list[tuple[TensorSlice, Any]]:
    """Decompose one device-mode leaf into (global TensorSlice, device
    array) staging parts:

    - fully-addressable jax array: ONE part, the array itself (whole-array
      staging keeps its mesh sharding — the single-controller fast shape);
    - non-fully-addressable (true multi-controller SPMD): one part per
      addressable shard, each a committed single-device array placed by its
      shard index in the global space;
    - ``Shard`` wrapper: the data's parts re-based into the wrapper's global
      space (mp.spawn-style SPMD where each rank owns a disjoint device
      subset)."""
    from torchstore_tpu.client import Shard as _Shard

    if isinstance(value, _Shard):
        return [
            (_rebase_slice(ts_slice, value.tensor_slice), arr)
            for ts_slice, arr in _device_parts(value.data)
        ]
    if value.is_fully_addressable:
        return [(_full_slice(value.shape), value)]
    global_shape = tuple(int(s) for s in value.shape)
    out = []
    seen: set[tuple[int, ...]] = set()
    for shard in value.addressable_shards:
        offsets = tuple(int(sl.start or 0) for sl in shard.index)
        if offsets in seen:
            continue  # replicated-across-local-devices: stage one copy
        seen.add(offsets)
        out.append(
            (
                TensorSlice(
                    offsets=offsets,
                    local_shape=tuple(int(s) for s in shard.data.shape),
                    global_shape=global_shape,
                    coordinates=(),
                    mesh_shape=(),
                ),
                shard.data,
            )
        )
    return out


def _aliases(a: np.ndarray, b: np.ndarray) -> bool:
    """Same memory AND same interpretation. Layout must match too: a
    transposed/reinterpreted view of the staging buffer is a real publish
    request (the transform must be materialized), not an alias to skip."""
    try:
        return (
            a.__array_interface__["data"][0] == b.__array_interface__["data"][0]
            and a.nbytes == b.nbytes
            and a.shape == b.shape
            and a.dtype == b.dtype
            and a.strides == b.strides
        )
    except (AttributeError, TypeError):
        return False


def _is_floating(arr) -> bool:
    return np.issubdtype(np.asarray(arr).dtype, np.floating) or "bfloat16" in str(
        getattr(arr, "dtype", "")
    )


# --------------------------------------------------------------------------
# dest side
# --------------------------------------------------------------------------


@dataclass
class _TransferOp:
    """One planned read: pull ``handle``'s bytes, slice-copy into every dest
    region it overlaps (reference plan semantics,
    direct_weight_sync.py:221-317)."""

    flat_key: str
    handle: WeightHandle
    region: Box  # global region this op covers


class DirectWeightSyncDest:
    def __init__(self, pool_size: int = 4) -> None:
        self.pool_size = pool_size
        self._plan: Optional[list[_TransferOp]] = None
        self._plan_sig: Optional[tuple] = None
        self._conns: dict[tuple[str, int], dict] = {}
        self._segments: dict[str, shm.ShmSegment] = {}
        self._lock = asyncio.Lock()
        # Set by preplan() (the ts.prewarm transfer-plan precompute); the
        # first pull that reuses the preplanned plan counts a cache hit.
        self._preplanned = False

    # ---- plan -------------------------------------------------------------

    def _build_plan(
        self,
        all_handles: dict[str, list[WeightHandle]],
        dest_flat: dict[str, Any],
    ) -> list[_TransferOp]:
        plan: list[_TransferOp] = []
        for flat_key, target in dest_flat.items():
            if not _is_tensor_like(target):
                continue
            handles = all_handles.get(flat_key)
            if handles is None:
                raise KeyError(
                    f"dest state dict expects {flat_key!r} but the source "
                    "published no handle for it"
                )
            for want in _target_slices(target):
                covered: set[Box] = set()
                covered_elems = 0
                for handle in handles:
                    inter = intersect_boxes(handle.tensor_slice.box, want.box)
                    if inter is None or inter in covered:
                        continue  # replicated-shard dedup (reference :247-261)
                    covered.add(inter)
                    covered_elems += inter.size
                    plan.append(_TransferOp(flat_key, handle, inter))
                if covered_elems < want.box.size:
                    # Returning np.empty garbage for uncovered regions would
                    # silently corrupt weights — fail loudly instead.
                    raise ValueError(
                        f"source shards cover only {covered_elems} of "
                        f"{want.box.size} elements of {flat_key!r} region "
                        f"{want.box}"
                    )
        return plan

    # ---- pull -------------------------------------------------------------

    async def pull(
        self,
        all_handles: dict[str, list[WeightHandle]],
        dest_state_dict: Any,
        key_order: Optional[list] = None,
        on_layer=None,
    ) -> Any:
        """Concurrently pull every planned region and rebuild the dest dict,
        seqlock-validated against concurrent source refreshes: source
        generations are read before and after the data moves, and the pull
        retries ONCE when any source refreshed mid-flight (a retry fully
        overwrites in-place landings). The plan is cached and reused while
        the handle/dest signature is unchanged (reference cached-plan
        invariant).

        ``key_order`` (model-forward order) serializes the pull into
        per-key waves so the FIRST layers land first, with
        ``on_layer(flat_key, value)`` (sync or async) invoked as each key
        completes — the consumer's forward pass starts before the last
        layer lands. Note the seqlock re-check still happens at the END of
        the full pull: on_layer consumers must treat served layers as
        tentative until pull returns (a raced refresh retries the whole
        pull and re-serves every layer)."""
        endpoints = sorted(
            {
                (h.hostname, h.port)
                for handle_list in all_handles.values()
                for h in handle_list
            }
        )
        gens0 = None
        for attempt in (0, 1):
            try:
                gens0 = await self._stable_gens(endpoints)
            except KeyError:
                # Pre-generation source (or server without the op): serve
                # the pull unchecked rather than failing it.
                return await self._pull_once(
                    all_handles, dest_state_dict, key_order, on_layer
                )
            result = await self._pull_once(
                all_handles, dest_state_dict, key_order, on_layer
            )
            gens1 = list(
                await asyncio.gather(
                    *(self._read_gen(h, p) for h, p in endpoints)
                )
            )
            if gens1 == gens0:
                return result
            logger.info(
                "direct pull raced a source refresh (gens %s -> %s); "
                "retrying once",
                gens0,
                gens1,
            )
        raise PullRaceError(
            "direct pull torn twice by concurrent source refreshes — "
            "throttle publishes or pull between refreshes"
        )

    async def _read_gen(self, hostname: str, port: int) -> int:
        (gen,) = _U64.unpack(
            await self._control_op(hostname, port, _GET_GEN)
        )
        return gen

    async def _stable_gens(self, endpoints) -> list:
        """Every source's generation once none is mid-overwrite (odd).

        The wait scales to ``config.direct_settle_timeout`` (default 30 s,
        env ``TORCHSTORE_TPU_DIRECT_SETTLE_TIMEOUT``): a model-scale host
        refresh or another dest's fallback D2H staging legitimately holds
        the generation odd for seconds."""
        import time

        from torchstore_tpu.config import default_config

        deadline = time.monotonic() + default_config().direct_settle_timeout
        delay = 0.02
        while True:
            gens = list(
                await asyncio.gather(
                    *(self._read_gen(h, p) for h, p in endpoints)
                )
            )
            if all(g % 2 == 0 for g in gens):
                return gens
            if time.monotonic() >= deadline:
                raise PullRaceError(
                    "source refresh never settled (generation stayed odd "
                    f"for {default_config().direct_settle_timeout:.0f}s) — "
                    "source wedged mid-refresh?"
                )
            await asyncio.sleep(delay)
            delay = min(delay * 1.5, 0.25)

    @staticmethod
    def _plan_signature(
        all_handles: dict[str, list[WeightHandle]], dest_flat: dict[str, Any]
    ) -> tuple:
        # The signature must cover the dest layouts, not just key names — a
        # changed target sharding must rebuild the plan (and re-run its
        # coverage validation), never reuse a stale one.
        target_sig = tuple(
            sorted(
                (
                    k,
                    tuple(
                        (ts.offsets, ts.local_shape, ts.global_shape)
                        for ts in _target_slices(v)
                    ),
                )
                for k, v in dest_flat.items()
                if _is_tensor_like(v)
            )
        )
        handle_sig = tuple(
            sorted(
                (
                    k,
                    tuple(
                        sorted(
                            (h.tensor_slice.offsets, h.tensor_slice.local_shape)
                            for h in v
                        )
                    ),
                )
                for k, v in all_handles.items()
            )
        )
        return (handle_sig, target_sig)

    def _ensure_plan(
        self,
        all_handles: dict[str, list[WeightHandle]],
        dest_flat: dict[str, Any],
    ) -> bool:
        """Build (or reuse) the transfer plan for this handle/target pair;
        returns True when the cached plan was reused."""
        sig = self._plan_signature(all_handles, dest_flat)
        if self._plan is not None and self._plan_sig == sig:
            return True
        self._plan = self._build_plan(all_handles, dest_flat)
        self._plan_sig = sig
        return False

    async def preplan(
        self,
        all_handles: dict[str, list[WeightHandle]],
        dest_state_dict: Any,
    ) -> dict:
        """Transfer-plan precompute (the ts.prewarm hook for the direct
        path): build + cache the plan, pre-dial every source endpoint's
        first connection, and pre-attach same-host SHM staging segments —
        so iteration 0 of acquire() pays only the data movement. Failures
        are per-resource and advisory (the lazy path re-dials/attaches as
        before); the plan itself raises on genuine coverage errors so a
        misconfigured dest fails at prewarm time rather than mid-sync."""
        dest_flat, _ = flatten_state_dict(dest_state_dict)
        reused = self._ensure_plan(all_handles, dest_flat)
        self._preplanned = True
        dials = 0
        dial_errors = 0
        endpoints = sorted(
            {
                (h.hostname, h.port)
                for handle_list in all_handles.values()
                for h in handle_list
            }
        )
        for hostname, port in endpoints:
            host = "127.0.0.1" if hostname == get_hostname() else hostname
            try:
                await self._get_conn(host, port)
                dials += 1
            except Exception:  # noqa: BLE001 - advisory; lazy path re-dials
                dial_errors += 1
        attached = 0
        for handle_list in all_handles.values():
            for h in handle_list:
                if (
                    h.shm_name is None
                    or h.hostname != get_hostname()
                    or h.shm_name in self._segments
                ):
                    continue
                try:
                    self._segments[h.shm_name] = shm.ShmSegment.attach(
                        h.shm_name, max(h.meta.nbytes, 1), populate=True
                    )
                    attached += 1
                except OSError:
                    pass  # source gone/re-registered; lazy path resolves
        return {
            "plan_ops": len(self._plan or ()),
            "plan_reused": reused,
            "dials": dials,
            "dial_errors": dial_errors,
            "segments_attached": attached,
        }

    async def _pull_once(
        self,
        all_handles: dict[str, list[WeightHandle]],
        dest_state_dict: Any,
        key_order: Optional[list] = None,
        on_layer=None,
    ) -> Any:
        tracker = LatencyTracker("direct_pull")
        dest_flat, mapping = flatten_state_dict(dest_state_dict)
        reused = self._ensure_plan(all_handles, dest_flat)
        if reused and self._preplanned:
            # Iteration-0 hit on a prewarm-built plan: the cold/steady gap's
            # plan component was paid at prewarm time.
            _PLAN_PREWARM_HITS.inc()
            self._preplanned = False
        tracker.track_step("plan")

        # Host landing buffers per (flat_key, target slice). A numpy target
        # with one full-array slice IS its own landing buffer — ops write
        # straight into destination memory (the reference's exact-match
        # zero-extra-copy path, direct_weight_sync.py:221-247).
        landings: dict[str, list[tuple[TensorSlice, np.ndarray]]] = {}
        inplace_targets: set[str] = set()
        from torchstore_tpu.client import Shard as _Shard

        for flat_key, target in dest_flat.items():
            if not _is_tensor_like(target):
                continue
            wants = _target_slices(target)
            # Shard targets land into their provided buffer; plain ndarray
            # targets into themselves (both in place, no extra copy).
            buf = target.data if isinstance(target, _Shard) else target
            if (
                isinstance(buf, np.ndarray)
                and len(wants) == 1
                and tuple(buf.shape) == wants[0].local_shape
                and buf.flags["C_CONTIGUOUS"]
                and buf.flags["WRITEABLE"]
            ):
                landings[flat_key] = [(wants[0], buf)]
                inplace_targets.add(flat_key)
            else:
                if isinstance(target, _Shard) and target.data is None:
                    # Buffer-less region pull: dtype comes from the source.
                    dtype = all_handles[flat_key][0].meta.np_dtype
                else:
                    dtype = _np_dtype_of(target)
                landings[flat_key] = [
                    (want, np.empty(want.local_shape, dtype)) for want in wants
                ]

        # Each source shard is read ONCE per pull, however many dest regions
        # overlap it — and only the row range its ops actually need (ranged
        # reads cut DCN bytes when a pull touches part of a shard). Keyed by
        # (host, port, buffer_id): buffer ids are per-SOURCE counters, so two
        # ranks' shards share ids and a bare-id key would collapse them.
        by_handle: dict[tuple, tuple[WeightHandle, list[_TransferOp]]] = {}
        for op in self._plan:
            hkey = (op.handle.hostname, op.handle.port, op.handle.buffer_id)
            by_handle.setdefault(hkey, (op.handle, []))[1].append(op)
        row_ranges = {
            hkey: _row_range(handle, ops)
            for hkey, (handle, ops) in by_handle.items()
        }
        out_flat = dict(dest_flat)
        if key_order is not None or on_layer is not None:
            # Ordered per-key waves (layer-streamed consumers): each flat
            # key's shard reads + landings complete before the next key
            # starts, so forward-order consumers see layer k before k+1.
            # A shard feeding several keys is still read ONCE (cached by
            # handle key); keys outside the order are appended after it.
            from torchstore_tpu.utils import maybe_await

            ops_by_key: dict[str, list[_TransferOp]] = {}
            for op in self._plan:
                ops_by_key.setdefault(op.flat_key, []).append(op)
            order = [k for k in (key_order or []) if k in ops_by_key]
            tail = [k for k in ops_by_key if k not in set(order)]
            shard_raws: dict[tuple, tuple] = {}
            ops_bytes = 0
            for flat_key in order + tail:
                need = []
                for op in ops_by_key[flat_key]:
                    hkey = (
                        op.handle.hostname,
                        op.handle.port,
                        op.handle.buffer_id,
                    )
                    if hkey not in shard_raws and hkey not in need:
                        need.append(hkey)
                reads = await asyncio.gather(
                    *(
                        self._read_shard(by_handle[hk][0], row_ranges[hk])
                        for hk in need
                    )
                )
                for hk, read in zip(need, reads):
                    shard_raws[hk] = read
                    ops_bytes += read[0].nbytes
                for op in ops_by_key[flat_key]:
                    hkey = (
                        op.handle.hostname,
                        op.handle.port,
                        op.handle.buffer_id,
                    )
                    arr, row0 = shard_raws[hkey]
                    self._apply_op(op, arr, row0, landings)
                parts = landings[flat_key]
                if flat_key in inplace_targets:
                    out_flat[flat_key] = parts[0][1]
                else:
                    out_flat[flat_key] = _rebuild(
                        dest_flat[flat_key], parts
                    )
                if on_layer is not None:
                    await maybe_await(
                        on_layer(flat_key, out_flat[flat_key])
                    )
            tracker.track_step("reads", ops_bytes)
            tracker.track_step("rebuild")
        else:
            reads = await asyncio.gather(
                *(
                    self._read_shard(handle, row_ranges[hkey])
                    for hkey, (handle, _) in by_handle.items()
                )
            )
            shard_raws = dict(zip(by_handle.keys(), reads))
            ops_bytes = 0
            for hkey, (arr, row0) in shard_raws.items():
                ops_bytes += arr.nbytes
                for op in by_handle[hkey][1]:
                    self._apply_op(op, arr, row0, landings)
            tracker.track_step("reads", ops_bytes)

            for flat_key, parts in landings.items():
                if flat_key in inplace_targets:
                    out_flat[flat_key] = parts[0][1]  # the target array
                else:
                    out_flat[flat_key] = _rebuild(dest_flat[flat_key], parts)
            tracker.track_step("rebuild")
        tracker.log_summary(level=20)
        from torchstore_tpu.state_dict_utils import unflatten_state_dict

        return unflatten_state_dict(out_flat, mapping)

    def _apply_op(
        self, op: _TransferOp, shard_arr: np.ndarray, row0: int, landings
    ) -> None:
        """``shard_arr`` covers shard rows [row0, row0+len) of the handle's
        slice (row0 > 0 for ranged reads)."""
        for want, buf in landings[op.flat_key]:
            inter = intersect_boxes(op.region, want.box)
            if inter is None:
                continue
            shard_offsets = op.handle.tensor_slice.offsets
            rel_src = tuple(
                slice(
                    o - so - (row0 if d == 0 else 0),
                    o - so - (row0 if d == 0 else 0) + s,
                )
                for d, (o, so, s) in enumerate(
                    zip(inter.offsets, shard_offsets, inter.shape)
                )
            )
            view = get_destination_view(
                buf, want.box, inter, require_contiguous=False
            )
            copy_into(view, shard_arr[rel_src])

    async def _get_conn(self, host: str, port: int):
        """A pooled (reader, writer, lock) to a source's peer server — a
        small pool per source so concurrent reads overlap on the wire
        instead of serializing behind one connection."""
        key = (host, port)
        async with self._lock:
            pool = self._conns.get(key)
            if pool is None:
                pool = {"conns": [], "rr": 0}
                self._conns[key] = pool
            if len(pool["conns"]) < self.pool_size:
                reader, writer = await asyncio.wait_for(
                    asyncio.open_connection(host, port), timeout=30
                )
                from torchstore_tpu.runtime.auth import client_authenticate

                await client_authenticate(reader, writer)
                conn = (reader, writer, asyncio.Lock())
                pool["conns"].append(conn)
            else:
                conn = pool["conns"][pool["rr"] % len(pool["conns"])]
                pool["rr"] += 1
        return conn

    # ---- device (ICI) path ------------------------------------------------

    async def pull_device(
        self, device_infos: list[dict], dest_state_dict: Any
    ) -> Any:
        """One-hop device pull across every source rank: ask each rank to
        stage its current arrays, pull them device-to-device through the
        transfer engine, merge the per-rank parts, then land into the dest
        targets (resharding locally where the target sharding differs — XLA
        moves the shards over ICI). Falls back to each rank's host-staging
        control op when the published device shardings reference device ids
        this process cannot see (disjoint jax worlds)."""
        from torchstore_tpu.transport import device_transfer as dt

        tracker = LatencyTracker("direct_pull_device")
        dest_flat, mapping = flatten_state_dict(dest_state_dict)
        # Build every rank's pull specs BEFORE staging anything: a
        # staged-but-never-pulled uuid would pin source arrays in its
        # transfer server. The built shardings are reused for the pull
        # itself (one Mesh construction per entry, not two).
        try:
            built_specs = [
                [e.spec.to_jax() for e in info["entries"]]
                for info in device_infos
            ]
        except ValueError as exc:
            logger.warning(
                "device path unavailable (%s); falling back to source-side "
                "host staging",
                exc,
            )
            all_handles: dict[str, list[WeightHandle]] = {}
            # Ranks materialize independently — fetch concurrently (each
            # rank's D2H staging overlaps instead of serializing).
            fetched = await asyncio.gather(
                *(self._fetch_host_handles(info) for info in device_infos)
            )
            for rank_handles in fetched:
                for flat_key, hl in rank_handles.items():
                    all_handles.setdefault(flat_key, []).extend(hl)
            return await self.pull(all_handles, dest_state_dict)

        engine = dt.DeviceTransferEngine.get()
        parts_by_key: dict[str, list[tuple[TensorSlice, Any]]] = {}
        pulled_bytes = 0
        # Each staged snapshot is internally consistent (immutable arrays
        # captured in one event-loop call), but ranks refresh independently
        # — a pull mixing rank A at step N with rank B at N+1 is torn.
        # Every rank's stage op reports its generation; mixed gens retry
        # the whole pull once. Stage each rank immediately before pulling
        # it: on a mid-sequence failure at most ONE staged uuid is left
        # un-pulled (the engine has no un-stage op).
        for attempt in (0, 1):
            parts_by_key.clear()
            pulled_bytes = 0
            gens = []
            for info, specs in zip(device_infos, built_specs):
                uid, gen = await self._stage_remote(info)
                gens.append(gen)
                entries = info["entries"]
                arrays = engine.pull_built(info["address"], uid, specs)
                for entry, arr in zip(entries, arrays):
                    parts_by_key.setdefault(entry.flat_key, []).append(
                        (entry.tensor_slice, arr)
                    )
                    pulled_bytes += int(np.prod(entry.spec.shape)) * TensorMeta(
                        shape=(), dtype=entry.spec.dtype
                    ).np_dtype.itemsize
            if len(set(gens)) <= 1:
                break
            logger.info(
                "device pull mixed source generations %s; retrying once",
                gens,
            )
        else:
            raise PullRaceError(
                f"device pull mixed source generations twice ({gens}) — "
                "source ranks are publishing out of lockstep"
            )
        tracker.track_step("pull", pulled_bytes)
        out_flat = dict(dest_flat)
        for flat_key, target in dest_flat.items():
            if not _is_tensor_like(target):
                continue
            parts = parts_by_key.get(flat_key)
            if parts is None:
                raise KeyError(
                    f"dest state dict expects {flat_key!r} but no source "
                    "rank published a device entry for it"
                )
            if len(parts) == 1 and parts[0][0].is_full():
                out_flat[flat_key] = _land_device(target, parts[0][1])
            else:
                out_flat[flat_key] = _assemble_device(flat_key, target, parts)
        tracker.track_step("land")
        tracker.log_summary(level=20)
        from torchstore_tpu.state_dict_utils import unflatten_state_dict

        return unflatten_state_dict(out_flat, mapping)

    async def _control_op(self, hostname: str, port: int, opcode: int) -> bytes:
        """One control op against a source's peer server: send the sentinel
        ``opcode``, return the response payload (all control ops share the
        length-prefixed reply shape)."""
        host = "127.0.0.1" if hostname == get_hostname() else hostname
        reader, writer, lock = await self._get_conn(host, port)
        async with lock:
            writer.write(_READ_REQ.pack(opcode, 0, 0))
            await writer.drain()
            (length,) = _READ_RESP.unpack(await reader.readexactly(_READ_RESP.size))
            if length == _ERR:
                raise KeyError(
                    "source refused to stage: no device-mode "
                    "registration, or stage-time validation failed "
                    "(check source logs)"
                )
            return await reader.readexactly(length)

    async def _control_request(self, device_info: dict, opcode: int) -> bytes:
        return await self._control_op(
            device_info["hostname"], device_info["control_port"], opcode
        )

    async def _stage_remote(self, device_info: dict) -> tuple[int, int]:
        """Ask one source rank to stage its current arrays; returns the
        transfer uuid serving exactly this pull plus the source's weight
        generation at staging time (the snapshot's step identity)."""
        uid, gen = _2U64.unpack(
            await self._control_request(device_info, _STAGE_DEVICE)
        )
        return uid, gen

    async def _fetch_host_handles(
        self, device_info: dict
    ) -> dict[str, list[WeightHandle]]:
        """Ask one source rank to materialize its device arrays into host
        buffers; returns the WeightHandles serving them over TCP."""
        import pickle

        return pickle.loads(
            await self._control_request(device_info, _STAGE_HOST)
        )

    async def _read_shard(
        self, handle: WeightHandle, row_range: Optional[tuple[int, int]] = None
    ) -> tuple[np.ndarray, int]:
        """One-hop read of a source buffer: SHM attach on the same host, TCP
        (ranged when ``row_range`` is set) across hosts. Returns
        ``(shard-shaped array rows, first_row)``."""
        shape = handle.meta.shape
        if handle.shm_name is not None and handle.hostname == get_hostname():
            # Attach is free — no transfer to range. The blessed one-sided
            # accessor: the surrounding pull() brackets this read with the
            # source's generation seqlock (_stable_gens before, gens
            # re-read after), so a torn read is detected and retried.
            seg = self._segments.get(handle.shm_name)
            if seg is None:
                seg = shm.ShmSegment.attach(
                    handle.shm_name, max(handle.meta.nbytes, 1), populate=True
                )
                self._segments[handle.shm_name] = seg
            view = shm.segment_read_view(seg, handle.meta)
            return np.asarray(view).reshape(shape), 0
        # Same-host TCP reads dial loopback (the container hostname may not
        # route back to this process); cross-host uses the advertised name.
        host = (
            "127.0.0.1" if handle.hostname == get_hostname() else handle.hostname
        )
        reader, writer, lock = await self._get_conn(host, handle.port)
        row_bytes = (
            handle.meta.nbytes // shape[0] if shape and shape[0] else handle.meta.nbytes
        )
        if row_range is not None and shape:
            r0, r1 = row_range
            offset, want_len = r0 * row_bytes, (r1 - r0) * row_bytes
            out_shape = (r1 - r0,) + tuple(shape[1:])
        else:
            r0, offset, want_len = 0, 0, handle.meta.nbytes
            out_shape = tuple(shape)
        async with lock:
            writer.write(_READ_REQ.pack(handle.buffer_id, offset, want_len))
            await writer.drain()
            (length,) = _READ_RESP.unpack(await reader.readexactly(_READ_RESP.size))
            if length == _ERR:
                raise KeyError(
                    f"source no longer has buffer {handle.buffer_id} "
                    f"(rank {handle.source_rank})"
                )
            raw = await reader.readexactly(length)
        arr = np.frombuffer(bytearray(raw), dtype=handle.meta.np_dtype)
        return arr.reshape(out_shape), r0

    async def close(self) -> None:
        # Under the pool lock: close racing a _get_conn mid-dial would
        # otherwise leak the freshly opened connection past the clear().
        async with self._lock:
            for pool in self._conns.values():
                for _, writer, _ in pool["conns"]:
                    try:
                        writer.close()
                    except Exception:
                        pass
            self._conns.clear()
        for seg in self._segments.values():
            seg.close()
        self._segments.clear()


# --------------------------------------------------------------------------
# helpers shared by plan/pull
# --------------------------------------------------------------------------


def _row_range(
    handle: WeightHandle, ops: list[_TransferOp]
) -> Optional[tuple[int, int]]:
    """Shard-local dim-0 row range covering every op, or None for a full
    read. Ranging applies only when each op's region spans the shard's full
    extent in every trailing dim (then rows are a contiguous byte range —
    the protocol's offset/length supports it directly)."""
    ts = handle.tensor_slice
    if not ts.local_shape:
        return None
    lo, hi = None, None
    for op in ops:
        for d in range(1, len(ts.local_shape)):
            if (
                op.region.offsets[d] != ts.offsets[d]
                or op.region.shape[d] != ts.local_shape[d]
            ):
                return None
        r0 = op.region.offsets[0] - ts.offsets[0]
        r1 = r0 + op.region.shape[0]
        lo = r0 if lo is None else min(lo, r0)
        hi = r1 if hi is None else max(hi, r1)
    if lo == 0 and hi == ts.local_shape[0]:
        return None  # full shard anyway
    return lo, hi


def _is_tensor_like(value) -> bool:
    from torchstore_tpu.client import Shard

    return (
        isinstance(value, (np.ndarray, Shard))
        or shd.is_jax_array(value)
        or shd.is_sharded_spec(value)
        or shd.is_plain_spec(value)
    )


def _is_tensor_leaf(value) -> bool:
    """Source-side leaf classification (register): array-valued leaves,
    including rank-local Shard wrappers (SPMD sources)."""
    from torchstore_tpu.client import Shard as _Shard

    return (
        isinstance(value, (np.ndarray, _Shard)) or shd.is_jax_array(value)
    )


def _assemble_region_on_device(want, parts, dtype, device):
    """Assemble global region ``want`` from overlapping ``parts`` as a
    single-device array on ``device``: each overlap is sliced out of its
    part ON the part's devices (lax.slice), moved with device_put (ICI on
    real hardware), and placed with dynamic_update_slice — peak memory is
    one region plus one overlap piece, never the dense global tensor."""
    import jax
    import jax.numpy as jnp

    out = jax.device_put(jnp.zeros(want.local_shape, dtype), device)
    for ts_slice, arr in parts:
        inter = intersect_boxes(ts_slice.box, want.box)
        if inter is None:
            continue
        starts = [o - so for o, so in zip(inter.offsets, ts_slice.offsets)]
        piece = jax.lax.slice(
            arr, starts, [s + sz for s, sz in zip(starts, inter.shape)]
        )
        piece = jax.device_put(piece, device)
        if piece.dtype != dtype:
            piece = piece.astype(dtype)
        out = jax.lax.dynamic_update_slice(
            out,
            piece,
            tuple(o - wo for o, wo in zip(inter.offsets, want.offsets)),
        )
    return out


def _assemble_device(flat_key: str, target, parts):
    """Assemble a multi-part device pull (per-rank / per-shard entries) into
    one dest target. jax-ish targets assemble ON DEVICE, one target shard
    at a time (no dense single-device copy of the global tensor is ever
    materialized); host targets land each part into its destination
    region. Coverage is validated by exact box union — overlapping or
    replicated parts cannot mask a hole."""
    import jax
    import jax.numpy as jnp

    from torchstore_tpu.client import Shard as _Shard

    # Replicated source shards publish identical regions; pull cost was
    # already paid upstream (dedup at publication), this guards merged
    # multi-rank duplicates.
    seen: set[tuple] = set()
    deduped = []
    for ts_slice, arr in parts:
        sig = (ts_slice.offsets, ts_slice.local_shape)
        if sig in seen:
            continue
        seen.add(sig)
        deduped.append((ts_slice, arr))
    parts = deduped
    global_shape = tuple(parts[0][0].global_shape)
    global_box = Box((0,) * len(global_shape), global_shape)
    if not boxes_cover(global_box, [ts_slice.box for ts_slice, _ in parts]):
        raise ValueError(
            f"source ranks do not cover all of {flat_key!r} "
            f"{global_shape} — missing regions would silently read as zeros"
        )
    if (
        shd.is_jax_array(target)
        or shd.is_sharded_spec(target)
        or shd.is_plain_spec(target)
    ):
        if tuple(target.shape) != global_shape:
            raise ValueError(
                f"pulled global shape {global_shape} != target shape "
                f"{tuple(target.shape)} for {flat_key!r}"
            )
        dtype = jnp.dtype(str(target.dtype))
        sharding = getattr(target, "sharding", None)
        if sharding is not None and not shd._is_demotable(sharding):
            # Shard-wise assembly straight into the target layout.
            shard_list = shd.target_slices(target)
            locals_ = [
                _assemble_region_on_device(want, parts, dtype, dev)
                for dev, want in shard_list
            ]
            return jax.make_array_from_single_device_arrays(
                global_shape, sharding, locals_
            )
        full = _full_slice(global_shape)
        out = _assemble_region_on_device(full, parts, dtype, jax.devices()[0])
        if sharding is not None:
            out = jax.device_put(out, sharding)
        return out
    # Host targets: one want region (Shard → its slice, numpy → full);
    # copy every overlapping part into the destination view.
    (want,) = _target_slices(target)
    buf = target.data if isinstance(target, _Shard) else target
    if buf is None:
        dtype = TensorMeta(shape=(), dtype=parts[0][1].dtype.name).np_dtype
        buf = np.empty(want.local_shape, dtype)
    touched = []
    for ts_slice, arr in parts:
        inter = intersect_boxes(ts_slice.box, want.box)
        if inter is None:
            continue
        host = np.asarray(arr)
        rel_src = tuple(
            slice(o - so, o - so + s)
            for o, so, s in zip(inter.offsets, ts_slice.offsets, inter.shape)
        )
        view = get_destination_view(buf, want.box, inter, require_contiguous=False)
        copy_into(view, host[rel_src])
        touched.append(inter)
    if not boxes_cover(want.box, touched):
        raise ValueError(
            f"source ranks do not cover region {want.box} of {flat_key!r}"
        )
    return buf


def _land_device(target, arr):
    """Land a pulled device array into a dest target: reshard on device for
    jax targets (device_put compiles to ICI collectives), copy to host
    memory for numpy/Shard targets."""
    import jax

    from torchstore_tpu.client import Shard as _Shard

    if (
        shd.is_jax_array(target)
        or shd.is_sharded_spec(target)
        or shd.is_plain_spec(target)
    ):
        if tuple(arr.shape) != tuple(target.shape):
            raise ValueError(
                f"pulled shape {tuple(arr.shape)} != target shape "
                f"{tuple(target.shape)} (source re-published under a "
                "different shape?)"
            )
        want_dtype = getattr(target, "dtype", None)
        if want_dtype is not None and arr.dtype != want_dtype:
            arr = arr.astype(want_dtype)
        sharding = getattr(target, "sharding", None)
        if sharding is not None and sharding != arr.sharding:
            arr = jax.device_put(arr, sharding)
        return arr
    if isinstance(target, _Shard):
        region = tuple(
            slice(o, o + s)
            for o, s in zip(target.tensor_slice.offsets, target.tensor_slice.local_shape)
        )
        part = np.asarray(arr[region])
        if target.data is not None:
            copy_into(target.data, part)
            return target.data
        return part
    # numpy target: full copy in place.
    copy_into(target, np.asarray(arr))
    return target


def _np_dtype_of(value) -> np.dtype:
    from torchstore_tpu.client import Shard

    if isinstance(value, Shard):
        value = value.data
    # Avoids materializing jax arrays on host just to learn their dtype.
    return TensorMeta(shape=(), dtype=str(value.dtype)).np_dtype


def _target_slices(value) -> list[TensorSlice]:
    from torchstore_tpu.client import Shard

    if isinstance(value, Shard):
        # Explicit region target: pull only this slice of the global space
        # (SPMD ranks syncing their own shard).
        return [value.tensor_slice]
    if shd.is_jax_array(value) or shd.is_sharded_spec(value):
        return [ts for _, ts in shd.target_slices(value)]
    # numpy arrays and sharding-less ShapeDtypeStructs: one full slice.
    return [_full_slice(value.shape)]


def _rebuild(target, parts: list[tuple[TensorSlice, np.ndarray]]):
    from torchstore_tpu.client import Shard

    if isinstance(target, Shard):
        ((_, arr),) = parts
        if target.data is not None:
            copy_into(target.data, arr)
            return target.data
        return arr
    if shd.is_jax_array(target) or shd.is_sharded_spec(target):
        devs = [dev for dev, _ in shd.target_slices(target)]
        return shd.build_array(target, [(d, arr) for d, (_, arr) in zip(devs, parts)])
    if shd.is_plain_spec(target):
        import jax.numpy as jnp

        ((_, arr),) = parts
        return jnp.asarray(arr, dtype=target.dtype)
    # numpy target: single full slice, filled in place.
    ((_, arr),) = parts
    copy_into(target, arr)
    return target
