"""Direct (one-hop) weight sync: dest pulls straight from the source's
registered buffers — the store carries only metadata handles.

TPU re-architecture of /root/reference/torchstore/direct_weight_sync.py
(:46-350). The reference rides ibverbs one-sided RDMA reads of source GPU
memory; TPUs expose no such primitive (SURVEY §7.3), so the same API —
register -> publish handles -> cached transfer plan -> concurrent pull ->
refresh — is kept, with the data path re-based on a source-side **peer
buffer engine**:

- same host: staging buffers live in /dev/shm segments; the dest attaches
  and copies directly (true one-hop, zero intermediary).
- cross host: the source process runs a tiny read server; dests issue
  ranged reads over cached TCP connections (DCN path).

Handles published under ``{key}/rank_{r}`` + ``{key}/num_ranks`` exactly like
the reference (state_dict_utils.py:217-275), so discovery flows through the
normal store.
"""

from __future__ import annotations

import asyncio
import struct
from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

from torchstore_tpu import sharding as shd
from torchstore_tpu.logging import LatencyTracker, get_logger
from torchstore_tpu.native import copy_into
from torchstore_tpu.state_dict_utils import flatten_state_dict
from torchstore_tpu.transport import shared_memory as shm
from torchstore_tpu.transport.types import TensorMeta, TensorSlice
from torchstore_tpu.utils import (
    Box,
    get_destination_view,
    get_hostname,
    intersect_boxes,
)

logger = get_logger("torchstore_tpu.direct")

_READ_REQ = struct.Struct("<QQQ")  # buffer_id, offset, length
_READ_RESP = struct.Struct("<Q")  # length (0xFFFF.. = error)
_ERR = (1 << 64) - 1
# buffer_id sentinel: "stage the registered device arrays for one pull and
# reply with the transfer uuid" (the ICI rung's control op — each staging
# serves exactly one jax.experimental.transfer pull).
_STAGE_DEVICE = (1 << 64) - 2
_U64 = struct.Struct("<Q")


# --------------------------------------------------------------------------
# handles
# --------------------------------------------------------------------------


@dataclass
class WeightHandle:
    """Picklable pointer to one registered source shard (the reference's
    RDMAWeightHandle, direct_weight_sync.py:46-58)."""

    buffer_id: int
    hostname: str
    port: int
    shm_name: Optional[str]
    meta: TensorMeta
    tensor_slice: TensorSlice
    source_rank: int


# --------------------------------------------------------------------------
# source side
# --------------------------------------------------------------------------


class _PeerReadServer:
    """Serves ranged reads of registered buffers over TCP (cross-host path)
    and the device-staging control op (ICI rung)."""

    def __init__(self) -> None:
        self.buffers: dict[int, np.ndarray] = {}
        # Set by the source when device mode is on: () -> transfer uuid.
        self.stage_device_fn = None
        self._server: Optional[asyncio.AbstractServer] = None
        self.port: Optional[int] = None
        self._writers: set = set()

    async def ensure_started(self) -> int:
        if self._server is None:
            import os

            # Loopback by default; cross-host deployments set
            # TORCHSTORE_TPU_BIND_HOST=0.0.0.0 (+ ADVERTISE_HOST).
            bind = os.environ.get("TORCHSTORE_TPU_BIND_HOST", "127.0.0.1")
            self._server = await asyncio.start_server(self._handle, bind, 0)
            self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def _handle(self, reader, writer) -> None:
        from torchstore_tpu.runtime.auth import server_authenticate

        if not await server_authenticate(reader, writer):
            try:
                writer.close()
            except Exception:
                pass
            return
        self._writers.add(writer)
        try:
            while True:
                req = await reader.readexactly(_READ_REQ.size)
                buffer_id, offset, length = _READ_REQ.unpack(req)
                if buffer_id == _STAGE_DEVICE:
                    if self.stage_device_fn is None:
                        writer.write(_READ_RESP.pack(_ERR))
                    else:
                        uid = self.stage_device_fn()
                        writer.write(_READ_RESP.pack(_U64.size) + _U64.pack(uid))
                    await writer.drain()
                    continue
                arr = self.buffers.get(buffer_id)
                if arr is None:
                    writer.write(_READ_RESP.pack(_ERR))
                    await writer.drain()
                    continue
                flat = arr.reshape(-1).view(np.uint8)
                chunk = flat[offset : offset + length]
                writer.write(_READ_RESP.pack(chunk.nbytes))
                writer.write(memoryview(chunk))
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            self._writers.discard(writer)
            try:
                writer.close()
            except Exception:
                pass

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # Close live client connections first: py3.12's wait_closed()
            # waits for handlers, which would otherwise block forever.
            for writer in list(self._writers):
                try:
                    writer.close()
                except Exception:
                    pass
            try:
                await asyncio.wait_for(self._server.wait_closed(), timeout=2.0)
            except asyncio.TimeoutError:
                pass
            self._server = None


class DirectWeightSyncSource:
    """Registers a state dict's shards into pull-able staging buffers.

    ``register`` stages every shard once (device->host copy + optional dtype
    cast, reference staging-buffer pattern direct_weight_sync.py:99-156);
    ``refresh`` re-copies current values into the SAME buffers so published
    handles stay valid across training steps (direct_weight_sync.py:158-169).
    """

    def __init__(self, use_shm: bool = True, config=None, device: Optional[bool] = None):
        from torchstore_tpu.config import default_config

        self.use_shm = use_shm and shm.is_available()
        self.config = config or default_config()
        # None = auto (device path when eligible); False pins the host path.
        self.device = device
        self.server = _PeerReadServer()
        self.segments: dict[int, shm.ShmSegment] = {}
        self.handles: dict[str, list[WeightHandle]] = {}
        self._sources: dict[str, Any] = {}  # flat_key -> live array/jax ref
        self._transfer_dtype = None
        self._next_id = 0
        self._registered = False
        self._mapping: Optional[dict] = None
        self._flat_template: dict[str, Any] = {}
        # Device (ICI) mode state: ordered flat keys + current jax arrays.
        self.device_info: Optional[dict] = None
        self._device_keys: list[str] = []
        self._device_arrays: dict[str, Any] = {}

    def _device_mode_eligible(self, flat: dict, rank: int, num_ranks: int) -> bool:
        """Device path engages for single-controller sources whose tensor
        leaves are ALL jax arrays (the trainer owns its device mesh). Multi
        -rank SPMD sources keep the host path — combining per-rank device
        shards source-side would need a cross-rank transfer plan."""
        if self.device is False:
            return False
        if not self.config.ici_enabled or num_ranks != 1 or rank != 0:
            return False
        from torchstore_tpu.transport import device_transfer as dt

        if not dt.is_available():
            return False
        tensorish = [v for v in flat.values() if _is_tensor_leaf(v)]
        return bool(tensorish) and all(shd.is_jax_array(v) for v in tensorish)

    async def register(
        self,
        state_dict: Any,
        rank: int = 0,
        transfer_dtype=None,
        num_ranks: int = 1,
    ) -> dict[str, list[WeightHandle]]:
        import os

        port = await self.server.ensure_started()
        self._transfer_dtype = transfer_dtype
        flat, mapping = flatten_state_dict(state_dict)
        self._mapping = mapping
        # Only NON-tensor leaves are kept (staging_state_dict fills tensor
        # keys from the registered buffers); keeping tensor leaves would pin
        # a full copy of the registration-time weights forever.
        self._flat_template = {
            k: v for k, v in flat.items() if not _is_tensor_leaf(v)
        }
        # Advertise the same reachable name the actor runtime uses.
        hostname = os.environ.get("TORCHSTORE_TPU_ADVERTISE_HOST", get_hostname())
        if self._device_mode_eligible(flat, rank, num_ranks):
            return self._register_device(flat, hostname, port, transfer_dtype)
        for flat_key, value in flat.items():
            if (
                transfer_dtype is not None
                and shd.is_jax_array(value)
                and _is_floating(value)
            ):
                # Cast on device (ops.device_cast: fused XLA / pallas kernel)
                # so the HBM->host copy moves the transfer dtype's bytes.
                from torchstore_tpu.ops import device_cast

                value = device_cast(value, transfer_dtype)
            shards = self._shards_of(value)
            if shards is None:
                continue  # non-tensor leaves don't take the direct path
            self._sources[flat_key] = value
            handle_list: list[WeightHandle] = []
            for ts_slice, host_arr in shards:
                if (
                    transfer_dtype is not None
                    and _is_floating(host_arr)
                    and host_arr.dtype != np.dtype(transfer_dtype)
                ):
                    host_arr = host_arr.astype(transfer_dtype)
                host_arr = np.ascontiguousarray(host_arr)
                buffer_id = self._next_id
                self._next_id += 1
                shm_name = None
                if self.use_shm:
                    seg = shm.ShmSegment.create(max(host_arr.nbytes, 1))
                    staged = seg.view(TensorMeta.of(host_arr))
                    np.copyto(staged, host_arr)
                    self.segments[buffer_id] = seg
                    self.server.buffers[buffer_id] = staged
                    shm_name = seg.name
                else:
                    self.server.buffers[buffer_id] = host_arr.copy()
                handle_list.append(
                    WeightHandle(
                        buffer_id=buffer_id,
                        hostname=hostname,
                        port=port,
                        shm_name=shm_name,
                        meta=TensorMeta.of(host_arr),
                        tensor_slice=ts_slice,
                        source_rank=rank,
                    )
                )
            self.handles[flat_key] = handle_list
        self._registered = True
        return self.handles

    def _register_device(
        self, flat: dict, hostname: str, port: int, transfer_dtype
    ) -> dict:
        """ICI rung registration: no host staging at all. Arrays stay on
        device; every dest pull stages the CURRENT arrays through the XLA
        transfer server (device-to-device over ICI/DCN — the reference's
        one-sided GPU read, monarch_rdma.py:158-219, without host bounce)."""
        from torchstore_tpu.transport import device_transfer as dt

        engine = dt.DeviceTransferEngine.get()
        self._device_keys = []
        self._device_arrays = {}
        specs = {}
        for flat_key, value in flat.items():
            if not _is_tensor_leaf(value):
                continue
            self._device_keys.append(flat_key)
            self._device_arrays[flat_key] = value  # uncast; cast at stage time
            if transfer_dtype is not None and _is_floating(value):
                from torchstore_tpu.ops import device_cast

                value = device_cast(value, transfer_dtype)
            specs[flat_key] = dt.DeviceSpec.of(value)
        address = engine.ensure_server()
        self.server.stage_device_fn = self._stage_current
        self.device_info = {
            "address": address,
            "hostname": hostname,
            "control_port": port,
            "keys": list(self._device_keys),
            "specs": specs,
        }
        self._registered = True
        self.handles = {}
        logger.info(
            "direct sync registered %d tensors on the device (ICI) path",
            len(self._device_keys),
        )
        return self.handles

    def _stage_current(self) -> int:
        from torchstore_tpu.transport import device_transfer as dt

        engine = dt.DeviceTransferEngine.get()
        arrays = [self._device_arrays[k] for k in self._device_keys]
        if self._transfer_dtype is not None:
            from torchstore_tpu.ops import device_cast

            arrays = [
                device_cast(a, self._transfer_dtype) if _is_floating(a) else a
                for a in arrays
            ]
        return engine.stage(arrays)

    @staticmethod
    def _shards_of(value) -> Optional[list[tuple[TensorSlice, np.ndarray]]]:
        if shd.is_jax_array(value):
            reqs = shd.put_requests("_", value)
            out = []
            for req in reqs:
                if req.tensor_slice is not None:
                    out.append((req.tensor_slice, np.asarray(req.tensor_val)))
                else:
                    arr = np.asarray(req.tensor_val)
                    out.append((_full_slice(arr.shape), arr))
            return out
        if isinstance(value, np.ndarray):
            return [(_full_slice(value.shape), value)]
        return None

    async def refresh(self) -> None:
        """Re-stage current param values into the registered buffers.

        Device (ICI) mode needs no work here: staging happens per pull, so
        dests always read the arrays ``update_sources`` last installed."""
        if not self._registered:
            raise RuntimeError("register() must run before refresh()")
        if self.device_info is not None:
            return
        for flat_key, value in self._sources.items():
            if (
                self._transfer_dtype is not None
                and shd.is_jax_array(value)
                and _is_floating(value)
            ):
                from torchstore_tpu.ops import device_cast

                value = device_cast(value, self._transfer_dtype)
            shards = self._shards_of(value)
            handles = self.handles[flat_key]
            if shards is None or len(shards) != len(handles):
                raise ValueError(
                    f"refresh of {flat_key!r}: value now produces "
                    f"{0 if shards is None else len(shards)} shards but "
                    f"{len(handles)} buffers were registered — re-register "
                    "after changing a param's sharding"
                )
            for (_, host_arr), handle in zip(shards, handles):
                if (
                    self._transfer_dtype is not None
                    and _is_floating(host_arr)
                    and host_arr.dtype != np.dtype(self._transfer_dtype)
                ):
                    host_arr = host_arr.astype(self._transfer_dtype)
                staged = self.server.buffers[handle.buffer_id]
                if _aliases(staged, host_arr):
                    # Registered-buffer sources (staging_state_dict) write
                    # weights straight into the published buffers — the
                    # refresh copy vanishes, matching RDMA's register-once
                    # read-live semantics.
                    continue
                np.copyto(staged, np.ascontiguousarray(host_arr))

    def staging_state_dict(self) -> Optional[Any]:
        """The registered staging buffers in the ORIGINAL state-dict
        structure (host path, unsharded sources only). A trainer that
        writes its weights directly into these arrays makes every
        subsequent direct put a pure metadata publish — zero source-side
        copies, the host analog of RDMA registered memory
        (/root/reference/torchstore/direct_weight_sync.py:99-156 registers
        buffers once; here the caller may adopt them as its own weight
        storage). Returns None when any source is sharded/device-resident
        (device sources already sync copy-free via the ICI path)."""
        if (
            not self._registered
            or self.device_info is not None
            or self._mapping is None
        ):
            return None
        from torchstore_tpu.state_dict_utils import unflatten_state_dict

        flat = dict(self._flat_template)  # non-tensor leaves as registered
        for flat_key, handles in self.handles.items():
            if len(handles) != 1 or not handles[0].tensor_slice.is_full():
                return None
            flat[flat_key] = self.server.buffers[handles[0].buffer_id]
        return unflatten_state_dict(flat, self._mapping)

    def update_sources(self, state_dict: Any) -> None:
        """Point refresh() at new param objects (jax arrays are immutable, so
        each train step produces fresh arrays — functional-update analog of
        the reference's in-place staging refresh)."""
        flat, _ = flatten_state_dict(state_dict)
        for key in self._sources:
            self._sources[key] = flat[key]
        for key in self._device_keys:
            self._device_arrays[key] = flat[key]

    async def close(self) -> None:
        await self.server.stop()
        for seg in self.segments.values():
            seg.unlink()
        self.segments.clear()
        self.server.buffers.clear()


def _full_slice(shape) -> TensorSlice:
    return TensorSlice(
        offsets=(0,) * len(shape),
        local_shape=tuple(shape),
        global_shape=tuple(shape),
        coordinates=(),
        mesh_shape=(),
    )


def _aliases(a: np.ndarray, b: np.ndarray) -> bool:
    """Same memory AND same interpretation. Layout must match too: a
    transposed/reinterpreted view of the staging buffer is a real publish
    request (the transform must be materialized), not an alias to skip."""
    try:
        return (
            a.__array_interface__["data"][0] == b.__array_interface__["data"][0]
            and a.nbytes == b.nbytes
            and a.shape == b.shape
            and a.dtype == b.dtype
            and a.strides == b.strides
        )
    except (AttributeError, TypeError):
        return False


def _is_floating(arr) -> bool:
    return np.issubdtype(np.asarray(arr).dtype, np.floating) or "bfloat16" in str(
        getattr(arr, "dtype", "")
    )


# --------------------------------------------------------------------------
# dest side
# --------------------------------------------------------------------------


@dataclass
class _TransferOp:
    """One planned read: pull ``handle``'s bytes, slice-copy into every dest
    region it overlaps (reference plan semantics,
    direct_weight_sync.py:221-317)."""

    flat_key: str
    handle: WeightHandle
    region: Box  # global region this op covers


class DirectWeightSyncDest:
    def __init__(self, pool_size: int = 4) -> None:
        self.pool_size = pool_size
        self._plan: Optional[list[_TransferOp]] = None
        self._plan_sig: Optional[tuple] = None
        self._conns: dict[tuple[str, int], dict] = {}
        self._segments: dict[str, shm.ShmSegment] = {}
        self._lock = asyncio.Lock()

    # ---- plan -------------------------------------------------------------

    def _build_plan(
        self,
        all_handles: dict[str, list[WeightHandle]],
        dest_flat: dict[str, Any],
    ) -> list[_TransferOp]:
        plan: list[_TransferOp] = []
        for flat_key, target in dest_flat.items():
            if not _is_tensor_like(target):
                continue
            handles = all_handles.get(flat_key)
            if handles is None:
                raise KeyError(
                    f"dest state dict expects {flat_key!r} but the source "
                    "published no handle for it"
                )
            for want in _target_slices(target):
                covered: set[Box] = set()
                covered_elems = 0
                for handle in handles:
                    inter = intersect_boxes(handle.tensor_slice.box, want.box)
                    if inter is None or inter in covered:
                        continue  # replicated-shard dedup (reference :247-261)
                    covered.add(inter)
                    covered_elems += inter.size
                    plan.append(_TransferOp(flat_key, handle, inter))
                if covered_elems < want.box.size:
                    # Returning np.empty garbage for uncovered regions would
                    # silently corrupt weights — fail loudly instead.
                    raise ValueError(
                        f"source shards cover only {covered_elems} of "
                        f"{want.box.size} elements of {flat_key!r} region "
                        f"{want.box}"
                    )
        return plan

    # ---- pull -------------------------------------------------------------

    async def pull(
        self,
        all_handles: dict[str, list[WeightHandle]],
        dest_state_dict: Any,
    ) -> Any:
        """Concurrently pull every planned region and rebuild the dest dict.
        The plan is cached and reused while the handle/dest signature is
        unchanged (reference cached-plan invariant)."""
        tracker = LatencyTracker("direct_pull")
        dest_flat, mapping = flatten_state_dict(dest_state_dict)
        # The signature must cover the dest layouts, not just key names — a
        # changed target sharding must rebuild the plan (and re-run its
        # coverage validation), never reuse a stale one.
        target_sig = tuple(
            sorted(
                (
                    k,
                    tuple(
                        (ts.offsets, ts.local_shape, ts.global_shape)
                        for ts in _target_slices(v)
                    ),
                )
                for k, v in dest_flat.items()
                if _is_tensor_like(v)
            )
        )
        handle_sig = tuple(
            sorted(
                (
                    k,
                    tuple(
                        sorted(
                            (h.tensor_slice.offsets, h.tensor_slice.local_shape)
                            for h in v
                        )
                    ),
                )
                for k, v in all_handles.items()
            )
        )
        sig = (handle_sig, target_sig)
        if self._plan is None or self._plan_sig != sig:
            self._plan = self._build_plan(all_handles, dest_flat)
            self._plan_sig = sig
        tracker.track_step("plan")

        # Host landing buffers per (flat_key, target slice). A numpy target
        # with one full-array slice IS its own landing buffer — ops write
        # straight into destination memory (the reference's exact-match
        # zero-extra-copy path, direct_weight_sync.py:221-247).
        landings: dict[str, list[tuple[TensorSlice, np.ndarray]]] = {}
        inplace_targets: set[str] = set()
        from torchstore_tpu.client import Shard as _Shard

        for flat_key, target in dest_flat.items():
            if not _is_tensor_like(target):
                continue
            wants = _target_slices(target)
            # Shard targets land into their provided buffer; plain ndarray
            # targets into themselves (both in place, no extra copy).
            buf = target.data if isinstance(target, _Shard) else target
            if (
                isinstance(buf, np.ndarray)
                and len(wants) == 1
                and tuple(buf.shape) == wants[0].local_shape
                and buf.flags["C_CONTIGUOUS"]
                and buf.flags["WRITEABLE"]
            ):
                landings[flat_key] = [(wants[0], buf)]
                inplace_targets.add(flat_key)
            else:
                if isinstance(target, _Shard) and target.data is None:
                    # Buffer-less region pull: dtype comes from the source.
                    dtype = all_handles[flat_key][0].meta.np_dtype
                else:
                    dtype = _np_dtype_of(target)
                landings[flat_key] = [
                    (want, np.empty(want.local_shape, dtype)) for want in wants
                ]

        # Each source shard is read ONCE per pull, however many dest regions
        # overlap it — and only the row range its ops actually need (ranged
        # reads cut DCN bytes when a pull touches part of a shard). Keyed by
        # (host, port, buffer_id): buffer ids are per-SOURCE counters, so two
        # ranks' shards share ids and a bare-id key would collapse them.
        by_handle: dict[tuple, tuple[WeightHandle, list[_TransferOp]]] = {}
        for op in self._plan:
            hkey = (op.handle.hostname, op.handle.port, op.handle.buffer_id)
            by_handle.setdefault(hkey, (op.handle, []))[1].append(op)
        row_ranges = {
            hkey: _row_range(handle, ops)
            for hkey, (handle, ops) in by_handle.items()
        }
        reads = await asyncio.gather(
            *(
                self._read_shard(handle, row_ranges[hkey])
                for hkey, (handle, _) in by_handle.items()
            )
        )
        shard_raws = dict(zip(by_handle.keys(), reads))
        ops_bytes = 0
        for hkey, (arr, row0) in shard_raws.items():
            ops_bytes += arr.nbytes
            for op in by_handle[hkey][1]:
                self._apply_op(op, arr, row0, landings)
        tracker.track_step("reads", ops_bytes)

        out_flat = dict(dest_flat)
        for flat_key, parts in landings.items():
            if flat_key in inplace_targets:
                out_flat[flat_key] = parts[0][1]  # already the target array
            else:
                out_flat[flat_key] = _rebuild(dest_flat[flat_key], parts)
        tracker.track_step("rebuild")
        tracker.log_summary(level=20)
        from torchstore_tpu.state_dict_utils import unflatten_state_dict

        return unflatten_state_dict(out_flat, mapping)

    def _apply_op(
        self, op: _TransferOp, shard_arr: np.ndarray, row0: int, landings
    ) -> None:
        """``shard_arr`` covers shard rows [row0, row0+len) of the handle's
        slice (row0 > 0 for ranged reads)."""
        for want, buf in landings[op.flat_key]:
            inter = intersect_boxes(op.region, want.box)
            if inter is None:
                continue
            shard_offsets = op.handle.tensor_slice.offsets
            rel_src = tuple(
                slice(
                    o - so - (row0 if d == 0 else 0),
                    o - so - (row0 if d == 0 else 0) + s,
                )
                for d, (o, so, s) in enumerate(
                    zip(inter.offsets, shard_offsets, inter.shape)
                )
            )
            view = get_destination_view(
                buf, want.box, inter, require_contiguous=False
            )
            copy_into(view, shard_arr[rel_src])

    async def _get_conn(self, host: str, port: int):
        """A pooled (reader, writer, lock) to a source's peer server — a
        small pool per source so concurrent reads overlap on the wire
        instead of serializing behind one connection."""
        key = (host, port)
        async with self._lock:
            pool = self._conns.get(key)
            if pool is None:
                pool = {"conns": [], "rr": 0}
                self._conns[key] = pool
            if len(pool["conns"]) < self.pool_size:
                reader, writer = await asyncio.wait_for(
                    asyncio.open_connection(host, port), timeout=30
                )
                from torchstore_tpu.runtime.auth import client_authenticate

                await client_authenticate(reader, writer)
                conn = (reader, writer, asyncio.Lock())
                pool["conns"].append(conn)
            else:
                conn = pool["conns"][pool["rr"] % len(pool["conns"])]
                pool["rr"] += 1
        return conn

    # ---- device (ICI) path ------------------------------------------------

    async def pull_device(self, device_info: dict, dest_state_dict: Any) -> Any:
        """One-hop device pull: ask the source to stage its current arrays,
        pull them device-to-device through the transfer engine, then land
        into the dest targets (resharding locally where the target sharding
        differs — XLA moves the shards over ICI)."""
        from torchstore_tpu.transport import device_transfer as dt

        tracker = LatencyTracker("direct_pull_device")
        dest_flat, mapping = flatten_state_dict(dest_state_dict)
        host = (
            "127.0.0.1"
            if device_info["hostname"] == get_hostname()
            else device_info["hostname"]
        )
        reader, writer, lock = await self._get_conn(
            host, device_info["control_port"]
        )
        async with lock:
            writer.write(_READ_REQ.pack(_STAGE_DEVICE, 0, 0))
            await writer.drain()
            (length,) = _READ_RESP.unpack(await reader.readexactly(_READ_RESP.size))
            if length == _ERR:
                raise KeyError("source has no device-mode registration")
            (uid,) = _U64.unpack(await reader.readexactly(_U64.size))
        tracker.track_step("stage")
        keys = device_info["keys"]
        specs = [device_info["specs"][k] for k in keys]
        engine = dt.DeviceTransferEngine.get()
        arrays = engine.pull(device_info["address"], uid, specs)
        by_key = dict(zip(keys, arrays))
        tracker.track_step(
            "pull",
            sum(
                int(np.prod(s.shape))
                * TensorMeta(shape=(), dtype=s.dtype).np_dtype.itemsize
                for s in specs
            ),
        )
        out_flat = dict(dest_flat)
        for flat_key, target in dest_flat.items():
            if not _is_tensor_like(target):
                continue
            arr = by_key.get(flat_key)
            if arr is None:
                raise KeyError(
                    f"dest state dict expects {flat_key!r} but the source "
                    "published no device entry for it"
                )
            out_flat[flat_key] = _land_device(target, arr)
        tracker.track_step("land")
        tracker.log_summary(level=20)
        from torchstore_tpu.state_dict_utils import unflatten_state_dict

        return unflatten_state_dict(out_flat, mapping)

    async def _read_shard(
        self, handle: WeightHandle, row_range: Optional[tuple[int, int]] = None
    ) -> tuple[np.ndarray, int]:
        """One-hop read of a source buffer: SHM attach on the same host, TCP
        (ranged when ``row_range`` is set) across hosts. Returns
        ``(shard-shaped array rows, first_row)``."""
        shape = handle.meta.shape
        if handle.shm_name is not None and handle.hostname == get_hostname():
            # Attach is free — no transfer to range.
            seg = self._segments.get(handle.shm_name)
            if seg is None:
                seg = shm.ShmSegment.attach(handle.shm_name, max(handle.meta.nbytes, 1))
                self._segments[handle.shm_name] = seg
            return np.asarray(seg.view(handle.meta)).reshape(shape), 0
        # Same-host TCP reads dial loopback (the container hostname may not
        # route back to this process); cross-host uses the advertised name.
        host = (
            "127.0.0.1" if handle.hostname == get_hostname() else handle.hostname
        )
        reader, writer, lock = await self._get_conn(host, handle.port)
        row_bytes = (
            handle.meta.nbytes // shape[0] if shape and shape[0] else handle.meta.nbytes
        )
        if row_range is not None and shape:
            r0, r1 = row_range
            offset, want_len = r0 * row_bytes, (r1 - r0) * row_bytes
            out_shape = (r1 - r0,) + tuple(shape[1:])
        else:
            r0, offset, want_len = 0, 0, handle.meta.nbytes
            out_shape = tuple(shape)
        async with lock:
            writer.write(_READ_REQ.pack(handle.buffer_id, offset, want_len))
            await writer.drain()
            (length,) = _READ_RESP.unpack(await reader.readexactly(_READ_RESP.size))
            if length == _ERR:
                raise KeyError(
                    f"source no longer has buffer {handle.buffer_id} "
                    f"(rank {handle.source_rank})"
                )
            raw = await reader.readexactly(length)
        arr = np.frombuffer(bytearray(raw), dtype=handle.meta.np_dtype)
        return arr.reshape(out_shape), r0

    async def close(self) -> None:
        for pool in self._conns.values():
            for _, writer, _ in pool["conns"]:
                try:
                    writer.close()
                except Exception:
                    pass
        self._conns.clear()
        for seg in self._segments.values():
            seg.close()
        self._segments.clear()


# --------------------------------------------------------------------------
# helpers shared by plan/pull
# --------------------------------------------------------------------------


def _row_range(
    handle: WeightHandle, ops: list[_TransferOp]
) -> Optional[tuple[int, int]]:
    """Shard-local dim-0 row range covering every op, or None for a full
    read. Ranging applies only when each op's region spans the shard's full
    extent in every trailing dim (then rows are a contiguous byte range —
    the protocol's offset/length supports it directly)."""
    ts = handle.tensor_slice
    if not ts.local_shape:
        return None
    lo, hi = None, None
    for op in ops:
        for d in range(1, len(ts.local_shape)):
            if (
                op.region.offsets[d] != ts.offsets[d]
                or op.region.shape[d] != ts.local_shape[d]
            ):
                return None
        r0 = op.region.offsets[0] - ts.offsets[0]
        r1 = r0 + op.region.shape[0]
        lo = r0 if lo is None else min(lo, r0)
        hi = r1 if hi is None else max(hi, r1)
    if lo == 0 and hi == ts.local_shape[0]:
        return None  # full shard anyway
    return lo, hi


def _is_tensor_like(value) -> bool:
    from torchstore_tpu.client import Shard

    return (
        isinstance(value, (np.ndarray, Shard))
        or shd.is_jax_array(value)
        or shd.is_sharded_spec(value)
        or shd.is_plain_spec(value)
    )


def _is_tensor_leaf(value) -> bool:
    """Source-side leaf classification (register): array-valued leaves."""
    return isinstance(value, np.ndarray) or shd.is_jax_array(value)


def _land_device(target, arr):
    """Land a pulled device array into a dest target: reshard on device for
    jax targets (device_put compiles to ICI collectives), copy to host
    memory for numpy/Shard targets."""
    import jax

    from torchstore_tpu.client import Shard as _Shard

    if (
        shd.is_jax_array(target)
        or shd.is_sharded_spec(target)
        or shd.is_plain_spec(target)
    ):
        if tuple(arr.shape) != tuple(target.shape):
            raise ValueError(
                f"pulled shape {tuple(arr.shape)} != target shape "
                f"{tuple(target.shape)} (source re-published under a "
                "different shape?)"
            )
        want_dtype = getattr(target, "dtype", None)
        if want_dtype is not None and arr.dtype != want_dtype:
            arr = arr.astype(want_dtype)
        sharding = getattr(target, "sharding", None)
        if sharding is not None and sharding != arr.sharding:
            arr = jax.device_put(arr, sharding)
        return arr
    if isinstance(target, _Shard):
        region = tuple(
            slice(o, o + s)
            for o, s in zip(target.tensor_slice.offsets, target.tensor_slice.local_shape)
        )
        part = np.asarray(arr[region])
        if target.data is not None:
            np.copyto(target.data, part)
            return target.data
        return part
    # numpy target: full copy in place.
    np.copyto(target, np.asarray(arr))
    return target


def _np_dtype_of(value) -> np.dtype:
    from torchstore_tpu.client import Shard

    if isinstance(value, Shard):
        value = value.data
    # Avoids materializing jax arrays on host just to learn their dtype.
    return TensorMeta(shape=(), dtype=str(value.dtype)).np_dtype


def _target_slices(value) -> list[TensorSlice]:
    from torchstore_tpu.client import Shard

    if isinstance(value, Shard):
        # Explicit region target: pull only this slice of the global space
        # (SPMD ranks syncing their own shard).
        return [value.tensor_slice]
    if shd.is_jax_array(value) or shd.is_sharded_spec(value):
        return [ts for _, ts in shd.target_slices(value)]
    # numpy arrays and sharding-less ShapeDtypeStructs: one full slice.
    return [_full_slice(value.shape)]


def _rebuild(target, parts: list[tuple[TensorSlice, np.ndarray]]):
    from torchstore_tpu.client import Shard

    if isinstance(target, Shard):
        ((_, arr),) = parts
        if target.data is not None:
            np.copyto(target.data, arr)
            return target.data
        return arr
    if shd.is_jax_array(target) or shd.is_sharded_spec(target):
        devs = [dev for dev, _ in shd.target_slices(target)]
        return shd.build_array(target, [(d, arr) for d, (_, arr) in zip(devs, parts)])
    if shd.is_plain_spec(target):
        import jax.numpy as jnp

        ((_, arr),) = parts
        return jnp.asarray(arr, dtype=target.dtype)
    # numpy target: single full slice, filled in place.
    ((_, arr),) = parts
    np.copyto(target, arr)
    return target
