"""Data plane: ``StorageVolume`` actor + ``InMemoryStore``.

TPU-native equivalent of /root/reference/torchstore/storage_volume.py:51-407.
A volume is one actor process holding host-memory entries:

    key -> {"type": "tensor",  "tensor": np.ndarray}
         | {"type": "sharded", "shards": {coords: {"slice": TensorSlice,
                                                   "tensor": np.ndarray}}}
         | {"type": "object",  "obj": Any}

Volumes are jax-free (host numpy only) so they spawn fast and never touch the
TPU runtime; device arrays are converted at the client boundary. Transfer
mechanics live entirely in the transport buffer that rides each RPC.
"""

from __future__ import annotations

import os
import time
from abc import ABC, abstractmethod
from typing import Any, Optional

import numpy as np

from torchstore_tpu import faults
from torchstore_tpu.logging import get_logger
from torchstore_tpu.observability import detect as obs_detect
from torchstore_tpu.observability import history as obs_history
from torchstore_tpu.observability import ledger as obs_ledger
from torchstore_tpu.observability import metrics as obs_metrics
from torchstore_tpu.observability import profile as obs_profile
from torchstore_tpu.observability import recorder as obs_recorder
from torchstore_tpu.observability import timeline as obs_timeline
from torchstore_tpu.runtime import Actor, endpoint
from torchstore_tpu.transport.buffers import TransportBuffer, TransportContext
from torchstore_tpu.transport.types import Request, TensorMeta, TensorSlice
from torchstore_tpu.utils import get_hostname, maybe_await

logger = get_logger("torchstore_tpu.storage_volume")

# Data-plane gauges (volume process; maintained incrementally per affected
# key so the hot path never re-walks the whole store).
_RESIDENT_BYTES = obs_metrics.gauge(
    "ts_volume_resident_bytes", "Tensor bytes resident in this volume"
)
_ENTRIES = obs_metrics.gauge(
    "ts_volume_entries", "Entries (keys) resident in this volume"
)
_PUT_OPS = obs_metrics.counter(
    "ts_volume_put_ops_total", "Put RPCs served by this volume"
)
_GET_OPS = obs_metrics.counter(
    "ts_volume_get_ops_total", "Get RPCs served by this volume"
)
# Overload signal (ts.slo_report): landings currently holding the volume-
# wide write bracket open. A sustained non-zero floor means the landing
# pool (or a wedged faultpoint) is the queue building up.
_LANDING_INFLIGHT = obs_metrics.gauge(
    "ts_landing_inflight", "Open landing brackets on this volume"
)


class KeyNotFoundError(KeyError):
    pass


class PartialShardError(KeyError):
    pass


class StorageImpl(ABC):
    """Pluggable storage backend behind a volume (reference
    /root/reference/torchstore/storage_volume.py:102-150)."""

    @abstractmethod
    def extract_existing(self, metas: list[Request]) -> dict[int, np.ndarray]: ...

    @abstractmethod
    def store(self, metas: list[Request], values: dict[int, Any]) -> None: ...

    @abstractmethod
    def get_data(self, meta: Request) -> Any: ...

    @abstractmethod
    def get_meta(self, meta: Request) -> Any: ...

    @abstractmethod
    def delete(self, key: str) -> bool: ...

    @abstractmethod
    def reset(self) -> None: ...


class InMemoryStore(StorageImpl):
    def __init__(self) -> None:
        self.kv: dict[str, dict] = {}

    # ---- write path ------------------------------------------------------

    def _check_type(self, key: str, entry: dict, incoming: str) -> None:
        if entry["type"] != incoming:
            raise ValueError(
                f"key {key!r} already stored as {entry['type']!r}; cannot "
                f"overwrite with {incoming!r} (delete first)"
            )

    def extract_existing(self, metas: list[Request]) -> dict[int, np.ndarray]:
        """Existing stored arrays for in-place overwrite: a second put of the
        same key writes into the same memory so SHM/bulk clients aliasing the
        buffer observe updates (reference invariant 6,
        /root/reference/torchstore/storage_volume.py:161-207)."""
        out: dict[int, np.ndarray] = {}
        for idx, meta in enumerate(metas):
            entry = self.kv.get(meta.key)
            if entry is None:
                continue
            incoming = (
                "object"
                if meta.is_object
                else ("sharded" if meta.tensor_slice is not None else "tensor")
            )
            self._check_type(meta.key, entry, incoming)
            if incoming == "tensor":
                out[idx] = entry["tensor"]
            elif incoming == "sharded":
                shard = entry["shards"].get(meta.tensor_slice.coordinates)
                if shard is not None and (
                    shard["slice"].local_shape == meta.tensor_slice.local_shape
                ):
                    out[idx] = shard["tensor"]
        return out

    def store(self, metas: list[Request], values: dict[int, Any]) -> None:
        for idx, meta in enumerate(metas):
            if idx not in values:
                raise ValueError(f"transport produced no value for {meta.key!r}")
            value = values[idx]
            if meta.is_object:
                self.kv[meta.key] = {"type": "object", "obj": value}
            elif meta.tensor_slice is not None:
                entry = self.kv.setdefault(meta.key, {"type": "sharded", "shards": {}})
                self._check_type(meta.key, entry, "sharded")
                ts = meta.tensor_slice
                _prune_superseded_shards(entry["shards"], ts)
                entry["shards"][ts.coordinates] = {
                    "slice": ts,
                    "tensor": np.asarray(value),
                }
            else:
                entry = self.kv.get(meta.key)
                if entry is not None:
                    self._check_type(meta.key, entry, "tensor")
                self.kv[meta.key] = {"type": "tensor", "tensor": np.asarray(value)}

    # ---- read path -------------------------------------------------------

    def _entry(self, key: str) -> dict:
        entry = self.kv.get(key)
        if entry is None:
            raise KeyNotFoundError(f"Key {key!r} not found in storage volume")
        return entry

    def get_data(self, meta: Request) -> Any:
        entry = self._entry(meta.key)
        if entry["type"] == "object":
            return entry["obj"]
        if meta.tensor_slice is None:
            if entry["type"] == "tensor":
                return entry["tensor"]
            shards = entry["shards"]
            if len(shards) == 1:
                (shard,) = shards.values()
                if shard["slice"].is_full():
                    return shard["tensor"]
            raise PartialShardError(
                f"Key {meta.key!r} is sharded across coordinates "
                f"{sorted(shards)}; a slice request is required"
            )
        box = meta.tensor_slice.box
        if entry["type"] == "tensor":
            # Slice extraction from a full tensor
            # (/root/reference/torchstore/storage_volume.py:220-237).
            tensor = entry["tensor"]
            if not TensorSlice(
                offsets=(0,) * tensor.ndim,
                local_shape=tensor.shape,
                global_shape=tensor.shape,
                coordinates=(),
                mesh_shape=(),
            ).box.contains(box):
                raise PartialShardError(
                    f"requested region {box} outside stored tensor "
                    f"{tensor.shape} for key {meta.key!r}"
                )
            return tensor[box.to_index()]
        shard = entry["shards"].get(meta.tensor_slice.coordinates)
        if shard is None:
            raise PartialShardError(
                f"no shard at coordinates {meta.tensor_slice.coordinates} "
                f"for key {meta.key!r}"
            )
        stored: TensorSlice = shard["slice"]
        if not stored.box.contains(box):
            # Volumes serve sub-slices of stored shards only when fully
            # contained (/root/reference/torchstore/storage_volume.py:239-280);
            # the client's planner guarantees this by construction.
            raise PartialShardError(
                f"requested region {box} not contained in stored shard "
                f"{stored.box} for key {meta.key!r}"
            )
        rel = tuple(
            slice(o - so, o - so + s)
            for o, so, s in zip(box.offsets, stored.offsets, box.shape)
        )
        return shard["tensor"][rel]

    def get_meta(self, meta: Request) -> Any:
        entry = self._entry(meta.key)
        if entry["type"] == "object":
            return "obj"
        data = self.get_data(meta)
        return TensorMeta.of(data)

    def delete(self, key: str) -> bool:
        return self.kv.pop(key, None) is not None

    def reset(self) -> None:
        self.kv.clear()


def _prune_superseded_shards(shards: dict, incoming: TensorSlice) -> list[tuple]:
    """Drop shards whose layout (mesh shape / global shape) differs from an
    incoming re-publish. Without this, a key re-published under a new
    sharding keeps old-layout shards alongside new ones: the commit check
    then passes on a mixed coords set and gets assemble overlapping
    stale+fresh slices — silent weight corruption (mirrors the controller's
    stale-layout invalidation, controller.py notify_put_batch)."""
    stale = [
        coords
        for coords, shard in shards.items()
        if shard["slice"].mesh_shape != incoming.mesh_shape
        or shard["slice"].global_shape != incoming.global_shape
    ]
    for coords in stale:
        del shards[coords]
    return stale


class StorageVolume(Actor):
    """Data-plane actor (/root/reference/torchstore/storage_volume.py:27-99)."""

    def __init__(self, strategy=None, storage: Optional[StorageImpl] = None):
        # Explicit id override: repair spawns a REPLACEMENT volume that must
        # adopt the dead volume's id regardless of strategy env derivation.
        forced_id = os.environ.get("TORCHSTORE_TPU_VOLUME_ID")
        if forced_id:
            self.volume_id = forced_id
        elif strategy is not None:
            self.volume_id = strategy.get_volume_id()
        else:
            self.volume_id = os.environ.get("RANK", "0")
        if storage is None:
            storage_dir = os.environ.get("TORCHSTORE_TPU_STORAGE_DIR")
            if storage_dir:
                # Durable backend: entries persist under
                # <dir>/<volume_id> and survive volume restarts.
                from torchstore_tpu.storage_utils.file_store import FileBackedStore

                storage = FileBackedStore(
                    os.path.join(storage_dir, str(self.volume_id))
                )
            else:
                storage = InMemoryStore()
        self.store = storage
        self.ctx = TransportContext()
        # Volume-wide landing bracket: ``_landing_inflight`` counts open
        # landings (puts/pulls/deletes interleave at awaits — actor
        # endpoints dispatch as independent tasks, so parity of a shared
        # counter says nothing); ``_landing_stamp`` only ever increases,
        # bumped at every bracket open AND close so an unchanged stamp
        # plus inflight==0 at both ends of a doorbell pack proves no
        # landing touched entries meanwhile. Per-entry precision lives in
        # the SHM stamp table; this pair covers entries no stamp table
        # describes (bulk/rpc-stored plain arrays).
        self._landing_stamp = 0
        self._landing_inflight = 0
        # Per-key write generation: microsecond timestamp (strictly
        # monotonic per key via max(prev+1, now)). Assigned on every
        # successful put, echoed to the client in the put reply, forwarded
        # to the controller's index — the token that makes stale-replica
        # reclaims conditional (delete_if_unchanged): a reclaim may only
        # delete bytes whose generation is <= the generation the controller
        # indexed, so a fresh put racing the reclaim always survives.
        # Timestamps (not counters) stay comparable across volume restarts
        # on durable backends.
        self._write_gens: dict[str, int] = {}
        # Incremental resident-bytes accounting: seeded from whatever the
        # backend already holds (durable volumes recover entries at init),
        # then adjusted by per-key deltas on every put/delete.
        self._resident_bytes = sum(
            self._entry_nbytes(key) for key in getattr(self.store, "kv", {})
        )
        # Spill tier (torchstore_tpu/tiering/spill.py): cold version groups
        # demote to disk under the watermark policy, gets on spilled keys
        # fault back in through this volume's normal serve path. None when
        # TORCHSTORE_TPU_TIER_ENABLED is unset — the warm path then pays
        # exactly one attribute check.
        self._tier = None
        from torchstore_tpu.tiering import spill as tiering_spill

        if tiering_spill.enabled():
            self._tier = tiering_spill.SpillTier(self.volume_id)
        # Blob cold tier (torchstore_tpu/tiering/blob.py): the third rung
        # below disk. Disk-spilled entries demote further into the emulated
        # object store on autoscale ``blob_demote`` decisions (blob_sweep),
        # blob_archive checkpoints everything for scale-to-zero, and
        # archived keys fault back in through the same get-RPC bracket as
        # the disk tier. None unless TORCHSTORE_TPU_BLOB_ENABLED is set —
        # the warm path then pays exactly one attribute check.
        self._blob = None
        from torchstore_tpu.tiering import blob as tiering_blob

        if tiering_blob.enabled():
            self._blob = tiering_blob.BlobTier(self.volume_id)
        # Serializes spill/fault-in/blob mutations of the tier bookkeeping
        # across endpoint tasks (all are cold-path; the warm path never
        # touches the lock).
        import asyncio

        self._tier_lock = asyncio.Lock()
        self._publish_residency()
        from torchstore_tpu import native
        from torchstore_tpu.transport import shared_memory

        native.get_lib()  # load (or wait for) the native data path at startup
        if shared_memory.is_available():
            # Crashed processes leave /dev/shm segments behind; sweep any
            # whose creator pid is gone before this volume starts serving.
            shared_memory.reap_orphaned_segments()
        # One-sided cross-host gets: doorbell frames on the bulk socket read
        # this volume's store directly (same process, no RPC dispatch).
        self._install_doorbell_hook()
        # Unclean-exit post-mortem: if this process dies with faults/errors
        # in its flight ring, the last seconds land on disk at exit.
        obs_recorder.recorder().arm_exit_dump()

    def _install_doorbell_hook(self) -> None:
        """Point the bulk server's doorbell at this volume's store. Eager is
        free — the BulkServer only binds a listener at the first bulk
        handshake. Re-run after reset(): ctx.clear() drops cache instances."""
        from torchstore_tpu.transport.bulk import BulkServerCache

        self.ctx.get_cache(BulkServerCache).server.doorbell_volume = self

    def _notify_push(self, gens: dict[str, int]) -> None:
        """Freshly committed write generations: kick the bulk server's
        push-on-publish pump so subscribed plans stream to their clients
        AT WATERMARK TIME (transport/bulk.py) instead of waiting for the
        next doorbell ring."""
        from torchstore_tpu.transport.bulk import BulkServerCache

        self.ctx.get_cache(BulkServerCache).server.notify_landed(gens)

    @endpoint
    async def get_id(self) -> dict:
        return {
            "volume_id": self.volume_id,
            "hostname": get_hostname(),
            "pid": os.getpid(),
        }

    @endpoint
    async def handshake(
        self, buffer: TransportBuffer, metas: list[Request], op: str
    ) -> Any:
        await faults.afire("volume.handshake")
        existing = self.store.extract_existing(metas) if op == "put" else {}
        return await maybe_await(buffer.recv_handshake(self.ctx, metas, existing, op))

    def _entry_nbytes(self, key: str) -> int:
        entry = getattr(self.store, "kv", {}).get(key)
        if entry is None:
            return 0
        if entry.get("type") == "tensor":
            return int(getattr(entry.get("tensor"), "nbytes", 0))
        if entry.get("type") == "sharded":
            return sum(
                int(getattr(shard.get("tensor"), "nbytes", 0))
                for shard in entry.get("shards", {}).values()
            )
        return 0

    def _publish_residency(self) -> None:
        _RESIDENT_BYTES.set(self._resident_bytes, volume=self.volume_id)
        _ENTRIES.set(len(getattr(self.store, "kv", {})), volume=self.volume_id)
        if self._tier is not None:
            self._tier.publish_gauges(self._resident_bytes)

    def _apply_residency_delta(self, keys, before: int) -> None:
        after = sum(self._entry_nbytes(k) for k in keys)
        self._resident_bytes += after - before
        self._publish_residency()

    def _bump_write_gens(self, metas: list[Request]) -> dict[str, int]:
        now = int(time.time() * 1e6)
        gens: dict[str, int] = {}
        for meta in metas:
            prev = self._write_gens.get(meta.key, 0)
            gen = max(prev + 1, now)
            self._write_gens[meta.key] = gen
            gens[meta.key] = gen
        return gens

    @staticmethod
    def _meta_nbytes(meta: Request) -> int:
        if meta.tensor_meta is not None:
            return int(meta.tensor_meta.nbytes)
        return int(meta.nbytes)

    # ---- one-sided stamp brackets ----------------------------------------

    def _shm_cache(self):
        from torchstore_tpu.transport.shared_memory import ShmServerCache

        return self.ctx.peek(ShmServerCache)

    @staticmethod
    def _stamp_pairs(metas: list[Request]) -> list[tuple]:
        return [
            (
                meta.key,
                meta.tensor_slice.coordinates if meta.tensor_slice else None,
            )
            for meta in metas
        ]

    def _landing_open(self) -> None:
        """Open the volume-wide landing bracket: doorbell serves racing
        this landing see inflight != 0 (busy) or a moved stamp (torn)."""
        self._landing_inflight += 1
        self._landing_stamp += 1
        _LANDING_INFLIGHT.set(self._landing_inflight, volume=self.volume_id)

    def _landing_close(self) -> None:
        self._landing_inflight -= 1
        self._landing_stamp += 1
        _LANDING_INFLIGHT.set(self._landing_inflight, volume=self.volume_id)

    async def _begin_landing(self, pairs: list[tuple]) -> None:
        """Open the one-sided write bracket: per-entry seqlock stamps go odd
        for every existing entry about to be (re)written — BEFORE any
        transport lands bytes that could alias entry memory (the bulk/rpc
        in-place overwrite paths) — and the volume-wide landing bracket
        opens so doorbell serves in flight declare themselves torn. The
        ``shm.landing_stamp`` faultpoint fires inside the bracket (async:
        a delay/wedge holds entries visibly write-in-flight without
        freezing the event loop's RPC fallback path)."""
        cache = self._shm_cache()
        if cache is not None:
            cache.begin_writes(pairs)
        try:
            self._landing_open()
            await faults.afire("shm.landing_stamp")
        except BaseException:
            # A raise-action fault (or cancellation during a delay/wedge)
            # escapes before the caller's try/finally is armed: close the
            # bracket here or inflight/nesting leak forever — every future
            # doorbell answers busy and stamps never settle even again.
            self._end_landing(pairs)
            raise

    def _end_landing(self, pairs: list[tuple]) -> None:
        """Close the bracket: written entries settle at their next EVEN
        generation (fresh entries get slots) strictly before the put RPC
        dispatch returns — i.e. before any retired segment could be
        re-offered to another writer, which is what makes a one-sided
        reader's post-copy re-check sound. Runs in a finally: a FAILED
        landing also settles (at a new generation), so cached plans built
        against the old bytes fall back instead of wedging odd forever."""
        cache = self._shm_cache()
        if cache is not None:
            cache.end_writes(pairs)
        self._landing_close()

    # ---- spill tier (torchstore_tpu/tiering/spill.py) --------------------

    async def _tier_fault_in(self, metas: list[Request], reason: str) -> None:
        """Promote any SPILLED keys among ``metas`` back into the memory
        tier before they are served: load the crash-safe disk copy, land it
        through the shared landing pool bracketed by the volume's landing
        stamps (one-sided readers and doorbells racing the promotion see a
        busy/moved bracket and fall back to the RPC path — never a torn or
        half-faulted tensor), store it, then drop the disk copy. The warm
        path exits on the first check: one attribute + one dict read."""
        tier = self._tier
        if tier is None or not tier.spilled:
            return
        keys = [meta.key for meta in metas if meta.key in tier.spilled]
        if not keys:
            return
        async with self._tier_lock:
            for key in dict.fromkeys(keys):
                if key not in tier.spilled:
                    continue  # a concurrent fault-in already promoted it
                await faults.afire("volume.fault_in")
                try:
                    dmetas, dvalues = tier.load(key)
                except KeyError:
                    continue
                await self._promote_entry(key, dmetas, dvalues)
                tier.faulted_in(key, reason)
        self._publish_residency()

    async def _promote_entry(
        self, key: str, dmetas: list[Request], dvalues: dict[int, Any]
    ) -> None:
        """Land a colder-tier entry back into the memory tier through the
        shared landing pool, bracketed by the volume's landing stamps
        (shared by the disk and blob fault-in paths — one-sided readers
        racing the promotion see busy/moved and fall back, never a torn
        tensor). Caller holds ``_tier_lock`` and owns the tier-side
        bookkeeping (``faulted_in``/``restored``)."""
        from torchstore_tpu.transport import landing as landing_mod

        values: dict[int, Any] = {}
        copy_pairs = []
        for idx, _dmeta in enumerate(dmetas):
            val = dvalues[idx]
            if isinstance(val, np.ndarray) and val.size:
                dst = np.empty_like(val)
                copy_pairs.append((dst, val))
                values[idx] = dst
            else:
                values[idx] = val
        stamp_pairs = self._stamp_pairs(dmetas)
        before = self._entry_nbytes(key)
        await self._begin_landing(stamp_pairs)
        try:
            if copy_pairs:
                await landing_mod.land_async(copy_pairs, stage="fault_in")
            self.store.store(dmetas, values)
        finally:
            self._end_landing(stamp_pairs)
        self._apply_residency_delta([key], before)

    async def _blob_fault_in(self, metas: list[Request], reason: str) -> None:
        """Promote any BLOB-archived keys among ``metas`` back into the
        memory tier before they are served — the bottom rung of the same
        ladder as ``_tier_fault_in``, riding the identical landing
        bracket. Only keys whose SOLE copy lives in blob promote: an
        archived key still resident (or still on the disk tier, which
        ``_tier_fault_in`` just promoted) is a ``blob_archive`` checkpoint
        copy — re-landing it would pay a pointless blob round trip and,
        worse, let ``restored()`` destroy the durable copy the fleet
        manifest references. The warm path exits on the first check: one
        attribute + one dict read."""
        blob = self._blob
        if blob is None or not blob.archived:
            return
        kv = getattr(self.store, "kv", {})
        tier = self._tier

        def _blob_only(key: str) -> bool:
            return (
                key in blob.archived
                and key not in kv
                and (tier is None or key not in tier.spilled)
            )

        keys = [meta.key for meta in metas if _blob_only(meta.key)]
        if not keys:
            return
        async with self._tier_lock:
            for key in dict.fromkeys(keys):
                if not _blob_only(key):
                    continue  # a concurrent fault-in already promoted it
                await faults.afire("volume.fault_in")
                try:
                    dmetas, dvalues = blob.load(key)
                except KeyError:
                    continue
                await self._promote_entry(key, dmetas, dvalues)
                blob.restored(key, reason)
        self._publish_residency()

    def _tier_after_put(self, keys) -> None:
        """Post-landing tier bookkeeping for fresh writes: a stale disk
        (or blob) copy is garbage the moment new bytes land resident, and
        the write refreshes the version group's LRU clock."""
        if self._blob is not None and self._blob.archived:
            for key in keys:
                self._blob.discard(key)
        if self._tier is None:
            return
        for key in keys:
            self._tier.discard(key)
        self._tier.touch(keys)

    async def _tier_demote_key(self, tier, kv, key: str) -> bool:
        """Demote one resident key to the disk tier (caller holds
        ``_tier_lock``). Returns True when the key's memory copy was
        dropped; a failed spill leaves the entry fully resident and served.
        Shared by the watermark sweep and the control plane's named-key
        demotion so both paths cross the same faultpoint and landing
        bracket."""
        import asyncio

        entry = kv.get(key)
        if entry is None:
            return False
        before = self._entry_nbytes(key)
        try:
            # The faultpoint fires INSIDE the failure domain: a raise (or a
            # crash-safe write failure) aborts THIS key's demotion only —
            # the entry stays fully resident and served.
            await faults.afire("volume.spill")
            tier.spill(key, entry)
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 - a failed spill
            # must leave the entry fully resident + served
            logger.exception(
                "spill of %r failed; entry stays resident", key
            )
            return False
        # Drop the memory copy under the landing bracket: one-sided readers
        # of the retired entry fall back (stamps tombstone) instead of
        # tearing.
        self._landing_open()
        try:
            self.store.delete(key)
            self.ctx.delete_key(key)
        finally:
            self._landing_close()
        self._apply_residency_delta([key], before)
        return True

    @endpoint
    async def tier_cold_keys(
        self, pins: Optional[list[str]] = None, limit: int = 64
    ) -> list[str]:
        """Coldest resident keys, coldest version group first (``pins`` —
        leased ``channel/vN`` groups — are exempt), up to ``limit``. A
        read-only advisory view for the control plane's per-key demotion
        planner: no lock, no state change, just the LRU order the next
        watermark sweep would walk."""
        tier = self._tier
        if tier is None:
            return []
        kv = getattr(self.store, "kv", {})
        out: list[str] = []
        for _group, keys in tier.cold_groups(kv, pins or ()):
            for key in keys:
                if key in kv:
                    out.append(key)
                    if len(out) >= limit:
                        return out
        return out

    @endpoint
    async def tier_sweep(
        self,
        pins: Optional[list[str]] = None,
        demote_keys: Optional[list[str]] = None,
    ) -> dict:
        """Run one spill pass: when resident bytes exceed the HIGH
        watermark, demote cold version groups (LRU by access; ``pins`` —
        leased ``channel/vN`` groups — are exempt) until under LOW. Also
        drains the fault-in feedback list so the controller can flip index
        tier states back to resident. Called by the controller's background
        sweeper and by ``ts.tier_sweep()`` on demand.

        ``demote_keys`` names specific keys the control plane decided to
        spill regardless of the watermark (frequency-aware demotion: the
        policy engine picks per-key cold candidates from the traffic
        ledger instead of whole-version LRU). Named keys demote first,
        then the watermark pass runs as usual."""
        tier = self._tier
        if tier is None:
            return {"enabled": False, "spilled": [], "fault_ins": []}
        from torchstore_tpu.tiering import version_group

        spilled: list[str] = []
        pinned = set(pins or ())
        async with self._tier_lock:
            fault_ins = tier.drain_faulted()
            kv = getattr(self.store, "kv", {})
            for key in dict.fromkeys(demote_keys or ()):
                if key in tier.spilled:
                    continue
                vg = version_group(key)
                if vg is not None and f"{vg[0]}/v{vg[1]}" in pinned:
                    continue  # leased groups stay exempt on this path too
                if await self._tier_demote_key(tier, kv, key):
                    spilled.append(key)
            if self._resident_bytes > tier.high_bytes:
                for _group, keys in tier.cold_groups(kv, pins or ()):
                    if self._resident_bytes <= tier.low_bytes:
                        break
                    for key in keys:
                        if await self._tier_demote_key(tier, kv, key):
                            spilled.append(key)
        if spilled:
            logger.info(
                "volume %s spilled %d key(s) to the disk tier "
                "(resident %d B, spilled %d B, budget %d B)",
                self.volume_id,
                len(spilled),
                self._resident_bytes,
                tier.spilled_bytes,
                tier.budget_bytes,
            )
        self._publish_residency()
        return {
            "enabled": True,
            "spilled": spilled,
            "fault_ins": fault_ins,
            "resident_bytes": self._resident_bytes,
            "spilled_bytes": tier.spilled_bytes,
            "spilled_keys": len(tier.spilled),
            "budget_bytes": tier.budget_bytes,
        }

    @endpoint
    async def blob_sweep(self, limit: int = 32) -> dict:
        """Demote SPILLED (disk-tier) entries one rung further down into
        the blob cold tier: load the crash-safe disk copy, materialise the
        memmap-backed values, archive them as one blob object, then drop
        the disk copy. Only keys already cold enough to have spilled are
        eligible — the blob tier sits strictly below disk — and they
        demote coldest version group first (the spill tier's LRU clock;
        keys outside any version group, which the clock never tracks,
        demote ahead of tracked ones). Driven by the autoscale plane's
        BLOB_DEMOTE action and ``ts.autoscale()``."""
        blob = self._blob
        tier = self._tier
        if blob is None or tier is None:
            return {"enabled": False, "archived": []}
        from torchstore_tpu.tiering import version_group

        def _coldness(key: str) -> tuple:
            vg = version_group(key)
            group = f"{vg[0]}/v{vg[1]}" if vg is not None else ""
            return (tier.access.get(group, 0.0), key)

        archived: list[str] = []
        nbytes = 0
        async with self._tier_lock:
            for key in sorted(tier.spilled, key=_coldness)[: max(1, limit)]:
                try:
                    dmetas, dvalues = tier.load(key)
                except KeyError:
                    continue
                # Materialise memmap-backed values before pickling: the
                # disk file they map is deleted the moment we discard the
                # spilled copy below.
                values = {
                    idx: (np.array(v) if isinstance(v, np.ndarray) else v)
                    for idx, v in dvalues.items()
                }
                nbytes += blob.archive(key, dmetas, values)
                tier.discard(key)
                archived.append(key)
        if archived:
            blob.demoted(archived, nbytes)
        self._publish_residency()
        return {
            "enabled": True,
            "archived": archived,
            "nbytes": nbytes,
            "remaining_spilled": len(tier.spilled),
        }

    @endpoint
    async def blob_archive(self) -> dict:
        """Checkpoint every committed entry on this volume into the blob
        cold tier (scale-to-zero): resident entries and spilled disk
        copies are archived as blob objects; entries already archived are
        carried forward. Memory/disk copies are NOT dropped — this is a
        durable snapshot, not a demotion. Returns the per-key object map
        (blob object name, payload bytes, committed write generation) the
        controller folds into the fleet manifest."""
        blob = self._blob
        if blob is None:
            return {"enabled": False, "objects": {}}
        from torchstore_tpu.tiering.spill import SpillTier

        objects: dict[str, dict] = {}

        def _note(key: str, n: int) -> None:
            objects[key] = {
                "object": blob.object_name(key),
                "nbytes": n,
                "write_gen": self._write_gens.get(key, 0),
            }

        async with self._tier_lock:
            kv = getattr(self.store, "kv", {})
            for key in sorted(kv):
                entry = kv.get(key)
                if entry is None:
                    continue
                dmetas, dvalues = SpillTier.entry_requests(key, entry)
                values = {
                    idx: (np.array(v) if isinstance(v, np.ndarray) else v)
                    for idx, v in dvalues.items()
                }
                _note(key, blob.archive(key, dmetas, values))
            tier = self._tier
            if tier is not None:
                for key in sorted(tier.spilled):
                    if key in objects:
                        continue
                    try:
                        dmetas, dvalues = tier.load(key)
                    except KeyError:
                        continue
                    values = {
                        idx: (np.array(v) if isinstance(v, np.ndarray) else v)
                        for idx, v in dvalues.items()
                    }
                    _note(key, blob.archive(key, dmetas, values))
            for key, n in sorted(blob.archived.items()):
                if key not in objects:
                    _note(key, n)
            # Every object the manifest will reference is a checkpoint
            # copy now: a later fault-in promotion must keep it.
            blob.pin(objects)
        return {"enabled": True, "objects": objects}

    @endpoint
    async def put(self, buffer: TransportBuffer, metas: list[Request]) -> Any:
        await faults.afire("volume.put")
        t0 = time.perf_counter()
        if self._tier is not None or self._blob is not None:
            # Sharded overwrites land shard-by-shard: promote a spilled
            # entry FIRST so sibling shards survive the partial overwrite
            # (whole-entry puts below simply discard the stale cold copy).
            sharded = [m for m in metas if m.tensor_slice is not None]
            await self._tier_fault_in(sharded, "put")
            await self._blob_fault_in(sharded, "put")
        pairs = self._stamp_pairs(metas)
        t_land = time.perf_counter()
        await self._begin_landing(pairs)
        try:
            existing = self.store.extract_existing(metas)
            values = await maybe_await(
                buffer.handle_put_request(self.ctx, metas, existing)
            )
            affected = {meta.key for meta in metas}
            before = sum(self._entry_nbytes(k) for k in affected)
            self.store.store(metas, values)
        finally:
            self._end_landing(pairs)
            # Stage attribution (volume side): the landing bracket — copies
            # into store memory, including any shm.landing_stamp hold — is
            # this process's "landing" segment of the put.
            obs_timeline.observe_stage(
                "put", "landing", time.perf_counter() - t_land
            )
        self._apply_residency_delta(affected, before)
        self._tier_after_put(affected)
        _PUT_OPS.inc(volume=self.volume_id)
        # Data-plane profiling: this volume's own hot-key view + slow-op
        # log (the RPC-dispatch trace context is active here, so a slow put
        # annotates the client's trace).
        items = [(meta.key, self._meta_nbytes(meta)) for meta in metas]
        obs_profile.record_keys(
            "volume_put",
            items,
            t0,
            time.perf_counter() - t0,
        )
        # Volume-side traffic accounting (peer unknown at this layer: the
        # client-side choke point owns the attributable matrix edge) + a
        # flight-recorder breadcrumb for the last-seconds timeline.
        nbytes = sum(n for _, n in items)
        obs_ledger.record(
            getattr(buffer, "transport_name", "unknown"),
            obs_ledger.INGRESS,
            nbytes,
            volume=self.volume_id,
            items=items,
        )
        obs_recorder.record(
            "volume_op", "put", keys=len(metas), nbytes=nbytes
        )
        gens = self._bump_write_gens(metas)
        self._notify_push(gens)
        return {"reply": buffer.put_reply(), "write_gens": gens}

    @endpoint
    async def get(
        self, buffer: TransportBuffer, metas: list[Request]
    ) -> TransportBuffer:
        await faults.afire("volume.get")
        t0 = time.perf_counter()
        if self._tier is not None or self._blob is not None:
            # Cold keys fault back in from the disk/blob tiers HERE —
            # inside the existing transport ladder (this get RPC is
            # exactly where the one-sided/doorbell paths already fall back
            # to), never via a new per-get RPC. Resident keys pay one dict
            # check per enabled tier.
            await self._tier_fault_in(metas, "get")
            await self._blob_fault_in(metas, "get")
            if self._tier is not None:
                self._tier.touch([meta.key for meta in metas])
        entries = [self.store.get_data(meta) for meta in metas]
        t_land = time.perf_counter()
        await maybe_await(buffer.handle_get_request(self.ctx, metas, entries))
        # Stage attribution (volume side): loading entries into the reply
        # buffer (segment copies / frame sends) is the serve's landing leg.
        obs_timeline.observe_stage(
            "get", "landing", time.perf_counter() - t_land
        )
        _GET_OPS.inc(volume=self.volume_id)
        items = [
            # Object entries are arbitrary user types: only count an
            # nbytes attribute that is actually a number (same guard as
            # the client side).
            (
                meta.key,
                n if isinstance((n := getattr(entry, "nbytes", 0)), int) else 0,
            )
            for meta, entry in zip(metas, entries)
        ]
        obs_profile.record_keys(
            "volume_get",
            items,
            t0,
            time.perf_counter() - t0,
        )
        nbytes = sum(n for _, n in items)
        obs_ledger.record(
            getattr(buffer, "transport_name", "unknown"),
            obs_ledger.EGRESS,
            nbytes,
            volume=self.volume_id,
            items=items,
        )
        obs_recorder.record(
            "volume_op", "get", keys=len(metas), nbytes=nbytes
        )
        return buffer

    @endpoint
    async def get_meta(self, metas: list[Request]) -> list[Any]:
        if self._tier is not None or self._blob is not None:
            await self._tier_fault_in(metas, "get_meta")
            await self._blob_fault_in(metas, "get_meta")
        return [self.store.get_meta(meta) for meta in metas]

    @endpoint
    async def delete_batch(self, keys: list[str]) -> int:
        # Idempotent: missing keys ignored so cleanup retries are safe
        # (/root/reference/torchstore/api.py:308).
        deleted = 0
        before = sum(self._entry_nbytes(k) for k in keys)
        # Coarse landing bracket for in-flight doorbell serves; the
        # per-entry stamps are tombstoned by ctx.delete_key (one-sided
        # readers of deleted entries fall back from their first check).
        self._landing_open()
        try:
            for key in keys:
                # A key can live in several tiers at once (a blob
                # checkpoint keeps the resident copy): drop EVERY copy and
                # count the key once.
                dropped = False
                if self.store.delete(key):
                    self.ctx.delete_key(key)
                    dropped = True
                if self._tier is not None and self._tier.discard(key):
                    dropped = True  # spilled copy: the disk tier held it
                if self._blob is not None and self._blob.discard(key):
                    dropped = True  # archived copy in the blob cold tier
                if dropped:
                    deleted += 1
                self._write_gens.pop(key, None)
        finally:
            self._landing_close()
        self._apply_residency_delta(keys, before)
        return deleted

    @endpoint
    async def delete_batch_if(
        self, items: list[tuple[str, int]]
    ) -> dict[str, Any]:
        """Conditional delete for stale-replica reclaims (ADVICE r3): each
        ``(key, stale_gen)`` is deleted only if the key's current write
        generation is not NEWER than ``stale_gen`` — a fresh put that
        landed after the controller detached this replica bumped the
        generation and its bytes survive. Check-and-delete is atomic with
        respect to puts (no await between them), closing the window where
        an unconditional reclaim delete could destroy an acknowledged
        overwrite. A key with no recorded generation is deleted: its bytes
        predate this process's puts (volume restart), i.e. they are the
        stale copy the reclaim targets."""
        removed: list[str] = []
        kept_fresh: list[str] = []
        kept_gens: dict[str, int] = {}
        affected = [key for key, _ in items]
        before = sum(self._entry_nbytes(k) for k in affected)
        self._landing_open()
        try:
            for key, stale_gen in items:
                current = self._write_gens.get(key)
                if current is not None and current > stale_gen:
                    # ``kept_gens`` lets the controller re-verify later: if the
                    # fresh put's notify never arrives (client died between
                    # data-plane ack and notify), a follow-up conditional
                    # delete at THIS generation reclaims the orphaned bytes.
                    kept_fresh.append(key)
                    kept_gens[key] = current
                    continue
                dropped = False
                if self.store.delete(key):
                    self.ctx.delete_key(key)
                    dropped = True
                if self._tier is not None and self._tier.discard(key):
                    dropped = True  # stale copy lived in the disk tier
                if self._blob is not None and self._blob.discard(key):
                    dropped = True  # checkpointed copy in the blob tier
                if dropped:
                    removed.append(key)
                self._write_gens.pop(key, None)
        finally:
            self._landing_close()
        self._apply_residency_delta(affected, before)
        return {
            "removed": removed,
            "kept_fresh": kept_fresh,
            "kept_gens": kept_gens,
        }

    @endpoint
    async def write_gens(self, keys: list[str]) -> dict[str, int]:
        """Current write generations for ``keys`` (missing keys omitted) —
        phase one of the reclaim's two-phase delete for copies whose
        indexed generation the controller never learned (partial batch
        landings on a replica that was detached before its notify)."""
        return {
            key: self._write_gens[key]
            for key in keys
            if key in self._write_gens
        }

    @endpoint
    async def pull_from(
        self,
        src,
        metas: list[Request],
        src_hostname: str = "",
        src_volume: str = "",
        relay: bool = False,
    ) -> dict[str, Any]:
        """Volume-to-volume copy: pull ``metas`` from the volume at
        ActorRef ``src`` and store them locally — no client involvement,
        works across hosts. The data plane for the controller's auto-repair
        AND every broadcast relay hop (``relay=True``, fired through the
        ``relay.forward`` faultpoint so chaos schedules can kill/wedge a
        relay node mid-broadcast).

        Transport: the bulk rung when available (striped above
        TORCHSTORE_TPU_BULK_STRIPE_THRESHOLD — relay hops never pay
        per-key RPC framing), else the RPC frames. Never SHM: this process
        is itself an SHM *server*; mixing the client-side segment cache
        into the same TransportContext would fight the serve path.

        Landing: entries that already exist locally are overwritten
        IN-PLACE through the shared landing pool (``transport/landing.py``
        — copies overlap each other and this volume's event loop, large
        tensors chunk across pool threads), preserving the put-path
        aliasing invariant for any SHM/bulk reader of the old bytes; fresh
        entries adopt the transport's arrays without a copy.

        ``src_hostname``/``src_volume`` make the transfer PEER-AWARE in the
        traffic ledger (the buffer records one ingress cell with both
        endpoints), so ``ts.traffic_matrix()`` attributes relay/repair hops
        as real src->dst host edges instead of dumping them in
        "unattributed" — the O(1)-egress acceptance measurement.

        Returns fresh local write generations so the controller can index
        the new copy with a sound reclaim token."""
        if relay:
            await faults.afire("relay.forward")
        from torchstore_tpu.config import default_config
        from torchstore_tpu.strategy import StorageVolumeRef
        from torchstore_tpu.transport import landing
        from torchstore_tpu.transport.factory import (
            TransportType,
            bulk_available,
            create_transport_buffer,
        )

        config = default_config()
        if self._tier is not None or self._blob is not None:
            # Same rule as put: sharded pulls overwrite per shard, so a
            # spilled local copy must promote first to keep its siblings.
            sharded = [m for m in metas if m.tensor_slice is not None]
            await self._tier_fault_in(sharded, "pull")
            await self._blob_fault_in(sharded, "pull")
        src_ref = StorageVolumeRef(
            actor=src,
            volume_id=src_volume or "",
            transport_context=self.ctx,
            hostname=src_hostname,
        )
        rung = (
            TransportType.BULK
            if bulk_available(src_ref, config)
            else TransportType.RPC
        )
        buffer = create_transport_buffer(src_ref, config, force=rung)
        requests = [meta.meta_only() for meta in metas]
        results = await buffer.get_from_storage_volume(src_ref, requests)
        values: dict[int, Any] = dict(enumerate(results))
        affected = {meta.key for meta in metas}
        before = sum(self._entry_nbytes(k) for k in affected)
        # A pull is a landing like any put: bracket it so one-sided readers
        # of entries it replaces fall back instead of tearing.
        pairs = self._stamp_pairs(metas)
        await self._begin_landing(pairs)
        try:
            existing = self.store.extract_existing(metas)
            copy_pairs = []
            for idx, meta in enumerate(metas):
                dst = existing.get(idx)
                val = values[idx]
                if (
                    dst is not None
                    and not meta.is_object
                    and isinstance(val, np.ndarray)
                    and dst.shape == val.shape
                    and dst.dtype == val.dtype
                    and dst is not val
                ):
                    # In-place overwrite: SHM/bulk readers aliasing the old
                    # segment observe the update, exactly like a put.
                    copy_pairs.append((dst, val))
                    values[idx] = dst
            if copy_pairs:
                await landing.land_async(
                    copy_pairs, stage="pull_from", config=config
                )
            self.store.store(metas, values)
        finally:
            self._end_landing(pairs)
        self._apply_residency_delta(affected, before)
        self._tier_after_put(affected)
        gens = self._bump_write_gens(metas)
        self._notify_push(gens)
        return {"write_gens": gens}

    # ---- fault injection (test/chaos control plane) ----------------------

    @endpoint
    async def inject_fault(
        self,
        name: str,
        action: str,
        count: Optional[int] = None,
        prob: Optional[float] = None,
        delay_ms: Optional[float] = None,
    ) -> dict:
        """Arm a faultpoint INSIDE this volume process (see
        torchstore_tpu/faults.py) — lets tests schedule deterministic
        failures in an already-forked volume without restarting the fleet."""
        return faults.arm(
            name, action, count=count, prob=prob, delay_ms=delay_ms
        )

    @endpoint
    async def clear_faults(self, name: Optional[str] = None) -> int:
        return faults.disarm(name)

    @endpoint
    async def list_faults(self) -> list:
        return faults.armed()

    @endpoint
    async def manifest(self) -> list:
        """Meta-only descriptions (``{"meta": Request, "mtime": float}``) of
        every stored entry (durable backends only) — feeds controller index
        rebuilds after restarts. Items are annotated with this process's
        live ``write_gen`` for the key (absent after a volume restart) so a
        rebuilt controller index keeps conditional reclaims sound: without
        it every recovered copy would carry gen 0 and no reclaim could
        ever fire (any real generation compares newer)."""
        fn = getattr(self.store, "manifest", None)
        items = list(fn()) if fn is not None else []
        if self._tier is not None:
            # Spilled entries' bytes live ONLY in the disk tier: an index
            # rebuild that skipped them would silently lose cold versions.
            items.extend(self._tier.manifest())
        if self._blob is not None:
            # Same rule one rung down: blob-archived entries whose bytes
            # left both memory and disk must still surface in rebuilds.
            seen = {
                item["meta"].key for item in items if isinstance(item, dict)
            }
            items.extend(self._blob.manifest(exclude=seen))
        for item in items:
            if isinstance(item, dict):
                gen = self._write_gens.get(item["meta"].key)
                if gen is not None:
                    item["write_gen"] = gen
        return items

    @endpoint
    async def shm_capacity(self, config=None) -> dict:
        """Capacity view for the controller's prewarm reservations: tmpfs
        bytes actually available, plus the SHM pool's cap and current fill.
        The controller grants prewarm reservations against
        ``min(available, cap - pooled)`` minus outstanding grants, so
        concurrent prewarms cannot oversubscribe /dev/shm. ``config`` (the
        prewarming CLIENT's StoreConfig, forwarded through the controller)
        is adopted first — a programmatic pool cap must govern the grant,
        not the volume's env default, or the later provision_shm would be
        clamped against a cap the grant never saw."""
        from torchstore_tpu.transport import shared_memory as shm_mod

        out = {
            "shm": shm_mod.is_available(),
            "available_bytes": 0,
            "pool_cap": 0,
            "pool_bytes": 0,
        }
        if not out["shm"]:
            return out
        out["available_bytes"] = shm_mod.shm_available_bytes()
        cache = self.ctx.get_cache(shm_mod.ShmServerCache)
        cache.adopt_config(config)
        out["pool_cap"] = cache.pool_cap
        out["pool_bytes"] = cache.free_bytes
        return out

    @endpoint
    async def provision_shm(self, sizes: dict, config=None) -> dict:
        """Prewarm executor (SHM leg): pre-create + prefault ``{size:
        count}`` segments into this volume's warm free pool so the first
        put handshake of the provisioned working set offers every segment
        instead of cold-creating on the critical path. Config travels from
        the client (pool cap, hugepage/thread knobs) exactly as it does on
        the put path."""
        from torchstore_tpu.observability.tracing import span
        from torchstore_tpu.transport import shared_memory as shm_mod

        if not shm_mod.is_available():
            return {"created": 0, "bytes": 0, "error": "shm unavailable"}
        cache = self.ctx.get_cache(shm_mod.ShmServerCache)
        cache.adopt_config(config)
        hugepages = getattr(config, "prewarm_hugepages", True)
        nthreads = getattr(config, "prewarm_threads", 0)
        with span(
            "provision.shm_pool",
            volume=self.volume_id,
            sizes=len(sizes),
            nbytes=sum(int(s) * int(c) for s, c in sizes.items()),
        ):
            result = await cache.provision(
                {int(s): int(c) for s, c in sizes.items()},
                hugepages=hugepages,
                nthreads=nthreads,
            )
        if result.get("created"):
            logger.info(
                "provisioned %d segment(s) / %d bytes into volume %s pool "
                "(%d already pooled, %d bytes clamped)",
                result["created"],
                result["bytes"],
                self.volume_id,
                result["already_pooled"],
                result["clamped_bytes"],
            )
        return result

    @endpoint
    async def stats(self, history: Optional[dict] = None) -> dict:
        """Data-plane observability: stored entry/byte counts plus SHM
        segment economics (live/retired/pooled bytes, outstanding read
        leases) — the per-volume view controller.stats() aggregates.

        ``history={"series": ..., "since": ...}`` additionally returns
        this process's retained time-series rings under ``"history"``
        (``ts.history()`` rides this; routine scrapes omit it and stay
        cheap)."""
        entries = 0
        stored_bytes = 0
        kv = getattr(self.store, "kv", {})
        for entry in kv.values():
            entries += 1
            if entry.get("type") == "tensor":
                arr = entry.get("tensor")
                stored_bytes += int(getattr(arr, "nbytes", 0))
            elif entry.get("type") == "sharded":
                for shard in entry.get("shards", {}).values():
                    stored_bytes += int(
                        getattr(shard.get("tensor"), "nbytes", 0)
                    )
        out = {
            "volume_id": self.volume_id,
            "entries": entries,
            "stored_bytes": stored_bytes,
            "tracked_generations": len(self._write_gens),
            # This volume process's registry (process-local; the controller's
            # stats(include_volumes=True) aggregates the fleet).
            "metrics": obs_metrics.metrics_snapshot(),
            # Rolling top-K keys by bytes served/stored through THIS volume
            # (ts.fleet_snapshot collects every volume's view).
            "hot_keys": obs_profile.hot_keys(10),
            # Traffic ledger cells + rolling key windows (decision
            # telemetry; ts.fleet_snapshot merges them under "ledgers").
            "ledger": obs_ledger.snapshot(),
            # Overload signals (ts.slo_report folds these per volume): open
            # landing brackets, resident one-sided doorbell plans, and this
            # process's per-stage wall-time digests.
            "overload": self._overload_signals(),
            "stages": obs_timeline.stage_quantiles().snapshot(),
            # Trend detector results over this process's history rings
            # (sustained landing-inflight etc.): ts.slo_report folds the
            # active ones fleet-wide, the control snapshot reads the
            # sustained kind as its sustained_overload signal.
            "trends": obs_detect.evaluate_trends(),
        }
        if history is not None:
            out["history"] = obs_history.history(
                series=history.get("series"), since=history.get("since")
            )
        if self._tier is not None:
            out["tier"] = {
                "resident_bytes": self._resident_bytes,
                "spilled_bytes": self._tier.spilled_bytes,
                "spilled_keys": len(self._tier.spilled),
                "budget_bytes": self._tier.budget_bytes,
                "high_bytes": self._tier.high_bytes,
                "low_bytes": self._tier.low_bytes,
            }
        if self._blob is not None:
            out.setdefault("tier", {})
            out["tier"]["blob_bytes"] = self._blob.archived_bytes
            out["tier"]["blob_keys"] = len(self._blob.archived)
        from torchstore_tpu.transport.shared_memory import ShmServerCache

        cache = self.ctx.peek(ShmServerCache)
        if cache is not None:
            out["shm"] = {
                "live_segments": sum(
                    len(by_coords) for by_coords in cache.by_key.values()
                ),
                # Segments shared by >1 entry are packed small-key arenas
                # (steady-state pipeline): one segment carrying a whole put
                # batch's small-tensor tail.
                "arena_segments": sum(
                    1 for refs in cache.seg_refs.values() if refs > 1
                ),
                "retired_segments": len(cache.retired),
                "pool_segments": sum(
                    len(s) for s in cache.free_by_size.values()
                ),
                "pool_bytes": cache.free_bytes,
                "read_leases": sum(cache.grants.values()),
                "staged": len(cache.staged),
            }
        return out

    def _overload_signals(self) -> dict:
        """Per-volume overload signals (rides ``stats()``; ``ts.slo_report``
        folds them fleet-wide): how backed up this volume's landing bracket
        and doorbell plan table are right now — the inputs admission
        control (ROADMAP item 3) will trigger on."""
        from torchstore_tpu.transport.bulk import BulkServerCache

        bulk = self.ctx.peek(BulkServerCache)
        return {
            "landing_inflight": self._landing_inflight,
            "doorbell_plans": (
                len(bulk.server.get_plans) if bulk is not None else 0
            ),
        }

    @endpoint
    async def flight_record(self) -> list:
        """This volume process's flight-recorder ring (recent ops/faults/
        errors, oldest first) — ``ts.flight_record()`` merges the fleet's
        into one timeline, and the controller pulls it when assembling a
        quarantine post-mortem."""
        return obs_recorder.snapshot()

    @endpoint
    async def reset(self) -> None:
        self._landing_open()
        try:
            self.store.reset()
            self.ctx.clear()  # tombstones + unlinks the stamp table
            self._write_gens.clear()
            if self._tier is not None:
                self._tier.reset()
            if self._blob is not None:
                # Bookkeeping-only: blob OBJECTS are the durable cold tier
                # scale-to-zero restores from — reset() must not wipe them.
                self._blob.reset()
        finally:
            self._landing_close()
        self._install_doorbell_hook()
        self._resident_bytes = 0
        self._publish_residency()
