"""Per-tenant admission control: client-side token-bucket backpressure.

One cohort's burst must not starve the landing pool for everyone else.
Each client carries an :class:`AdmissionController` (armed by
``TORCHSTORE_TPU_CONTROL_ADMISSION``, labeled by the client's tenant):
``put_batch``/``get_batch`` reserve one token per logical op and sleep
out any deficit BEFORE touching a volume, so a bursting tenant queues at
its own bucket instead of inside the fleet's landing brackets.

The bucket's refill is modulated by overload signals — the per-shard
metadata-RPC inflight depth this client observes locally on every
refresh, plus whatever ``ts.slo_report()`` overload view is fed to
:meth:`AdmissionController.refresh` (per-volume ``landing_inflight``).
Past ``overload_inflight`` the effective rate scales down
proportionally; throttle ENGAGE/RELEASE transitions (never individual
waits) are recorded as flight-recorder ``decision`` events.

:class:`TokenBucket` itself is pure over an injected clock value, so the
rate math is unit-testable without sleeping.
"""

from __future__ import annotations

import time
from typing import Any, Mapping, Optional

from torchstore_tpu.observability import metrics as obs_metrics
from torchstore_tpu.observability import recorder as obs_recorder

_THROTTLED = obs_metrics.counter(
    "ts_control_admission_throttled_total",
    "Logical ops delayed by the admission token bucket, by tenant",
)
_WAIT_S = obs_metrics.counter(
    "ts_control_admission_wait_s_total",
    "Total seconds admission control held ops back, by tenant",
)
_FACTOR = obs_metrics.gauge(
    "ts_control_admission_factor",
    "Current admission refill factor (1.0 = unthrottled), by tenant",
)


class TokenBucket:
    """Deterministic token bucket: ``reserve(now, cost)`` consumes and
    returns the seconds the caller must wait (0.0 when tokens covered
    it). Tokens may go negative — concurrent reservers queue fairly
    behind each other's deficits instead of racing the refill."""

    def __init__(self, rate_hz: float, burst: float) -> None:
        self.rate_hz = max(1e-6, float(rate_hz))
        self.burst = max(1.0, float(burst))
        self._tokens = self.burst
        self._last: Optional[float] = None

    def set_rate(self, rate_hz: float) -> None:
        self.rate_hz = max(1e-6, float(rate_hz))

    def reserve(self, now: float, cost: float = 1.0) -> float:
        if self._last is None:
            self._last = now
        elapsed = max(0.0, now - self._last)
        self._last = now
        self._tokens = min(self.burst, self._tokens + elapsed * self.rate_hz)
        self._tokens -= cost
        if self._tokens >= 0.0:
            return 0.0
        return -self._tokens / self.rate_hz


class AdmissionController:
    """One client's per-tenant admission gate (see module docstring).

    ``admit(ops)`` returns the delay to sleep (the async client awaits
    it; tests call it with injected ``now``). ``refresh`` re-derives the
    throttle factor from the freshest overload signals — cheap enough to
    run inline every ``REFRESH_OPS`` admissions."""

    REFRESH_OPS = 64

    def __init__(
        self,
        rate_hz: float,
        burst: Optional[float] = None,
        tenant: str = "",
        overload_inflight: int = 16,
        min_factor: float = 0.1,
    ) -> None:
        self.tenant = tenant or "default"
        self.base_rate_hz = max(1e-6, float(rate_hz))
        self.overload_inflight = max(1, int(overload_inflight))
        self.min_factor = min(1.0, max(0.01, float(min_factor)))
        self.bucket = TokenBucket(
            self.base_rate_hz,
            self.base_rate_hz * 2 if burst is None else burst,
        )
        self.factor = 1.0
        self._throttling = False
        self._since_refresh = 0
        self._local_signal = None  # () -> Mapping[str, int] inflight view
        _FACTOR.set(1.0, tenant=self.tenant)

    def bind_local_signal(self, fn) -> None:
        """Attach the zero-cost local overload probe (the metadata
        router's ``inflight_snapshot``)."""
        self._local_signal = fn

    # -- overload feedback -------------------------------------------------

    def refresh(self, slo_overload: Optional[Mapping[str, Any]] = None) -> float:
        """Re-derive the refill factor from overload signals: the local
        per-shard metadata-RPC inflight plus (when provided) the
        ``slo_report()["overload"]`` per-volume ``landing_inflight``
        view. Returns the new factor."""
        depth = 0
        if self._local_signal is not None:
            try:
                local = self._local_signal() or {}
            except Exception:  # noqa: BLE001 - telemetry must not gate ops
                local = {}
            depth = max((int(n) for n in local.values()), default=0)
        for entry in ((slo_overload or {}).get("volumes") or {}).values():
            depth = max(depth, int((entry or {}).get("landing_inflight", 0)))
        meta = (slo_overload or {}).get("metadata_rpc_inflight") or {}
        depth = max(depth, max((int(n) for n in meta.values()), default=0))
        if depth <= self.overload_inflight:
            factor = 1.0
        else:
            factor = max(self.min_factor, self.overload_inflight / depth)
        self._set_factor(factor, depth)
        return factor

    def _set_factor(self, factor: float, depth: int) -> None:
        self.factor = factor
        self.bucket.set_rate(self.base_rate_hz * factor)
        _FACTOR.set(factor, tenant=self.tenant)
        throttling = factor < 1.0
        if throttling != self._throttling:
            # State TRANSITIONS only — a decision event per admitted op
            # would be flight-ring noise.
            self._throttling = throttling
            obs_recorder.record(
                "decision",
                "admission_throttle" if throttling else "admission_release",
                tenant=self.tenant,
                factor=round(factor, 4),
                inflight=depth,
                rate_hz=round(self.bucket.rate_hz, 3),
            )

    # -- the gate ----------------------------------------------------------

    def admit(self, ops: int = 1, now: Optional[float] = None) -> float:
        """Reserve ``ops`` tokens; returns the seconds the caller must
        sleep before proceeding (0.0 on the unthrottled fast path)."""
        self._since_refresh += 1
        if self._since_refresh >= self.REFRESH_OPS:
            self._since_refresh = 0
            self.refresh()
        delay = self.bucket.reserve(
            time.monotonic() if now is None else now, float(max(1, ops))
        )
        if delay > 0.0:
            _THROTTLED.inc(ops, tenant=self.tenant)
            _WAIT_S.inc(delay, tenant=self.tenant)
        return delay

    def describe(self) -> dict[str, Any]:
        return {
            "tenant": self.tenant,
            "rate_hz": self.base_rate_hz,
            "factor": self.factor,
            "burst": self.bucket.burst,
            "throttling": self._throttling,
        }
