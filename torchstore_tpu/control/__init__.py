"""Control plane: close the telemetry -> placement loop (ROADMAP item 1).

The store emits every signal a scheduler could want — ``ts.traffic_matrix``
edges, hot-key windows, per-volume overload, stage-attributed SLO
violations — and exposes every actuator (``pull_from`` migration,
placement-epoch bumps, relay re-parenting, tier demotion, metadata
resharding). This package connects them:

- :mod:`torchstore_tpu.control.snapshot` — the frozen
  :class:`TelemetrySnapshot` the solver reads, plus the builder that
  normalizes raw telemetry dicts into it.
- :mod:`torchstore_tpu.control.solver` — the PURE placement policy:
  ``solve(snapshot, policy, history)`` returns typed actions, no fleet,
  no clock, no I/O (unit-testable over hand-built snapshots).
- :mod:`torchstore_tpu.control.engine` — the controller-side executor:
  scrapes telemetry, runs the solver, applies actions through the real
  actuators, and records every decision (inputs, action, outcome) as a
  flight-recorder ``decision`` event + ``ts_control_*`` metrics.
- :mod:`torchstore_tpu.control.admission` — the client-side per-tenant
  token bucket admission control refilled from ``slo_report`` overload
  signals.

Separation of powers is the design invariant: the solver DECIDES, the
engine ACTS, and neither imports the other's dependencies — the solver
must stay importable (and testable) with no fleet and no asyncio.
"""

from torchstore_tpu.control.admission import AdmissionController, TokenBucket
from torchstore_tpu.control.snapshot import (
    KeyStat,
    RelayView,
    TelemetrySnapshot,
    VolumeLoad,
    build_snapshot,
)
from torchstore_tpu.control.solver import (
    Action,
    ActionRecord,
    ControlPolicy,
    solve,
)

__all__ = [
    "Action",
    "ActionRecord",
    "AdmissionController",
    "ControlPolicy",
    "KeyStat",
    "RelayView",
    "TelemetrySnapshot",
    "TokenBucket",
    "VolumeLoad",
    "build_snapshot",
    "solve",
]
