"""The control engine: scrape telemetry, run the solver, apply actions.

One ``ControlEngine`` lives inside the Controller (coordinator) process.
Its reconcile round is strictly phased — SNAPSHOT (scrape every healthy
volume's ``stats()``, the index's replica placement for the keys that
moved bytes, relay membership, and tier-pressure cold keys into a frozen
:class:`TelemetrySnapshot`), SOLVE (the pure policy in
``control/solver.py``), ACT (apply each action through the real
actuators: ``pull_from`` migration via the index authority, relay member
preference, per-key tier demotion) — so the decision inputs the audit
trail records are exactly what the solver saw.

Every applied (or refused) action lands in the flight recorder as a
``decision`` event and in the ``ts_control_*`` metrics; ``plan()`` is the
dry-run half ``ts.control_plan()`` serves (solve, record nothing, touch
nothing). Client-fed telemetry (the fleet traffic matrix, the SLO
overload report) is folded in when provided — the periodic loop runs on
what the coordinator can reach alone.

Failure domains: one action failing never aborts the round; a
``control.migrate`` faultpoint fires inside each migration so chaos
schedules can kill a volume mid-move (the index-side generation check
then reclaims or abandons — loudly, as a ``decision`` outcome).
"""

from __future__ import annotations

import os
import time
from collections import deque
from typing import Any, Mapping, Optional

from torchstore_tpu import faults
from torchstore_tpu.control.snapshot import TelemetrySnapshot, build_snapshot
from torchstore_tpu.control.solver import (
    DEMOTE,
    MIGRATE,
    RELAY_ORDER,
    RESHARD,
    SPLIT,
    Action,
    ActionRecord,
    ControlPolicy,
    solve,
)
from torchstore_tpu.logging import get_logger
from torchstore_tpu.observability import metrics as obs_metrics
from torchstore_tpu.observability import recorder as obs_recorder

logger = get_logger("torchstore_tpu.control.engine")

_DECISIONS = obs_metrics.counter(
    "ts_control_decisions_total",
    "Control-plane decisions, by action kind and outcome",
)
_MIGRATION_BYTES = obs_metrics.counter(
    "ts_control_migration_bytes_total",
    "Logical bytes moved by control-plane migrations and splits",
)
_RECONCILES = obs_metrics.counter(
    "ts_control_reconciles_total",
    "Control-engine reconcile rounds, by trigger",
)
_LAST_ACTIONS = obs_metrics.gauge(
    "ts_control_last_actions",
    "Actions the last reconcile round decided",
)

# History depth: enough rounds to remember every damped subject without
# growing unboundedly on a long-lived fleet.
_HISTORY = 256


def policy_from_env() -> ControlPolicy:
    """The solver thresholds, with ``TORCHSTORE_TPU_CONTROL_*`` overrides
    (same raw-environ pattern as the controller's other knobs — the engine
    lives in the controller process, not behind StoreConfig)."""

    def _f(name: str, default: float) -> float:
        raw = os.environ.get(name)
        return float(raw) if raw not in (None, "") else default

    base = ControlPolicy()
    return ControlPolicy(
        overload_ratio=_f(
            "TORCHSTORE_TPU_CONTROL_OVERLOAD_RATIO", base.overload_ratio
        ),
        min_window_bytes=int(
            _f("TORCHSTORE_TPU_CONTROL_MIN_WINDOW_BYTES", base.min_window_bytes)
        ),
        hot_key_min_bytes=int(
            _f(
                "TORCHSTORE_TPU_CONTROL_HOT_KEY_MIN_BYTES",
                base.hot_key_min_bytes,
            )
        ),
        min_edge_bytes=int(
            _f("TORCHSTORE_TPU_CONTROL_MIN_EDGE_BYTES", base.min_edge_bytes)
        ),
        cooldown_s=_f("TORCHSTORE_TPU_CONTROL_COOLDOWN_S", base.cooldown_s),
        max_actions=int(
            _f("TORCHSTORE_TPU_CONTROL_MAX_ACTIONS", base.max_actions)
        ),
    )


class ControlEngine:
    """Controller-side executor for the placement policy (see module doc).

    ``host`` is the Controller actor instance — the engine reaches the
    fleet only through its surface (``volume_refs``, ``idx``, relay
    state), never through raw index structures."""

    def __init__(self, host: Any, policy: Optional[ControlPolicy] = None):
        self.host = host
        self.policy = policy or policy_from_env()
        self.history: deque[ActionRecord] = deque(maxlen=_HISTORY)
        self._rounds = 0

    # ---- SNAPSHOT --------------------------------------------------------

    async def snapshot(
        self,
        traffic: Optional[Mapping[str, Any]] = None,
        overload: Optional[Mapping[str, Any]] = None,
    ) -> TelemetrySnapshot:
        """Freeze what the coordinator can see right now, folding in any
        client-fed traffic matrix / SLO overload view."""
        import asyncio

        host = self.host
        quarantined = host.quarantined_ids()
        live = {
            vid: ref
            for vid, ref in host.volume_refs.items()
            if vid not in quarantined
        }

        async def one_stats(vid: str, ref: Any):
            try:
                return vid, await asyncio.wait_for(
                    ref.stats.call_one(), timeout=10.0
                )
            except Exception as exc:  # noqa: BLE001 - a dark volume is the
                # supervisor's problem; the solver plans around it
                logger.debug("control snapshot: stats(%s) failed: %s", vid, exc)
                return vid, None

        results = await asyncio.gather(
            *(one_stats(vid, ref) for vid, ref in live.items())
        )
        volume_stats = {vid: st for vid, st in results if st is not None}

        # Replica placement for every key the window saw moving bytes —
        # the solver needs it to tell single-replica hot keys (migrate)
        # from already-split ones.
        seen: set[str] = set()
        for st in volume_stats.values():
            for row in st.get("hot_keys") or ():
                seen.add(row["key"])
            for row in (st.get("ledger") or {}).get("keys") or ():
                seen.add(row["key"])
        for rows in ((traffic or {}).get("keys") or {}).values():
            for row in rows or ():
                seen.add(row["key"])
        key_placement: dict[str, tuple[str, ...]] = {}
        for key in sorted(seen):
            infos = await host.idx.get_entry(key)
            if infos:
                key_placement[key] = tuple(sorted(infos))

        # Per-key demotion candidates, only where tier pressure exists.
        pins = sorted(host._leases.pinned_groups())
        cold_keys: dict[str, list[str]] = {}
        for vid, st in volume_stats.items():
            tier = st.get("tier") or {}
            budget = int(tier.get("budget_bytes", 0) or 0)
            resident = int(tier.get("resident_bytes", 0) or 0)
            if budget <= 0 or resident < self.policy.demote_pct * budget:
                continue
            ref = live.get(vid)
            if ref is None:
                continue
            try:
                cold = await asyncio.wait_for(
                    ref.tier_cold_keys.call_one(
                        pins, self.policy.demote_keys_per_round
                    ),
                    timeout=10.0,
                )
            except Exception:  # noqa: BLE001 - candidates are optional
                continue
            if cold:
                cold_keys[vid] = list(cold)

        # Relay membership: channel -> (root of the newest live run, the
        # refcounted member volumes). Channels with no live run carry no
        # measured tree to re-order.
        relays: dict[str, tuple[str, list[str]]] = {}
        best_version: dict[str, int] = {}
        for run in host._relay_runs.values():
            if run.get("dead"):
                continue
            channel = run["channel"]
            ch = host._relay_channels.get(channel)
            if ch is None:
                continue
            if run["version"] >= best_version.get(channel, -1):
                best_version[channel] = run["version"]
                relays[channel] = (run["root"], sorted(ch["members"]))

        return build_snapshot(
            traffic=traffic,
            overload=overload,
            volume_stats=volume_stats,
            placement=dict(host.volume_hostnames),
            key_placement=key_placement,
            cold_keys=cold_keys,
            n_shards=len(host._shard_refs) or 1,
            relays=relays,
            generated_ts=time.monotonic(),
        )

    # ---- SOLVE -----------------------------------------------------------

    async def plan(
        self,
        traffic: Optional[Mapping[str, Any]] = None,
        overload: Optional[Mapping[str, Any]] = None,
    ) -> dict[str, Any]:
        """Dry run: what the engine WOULD do, touching nothing and
        recording nothing (``ts.control_plan()``)."""
        snap = await self.snapshot(traffic=traffic, overload=overload)
        actions = solve(snap, self.policy, self.history)
        return {
            "actions": [a.describe() for a in actions],
            "snapshot": snap.describe(),
            "history": len(self.history),
        }

    # ---- ACT -------------------------------------------------------------

    async def reconcile(
        self,
        traffic: Optional[Mapping[str, Any]] = None,
        overload: Optional[Mapping[str, Any]] = None,
        trigger: str = "interval",
    ) -> dict[str, Any]:
        """One full round: snapshot, solve, apply. Returns the per-action
        outcomes (also recorded as ``decision`` events)."""
        await faults.afire("control.reconcile")
        _RECONCILES.inc(trigger=trigger)
        self._rounds += 1
        snap = await self.snapshot(traffic=traffic, overload=overload)
        actions = solve(snap, self.policy, self.history)
        _LAST_ACTIONS.set(len(actions))
        outcomes = []
        for action in actions:
            outcome = await self._apply(snap, action)
            outcomes.append({**action.describe(), "outcome": outcome})
            # Failed actions enter history too: a migration that raced or
            # errored must cool down, not retry every round.
            self.history.append(
                ActionRecord(
                    ts=snap.generated_ts,
                    kind=action.kind,
                    subject=action.subject,
                    src_volume=action.src_volume,
                    dst_volume=action.dst_volume,
                )
            )
        return {
            "round": self._rounds,
            "trigger": trigger,
            "actions": outcomes,
            "snapshot": snap.describe(),
        }

    async def _apply(self, snap: TelemetrySnapshot, action: Action) -> str:
        import asyncio

        try:
            if action.kind in (MIGRATE, SPLIT):
                return await self._apply_move(snap, action)
            if action.kind == RELAY_ORDER:
                return self._apply_relay_order(snap, action)
            if action.kind == DEMOTE:
                return await self._apply_demote(snap, action)
            if action.kind == RESHARD:
                return self._apply_reshard(snap, action)
            return self._decision(snap, action, "skipped: unknown kind")
        except asyncio.CancelledError:
            raise
        except Exception as exc:  # noqa: BLE001 - one action's failure
            # must not abort the round; the outcome says it failed
            logger.warning(
                "control action %s/%s failed: %s",
                action.kind,
                action.subject,
                exc,
            )
            return self._decision(
                snap, action, f"error: {type(exc).__name__}: {exc}"
            )

    async def _apply_move(
        self, snap: TelemetrySnapshot, action: Action
    ) -> str:
        """Online key migration (MIGRATE drops the source replica after the
        copy lands; SPLIT keeps it). The copy itself — pull_from, the
        write-generation race check, indexing — lives with the index
        authority (``idx.migrate_key``), same layering as auto-repair."""
        await faults.afire("control.migrate")
        result = await self.host.idx.migrate_key(
            action.subject,
            action.src_volume,
            action.dst_volume,
            drop_src=action.kind == MIGRATE,
        )
        status = result.get("status", "error")
        nbytes = int(result.get("nbytes", 0) or 0)
        if status == "ok" and nbytes:
            _MIGRATION_BYTES.inc(nbytes)
        return self._decision(
            snap,
            action,
            "applied" if status == "ok" else f"abandoned: {status}",
            nbytes=nbytes,
        )

    def _apply_relay_order(
        self, snap: TelemetrySnapshot, action: Action
    ) -> str:
        """Prefer measured-proximity member order for the channel's NEXT
        relay trees (live runs keep their mid-version tree — stability
        beats topological optimality, same rule as membership joins)."""
        host = self.host
        ch = host._relay_channels.get(action.subject)
        if ch is None:
            return self._decision(snap, action, "abandoned: channel gone")
        host._relay_prefer[action.subject] = tuple(action.order)
        ch["epoch"] += 1
        return self._decision(
            snap, action, "applied", members=len(action.order)
        )

    async def _apply_demote(
        self, snap: TelemetrySnapshot, action: Action
    ) -> str:
        """Per-key frequency-aware demotion: spill exactly the idle keys
        (regardless of watermark), then fold the tier flips into the
        index — the same feedback loop as the background sweeper."""
        host = self.host
        ref = host.volume_refs.get(action.src_volume)
        if ref is None:
            return self._decision(snap, action, "abandoned: volume gone")
        pins = sorted(host._leases.pinned_groups())
        rep = await ref.tier_sweep.call_one(pins, list(action.keys))
        if not rep.get("enabled"):
            return self._decision(snap, action, "abandoned: tier disabled")
        await host.idx.set_tiers(
            action.src_volume,
            list(rep.get("spilled", ())),
            list(rep.get("fault_ins", ())),
        )
        return self._decision(
            snap, action, "applied", spilled=len(rep.get("spilled", ()))
        )

    def _apply_reshard(
        self, snap: TelemetrySnapshot, action: Action
    ) -> str:
        """The engine cannot spawn shard actors (the owner process does);
        a reshard decision is surfaced — loudly — for ``ts.rebalance(
        shards=N)`` to execute. The decision event IS the actuation here."""
        return self._decision(
            snap, action, "deferred: run ts.rebalance(shards=%d)" % action.shards
        )

    # ---- audit -----------------------------------------------------------

    def _decision(
        self,
        snap: TelemetrySnapshot,
        action: Action,
        outcome: str,
        **extra: Any,
    ) -> str:
        """The ONE decision-audit chokepoint: inputs (the snapshot summary
        the solver saw), the chosen action, and what happened."""
        _DECISIONS.inc(kind=action.kind, outcome=outcome.split(":")[0])
        obs_recorder.record(
            "decision",
            f"control/{action.kind}",
            subject=action.subject,
            reason=action.reason,
            outcome=outcome,
            action=action.describe(),
            inputs=snap.describe(),
            **extra,
        )
        return outcome
