"""The placement policy solver: a pure function over a frozen snapshot.

``solve(snapshot, policy, history)`` returns the typed actions the engine
(control/engine.py) should apply. No clock, no I/O, no fleet — the same
snapshot and history always produce the same plan, so every policy
behavior (skew -> co-locate, hot key -> split, idle -> no-op, oscillation
damping) is unit-testable over hand-built snapshots.

Decision families, in priority order:

1. ``migrate_key`` — one volume's rolling-window bytes exceed
   ``overload_ratio`` x the fleet mean: move its hottest keys onto the
   least-loaded volume, preferring a volume on the dominant CONSUMER host
   (the heaviest outgoing edge from the hot volume's host) so serves
   become host-local.
2. ``split_hot_key`` — a single key dominates its volume's window
   (``hot_key_frac``) with fewer than ``max_replicas`` committed copies:
   add a replica on the least-loaded volume not already holding it.
3. ``relay_order`` — a relay channel's measured edge traffic implies a
   better member ordering than the default sorted-id one: heaviest
   consumers attach nearest the root.
4. ``demote_keys`` — a tiered volume past ``demote_pct`` of its budget
   with keys that moved NO bytes in the window: demote exactly those
   (per-key frequency-aware, replacing whole-version LRU pressure).
5. ``reshard`` — sustained per-shard metadata-RPC queue depth at or over
   ``reshard_inflight_high``: double the shard count (capped).

Hysteresis / damping rules (the oscillation tests pin these):

- Enter/exit split: migration triggers at ``overload_ratio`` but any
  imbalance under ``settle_ratio`` is left alone — a fleet between the
  two thresholds is "settling" and produces no new plan.
- Cooldown: a subject (key, volume, channel, or the shard plane) acted
  on within ``cooldown_s`` of ``snapshot.generated_ts`` is never acted
  on again, and a migration that would REVERSE a recent move (same key,
  src and dst swapped) is dropped even past the cooldown window.
- Budget: at most ``max_actions`` actions per round, highest priority
  first.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Iterable, Optional

from torchstore_tpu.control.snapshot import TelemetrySnapshot, VolumeLoad

# Action kinds, in priority order (solve() emits them in this order and
# truncates at policy.max_actions).
MIGRATE = "migrate_key"
SPLIT = "split_hot_key"
RELAY_ORDER = "relay_order"
DEMOTE = "demote_keys"
RESHARD = "reshard"

KINDS = (MIGRATE, SPLIT, RELAY_ORDER, DEMOTE, RESHARD)


@dataclass(frozen=True)
class Action:
    """One decided action. ``subject`` is the hysteresis identity (the
    key for migrations/splits, the volume for demotions, the channel for
    relay ordering, ``"shards"`` for resharding); the remaining fields
    depend on ``kind`` and ride ``detail``."""

    kind: str
    subject: str
    reason: str
    src_volume: str = ""
    dst_volume: str = ""
    keys: tuple[str, ...] = ()
    order: tuple[str, ...] = ()
    shards: int = 0
    detail: dict = field(default_factory=dict)

    def describe(self) -> dict[str, Any]:
        out = {
            "kind": self.kind,
            "subject": self.subject,
            "reason": self.reason,
        }
        if self.src_volume:
            out["src_volume"] = self.src_volume
        if self.dst_volume:
            out["dst_volume"] = self.dst_volume
        if self.keys:
            out["keys"] = list(self.keys)
        if self.order:
            out["order"] = list(self.order)
        if self.shards:
            out["shards"] = self.shards
        if self.detail:
            out["detail"] = dict(self.detail)
        return out


@dataclass(frozen=True)
class ActionRecord:
    """One applied action, as the engine remembers it for hysteresis."""

    ts: float
    kind: str
    subject: str
    src_volume: str = ""
    dst_volume: str = ""


@dataclass(frozen=True)
class ControlPolicy:
    """Solver thresholds. Defaults are deliberately conservative: a
    balanced fleet must solve to an empty plan."""

    # Migration enter/exit thresholds over the fleet-mean window bytes.
    overload_ratio: float = 2.0
    settle_ratio: float = 1.5
    # Ignore volumes/keys below this much recent traffic entirely.
    min_window_bytes: int = 1 << 16
    migrate_keys_per_round: int = 4
    # Hot-key split: one key >= this fraction of its volume's window.
    hot_key_frac: float = 0.5
    hot_key_min_bytes: int = 1 << 20
    max_replicas: int = 3
    # Relay proximity: re-order only when the heaviest relevant edge
    # moved at least this many bytes in the window.
    min_edge_bytes: int = 1 << 20
    # Per-key demotion: trigger past this fraction of the tier budget.
    demote_pct: float = 0.85
    demote_keys_per_round: int = 32
    # Elastic reshard: per-shard inflight metadata RPCs that motivate a
    # shard-count doubling.
    reshard_inflight_high: int = 32
    max_shards: int = 8
    # Damping.
    cooldown_s: float = 30.0
    max_actions: int = 8


def _recent(
    history: Iterable[ActionRecord], now: float, cooldown_s: float
) -> list[ActionRecord]:
    return [r for r in history if now - r.ts < cooldown_s]


def _cooled(recent: list[ActionRecord], kind: str, subject: str) -> bool:
    """Whether (kind, subject) is inside its cooldown window."""
    return any(r.kind == kind and r.subject == subject for r in recent)


def _reversal(
    history: Iterable[ActionRecord], key: str, src: str, dst: str
) -> bool:
    """A migrate that would undo ANY remembered move of the same key —
    dropped regardless of cooldown (the anti-oscillation rule)."""
    return any(
        r.kind == MIGRATE
        and r.subject == key
        and r.src_volume == dst
        and r.dst_volume == src
        for r in history
    )


def _pick_target(
    snapshot: TelemetrySnapshot,
    src: VolumeLoad,
    exclude: Iterable[str] = (),
) -> Optional[VolumeLoad]:
    """The migration/split target: the least-loaded volume (by window
    bytes, stored bytes as tiebreak) that isn't excluded, preferring
    volumes on the dominant consumer host of ``src``'s traffic."""
    excluded = set(exclude) | {src.volume_id}
    candidates = [
        v for vid, v in snapshot.volumes.items() if vid not in excluded
    ]
    if not candidates:
        return None
    consumer_hosts = sorted(
        (snapshot.edges.get(src.host) or {}).items(),
        key=lambda kv: kv[1],
        reverse=True,
    )
    for host, _nbytes in consumer_hosts:
        if host == src.host:
            continue
        on_host = [v for v in candidates if v.host == host]
        if on_host:
            return min(
                on_host, key=lambda v: (v.window_bytes, v.stored_bytes)
            )
    return min(candidates, key=lambda v: (v.window_bytes, v.stored_bytes))


def _solve_migrations(
    snapshot: TelemetrySnapshot,
    policy: ControlPolicy,
    recent: list[ActionRecord],
    history: list[ActionRecord],
) -> list[Action]:
    loads = [
        v for v in snapshot.volumes.values() if v.window_bytes > 0
    ]
    if len(snapshot.volumes) < 2 or not loads:
        return []
    mean = snapshot.total_window_bytes() / max(1, len(snapshot.volumes))
    hot = max(loads, key=lambda v: v.window_bytes)
    if hot.window_bytes < policy.min_window_bytes:
        return []
    # Hysteresis enter threshold; between settle and overload: no-op —
    # UNLESS the trend plane says this volume's overload is sustained
    # (observability/detect.py via snapshot.sustained_overload): a held
    # regime change enters at the EXIT threshold instead, because the
    # hysteresis band exists to ignore bursts and this is provably not
    # one. A volume merely spiking still needs the full overload_ratio.
    enter = (
        policy.settle_ratio
        if hot.volume_id in snapshot.sustained_overload
        else policy.overload_ratio
    )
    if hot.window_bytes < enter * max(mean, 1.0):
        return []
    target = _pick_target(snapshot, hot)
    if target is None or target.window_bytes >= hot.window_bytes:
        return []
    out: list[Action] = []
    # Move the hot volume's hottest keys until the projected imbalance
    # drops under the EXIT threshold (settle_ratio) or the round budget
    # runs out. Keys with other replicas already serving stay put — a
    # split (below) spreads those.
    excess = hot.window_bytes - policy.settle_ratio * max(mean, 1.0)
    moved = 0
    for stat in snapshot.hot_keys:
        if len(out) >= policy.migrate_keys_per_round or moved >= excess:
            break
        if hot.volume_id not in stat.volumes or len(stat.volumes) > 1:
            continue
        if target.volume_id in stat.volumes:
            continue
        if _cooled(recent, MIGRATE, stat.key) or _cooled(
            recent, SPLIT, stat.key
        ):
            continue
        if _reversal(history, stat.key, hot.volume_id, target.volume_id):
            continue
        out.append(
            Action(
                kind=MIGRATE,
                subject=stat.key,
                reason=(
                    f"volume {hot.volume_id} window {hot.window_bytes}B >= "
                    f"{enter:g}x fleet mean {mean:.0f}B"
                    + (
                        " (sustained overload)"
                        if hot.volume_id in snapshot.sustained_overload
                        else ""
                    )
                ),
                src_volume=hot.volume_id,
                dst_volume=target.volume_id,
                keys=(stat.key,),
                detail={"key_bytes": stat.bytes},
            )
        )
        moved += stat.bytes
    return out


def _solve_splits(
    snapshot: TelemetrySnapshot,
    policy: ControlPolicy,
    recent: list[ActionRecord],
    claimed: frozenset[str] = frozenset(),
) -> list[Action]:
    out: list[Action] = []
    for stat in snapshot.hot_keys:
        if stat.key in claimed:
            continue  # already migrating this round; one plan per key
        if stat.bytes < policy.hot_key_min_bytes or not stat.volumes:
            continue
        if len(stat.volumes) >= policy.max_replicas:
            continue
        home = snapshot.volumes.get(stat.volumes[0])
        if home is None or home.window_bytes <= 0:
            continue
        if stat.bytes < policy.hot_key_frac * home.window_bytes:
            continue
        if _cooled(recent, SPLIT, stat.key) or _cooled(
            recent, MIGRATE, stat.key
        ):
            continue
        target = _pick_target(snapshot, home, exclude=stat.volumes)
        if target is None:
            continue
        out.append(
            Action(
                kind=SPLIT,
                subject=stat.key,
                reason=(
                    f"key moved {stat.bytes}B >= "
                    f"{policy.hot_key_frac:g} of volume "
                    f"{home.volume_id}'s window with "
                    f"{len(stat.volumes)} replica(s)"
                ),
                src_volume=home.volume_id,
                dst_volume=target.volume_id,
                keys=(stat.key,),
                detail={"replicas": len(stat.volumes)},
            )
        )
    return out


def _solve_relay_orders(
    snapshot: TelemetrySnapshot,
    policy: ControlPolicy,
    recent: list[ActionRecord],
) -> list[Action]:
    out: list[Action] = []
    for relay in snapshot.relays:
        if len(relay.members) < 2 or _cooled(
            recent, RELAY_ORDER, relay.channel
        ):
            continue
        root_host = (
            snapshot.volumes.get(relay.root) or VolumeLoad(relay.root)
        ).host
        root_edges = snapshot.edges.get(root_host) or {}

        def weight(vid: str) -> int:
            host = (
                snapshot.volumes.get(vid) or VolumeLoad(vid)
            ).host
            return int(root_edges.get(host, 0))

        default = sorted(set(relay.members) - {relay.root})
        measured = sorted(default, key=lambda v: (-weight(v), v))
        if measured == default or weight(measured[0]) < policy.min_edge_bytes:
            continue
        out.append(
            Action(
                kind=RELAY_ORDER,
                subject=relay.channel,
                reason=(
                    f"measured origin-edge traffic orders {measured[0]} "
                    f"({weight(measured[0])}B) ahead of sorted-id default"
                ),
                order=tuple(measured),
                detail={"root": relay.root},
            )
        )
    return out


def _solve_demotions(
    snapshot: TelemetrySnapshot,
    policy: ControlPolicy,
    recent: list[ActionRecord],
) -> list[Action]:
    out: list[Action] = []
    for vid, vol in sorted(snapshot.volumes.items()):
        if vol.tier_budget_bytes <= 0 or _cooled(recent, DEMOTE, vid):
            continue
        if vol.tier_resident_bytes < policy.demote_pct * vol.tier_budget_bytes:
            continue
        cold = snapshot.cold_keys.get(vid) or ()
        if not cold:
            continue
        out.append(
            Action(
                kind=DEMOTE,
                subject=vid,
                reason=(
                    f"resident {vol.tier_resident_bytes}B >= "
                    f"{policy.demote_pct:g} of tier budget "
                    f"{vol.tier_budget_bytes}B with {len(cold)} idle key(s)"
                ),
                src_volume=vid,
                keys=tuple(cold[: policy.demote_keys_per_round]),
            )
        )
    return out


def _solve_reshard(
    snapshot: TelemetrySnapshot,
    policy: ControlPolicy,
    recent: list[ActionRecord],
) -> list[Action]:
    if _cooled(recent, RESHARD, "shards"):
        return []
    if snapshot.n_shards >= policy.max_shards:
        return []
    depth = max(
        (
            n
            for shard, n in snapshot.meta_inflight.items()
            if shard != "coord"
        ),
        default=0,
    )
    if snapshot.n_shards == 1:
        depth = max(depth, snapshot.meta_inflight.get("coord", 0))
    if depth < policy.reshard_inflight_high:
        return []
    target = min(policy.max_shards, max(2, snapshot.n_shards * 2))
    return [
        Action(
            kind=RESHARD,
            subject="shards",
            reason=(
                f"per-shard metadata-RPC inflight {depth} >= "
                f"{policy.reshard_inflight_high} at {snapshot.n_shards} "
                f"shard(s)"
            ),
            shards=target,
        )
    ]


def solve(
    snapshot: TelemetrySnapshot,
    policy: Optional[ControlPolicy] = None,
    history: Iterable[ActionRecord] = (),
) -> list[Action]:
    """The pure policy: actions the engine should apply, highest priority
    first, capped at ``policy.max_actions``. ``history`` is the engine's
    applied-action memory; records within ``cooldown_s`` of
    ``snapshot.generated_ts`` suppress same-subject re-decisions, and any
    remembered migration suppresses its exact reversal."""
    policy = policy or ControlPolicy()
    history = list(history)
    recent = _recent(history, snapshot.generated_ts, policy.cooldown_s)
    actions: list[Action] = []
    actions.extend(_solve_migrations(snapshot, policy, recent, history))
    # A key already moving this round must not also split: the migration
    # drops the very source copy the split would fan out from.
    claimed = frozenset(a.subject for a in actions)
    actions.extend(_solve_splits(snapshot, policy, recent, claimed))
    actions.extend(_solve_relay_orders(snapshot, policy, recent))
    actions.extend(_solve_demotions(snapshot, policy, recent))
    actions.extend(_solve_reshard(snapshot, policy, recent))
    return actions[: policy.max_actions]
