"""Frozen telemetry snapshot: the one input the placement solver reads.

The solver (control/solver.py) must be a pure function — testable with a
hand-built snapshot, no fleet, no clock. This module defines that input
shape and the builder that folds raw telemetry (``ts.traffic_matrix()``
output, ``ts.slo_report()["overload"]``, per-volume ``stats()`` dicts,
the controller's own placement/index views) into it. Everything is a
plain frozen dataclass over dicts/tuples: the builder copies, the solver
only reads.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Mapping, Optional


@dataclass(frozen=True)
class VolumeLoad:
    """One volume's recent load view. ``window_bytes``/``window_ops`` are
    the rolling one-to-two-window ledger totals (the "how loaded RIGHT
    NOW" signal), never lifetime counters."""

    volume_id: str
    host: str = ""
    entries: int = 0
    stored_bytes: int = 0
    window_ops: int = 0
    window_bytes: int = 0
    landing_inflight: int = 0
    # Spill-tier pressure (0/0 when tiering is disabled on this volume).
    tier_resident_bytes: int = 0
    tier_budget_bytes: int = 0


@dataclass(frozen=True)
class KeyStat:
    """One key's recent traffic plus its current replica placement."""

    key: str
    ops: int = 0
    bytes: int = 0
    volumes: tuple[str, ...] = ()


@dataclass(frozen=True)
class RelayView:
    """One relay channel's membership: the origin (root) volume and the
    member volumes its published versions fan out to."""

    channel: str
    root: str
    members: tuple[str, ...] = ()


@dataclass(frozen=True)
class TelemetrySnapshot:
    """Everything the solver may look at, frozen at scrape time.

    ``edges`` is host-to-host recent wire bytes (``{src: {dst: bytes}}``);
    ``hot_keys`` is hottest-first; ``cold_keys`` maps a volume id to keys
    with no recent traffic (the per-key demotion candidates);
    ``meta_inflight`` is the per-shard metadata-RPC queue-depth signal
    (``{"coord": n, "s0": n, ...}``)."""

    generated_ts: float = 0.0
    volumes: Mapping[str, VolumeLoad] = field(default_factory=dict)
    edges: Mapping[str, Mapping[str, int]] = field(default_factory=dict)
    hot_keys: tuple[KeyStat, ...] = ()
    cold_keys: Mapping[str, tuple[str, ...]] = field(default_factory=dict)
    meta_inflight: Mapping[str, int] = field(default_factory=dict)
    n_shards: int = 1
    relays: tuple[RelayView, ...] = ()
    # Trend-detector verdicts (observability/detect.py): volume id ->
    # {detector_name: result} for volumes whose SUSTAINED-kind detectors
    # are currently firing — "this is a regime change, not a burst". The
    # solver relaxes its migration hysteresis for exactly these volumes;
    # an empty mapping (no history plane, all quiet) changes nothing.
    sustained_overload: Mapping[str, Mapping[str, Any]] = field(
        default_factory=dict
    )

    def total_window_bytes(self) -> int:
        return sum(v.window_bytes for v in self.volumes.values())

    def describe(self) -> dict:
        """Compact JSON-serializable summary (rides decision events)."""
        return {
            "volumes": {
                vid: {"window_bytes": v.window_bytes, "host": v.host}
                for vid, v in self.volumes.items()
            },
            "hot_keys": [
                {"key": k.key, "bytes": k.bytes, "replicas": len(k.volumes)}
                for k in self.hot_keys[:5]
            ],
            "meta_inflight": dict(self.meta_inflight),
            "n_shards": self.n_shards,
            "sustained_overload": {
                vid: sorted(dets) for vid, dets in self.sustained_overload.items()
            },
        }


def _edge_bytes(traffic: Optional[Mapping[str, Any]]) -> dict[str, dict[str, int]]:
    """Flatten ``traffic_matrix()["edges"]`` cells to plain byte counts."""
    out: dict[str, dict[str, int]] = {}
    for src, dsts in ((traffic or {}).get("edges") or {}).items():
        row = out.setdefault(src, {})
        for dst, cell in dsts.items():
            row[dst] = row.get(dst, 0) + int(
                cell.get("bytes", 0) if isinstance(cell, Mapping) else cell
            )
    return out


def build_snapshot(
    *,
    traffic: Optional[Mapping[str, Any]] = None,
    overload: Optional[Mapping[str, Any]] = None,
    volume_stats: Optional[Mapping[str, Mapping[str, Any]]] = None,
    placement: Optional[Mapping[str, str]] = None,
    key_placement: Optional[Mapping[str, Any]] = None,
    cold_keys: Optional[Mapping[str, Any]] = None,
    n_shards: int = 1,
    relays: Optional[Mapping[str, Any]] = None,
    generated_ts: float = 0.0,
) -> TelemetrySnapshot:
    """Normalize raw telemetry into a :class:`TelemetrySnapshot`.

    Every input is optional — the builder folds whatever view the caller
    could reach (the controller engine scrapes volume ``stats()`` and its
    own index; the client API additionally has the fleet traffic matrix
    and SLO overload report) and leaves the rest empty. ``placement``
    maps volume id -> host; ``key_placement`` maps key -> iterable of
    volume ids holding a committed copy; ``relays`` maps channel ->
    ``(root_volume, members)``.
    """
    placement = dict(placement or {})
    vols: dict[str, VolumeLoad] = {}
    key_bytes: dict[str, list[int]] = {}  # key -> [ops, bytes]
    sustained: dict[str, dict[str, Any]] = {}

    def _fold_sustained(vid: str, trends: Optional[Mapping[str, Any]]) -> None:
        for name, result in (trends or {}).items():
            if result.get("active") and result.get("kind") == "sustained":
                sustained.setdefault(vid, {})[name] = dict(result)

    for vid, st in (volume_stats or {}).items():
        st = st or {}
        _fold_sustained(vid, st.get("trends"))
        ledger = st.get("ledger") or {}
        window = ledger.get("window") or {}
        over = st.get("overload") or {}
        tier = st.get("tier") or {}
        vols[vid] = VolumeLoad(
            volume_id=vid,
            host=placement.get(vid, ledger.get("host", "")),
            entries=int(st.get("entries", 0)),
            stored_bytes=int(st.get("stored_bytes", 0)),
            window_ops=int(window.get("ops", 0)),
            window_bytes=int(window.get("bytes", 0)),
            landing_inflight=int(over.get("landing_inflight", 0)),
            tier_resident_bytes=int(tier.get("resident_bytes", 0)),
            tier_budget_bytes=int(tier.get("budget_bytes", 0)),
        )
        for row in st.get("hot_keys") or ():
            stat = key_bytes.setdefault(row["key"], [0, 0])
            stat[0] += int(row.get("ops", 0))
            stat[1] += int(row.get("bytes", 0))

    # slo_report overload refines/fills the per-volume window + inflight
    # view (it already folded ledger windows fleet-side).
    over_volumes = (overload or {}).get("volumes") or {}
    for vid, entry in over_volumes.items():
        _fold_sustained(vid, entry.get("trends"))
        base = vols.get(vid) or VolumeLoad(
            volume_id=vid, host=placement.get(vid, "")
        )
        vols[vid] = VolumeLoad(
            volume_id=vid,
            host=base.host,
            entries=base.entries,
            stored_bytes=base.stored_bytes,
            window_ops=max(base.window_ops, int(entry.get("window_ops", 0))),
            window_bytes=max(
                base.window_bytes, int(entry.get("window_bytes", 0))
            ),
            landing_inflight=max(
                base.landing_inflight, int(entry.get("landing_inflight", 0))
            ),
            tier_resident_bytes=base.tier_resident_bytes,
            tier_budget_bytes=base.tier_budget_bytes,
        )
    for vid, host in placement.items():
        if vid not in vols:
            vols[vid] = VolumeLoad(volume_id=vid, host=host)

    # Per-key rolling windows from every ledger the traffic matrix saw
    # (client processes see the one-sided serves no volume can).
    for rows in ((traffic or {}).get("keys") or {}).values():
        for row in rows or ():
            stat = key_bytes.setdefault(row["key"], [0, 0])
            stat[0] += int(row.get("ops", 0))
            stat[1] += int(row.get("bytes", 0))

    kp = {
        key: tuple(vids) for key, vids in (key_placement or {}).items()
    }
    hot = tuple(
        KeyStat(key=key, ops=stat[0], bytes=stat[1], volumes=kp.get(key, ()))
        for key, stat in sorted(
            key_bytes.items(), key=lambda kv: kv[1][1], reverse=True
        )
    )

    meta_inflight = {
        str(shard): int(n)
        for shard, n in (
            (overload or {}).get("metadata_rpc_inflight") or {}
        ).items()
    }

    relay_views = tuple(
        RelayView(
            channel=channel, root=str(root), members=tuple(members)
        )
        for channel, (root, members) in sorted((relays or {}).items())
    )

    return TelemetrySnapshot(
        generated_ts=generated_ts,
        volumes=vols,
        edges=_edge_bytes(traffic),
        hot_keys=hot,
        cold_keys={
            vid: tuple(keys) for vid, keys in (cold_keys or {}).items()
        },
        meta_inflight=meta_inflight,
        n_shards=max(1, int(n_shards)),
        relays=relay_views,
        sustained_overload=sustained,
    )
