"""Client<->volume mapping strategies.

TPU-native equivalent of /root/reference/torchstore/strategy.py:29-245. A
strategy decides (a) each volume's id, computed INSIDE the volume process
from its env (rank / hostname — on a TPU pod these are the (host, chip)
coordinates), and (b) which volume a given client writes to. Strategies are
small picklable objects shared by controller, clients and volumes.
"""

from __future__ import annotations

import os
from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from typing import Optional

from torchstore_tpu.runtime import ActorRef
from torchstore_tpu.transport.buffers import TransportContext
from torchstore_tpu.utils import get_hostname


@dataclass
class StorageVolumeRef:
    """Bundle handed to transports: actor handle + volume id + the client's
    transport context + optional forced transport + remote hostname
    (/root/reference/torchstore/strategy.py:29-51)."""

    actor: ActorRef
    volume_id: str
    transport_context: TransportContext
    hostname: str = ""
    transport_type: Optional[str] = None  # forced override, else auto-ladder
    extra: dict = field(default_factory=dict)

    def is_same_host(self) -> bool:
        return self.hostname == get_hostname()

    def is_inproc(self) -> bool:
        """True when the volume actor lives in THIS process (colocated
        mode): endpoint calls are direct method invocations — transports
        must copy stored/served arrays since nothing is serialized."""
        from torchstore_tpu.runtime.actors import _inproc_actors

        return (
            self.actor.host,
            self.actor.port,
            self.actor.name,
        ) in _inproc_actors


class StoreStrategy(ABC):
    """Base strategy. ``default_transport_type`` forces one transport for
    every volume mapped by this strategy (reference
    /root/reference/torchstore/strategy.py:65-66). ``replication`` > 1
    makes every put land on that many volumes (the primary plus its ring
    successors in sorted-id order): a volume death loses no data — gets
    transparently fail over to a surviving replica — and read load spreads
    across copies. Beyond the reference, which stores every key exactly
    once."""

    def __init__(
        self,
        default_transport_type: Optional[str] = None,
        replication: int = 1,
    ) -> None:
        if replication < 1:
            raise ValueError("replication must be >= 1")
        self.default_transport_type = default_transport_type
        self.replication = replication

    @abstractmethod
    def get_volume_id(self) -> str:
        """Runs inside the volume process (reads its own rank/hostname env)."""

    @abstractmethod
    def get_client_id(self) -> str:
        """Runs inside the client process."""

    def select_volume_id(self, client_id: str, volume_ids: list[str]) -> str:
        """Which volume a client writes to. Default: the volume whose id
        matches the client id."""
        if client_id in volume_ids:
            return client_id
        raise ValueError(
            f"no storage volume for client id {client_id!r}; "
            f"volumes: {sorted(volume_ids)}"
        )

    def select_put_volume_ids(
        self, client_id: str, volume_ids: list[str]
    ) -> list[str]:
        """Every volume a put writes to: the primary plus replication-1
        ring successors (deterministic for a given volume set)."""
        primary = self.select_volume_id(client_id, volume_ids)
        if self.replication == 1:
            return [primary]
        if self.replication > len(volume_ids):
            raise ValueError(
                f"replication={self.replication} exceeds the "
                f"{len(volume_ids)} available volumes"
            )
        ring = sorted(volume_ids)
        start = ring.index(primary)
        return [ring[(start + i) % len(ring)] for i in range(self.replication)]

    def num_volumes(self, num_clients: int) -> int:
        return num_clients


class LocalRankStrategy(StoreStrategy):
    """One volume per rank; clients map to the volume of their own rank.
    Client id precedence RANK > LOCAL_RANK matches the reference
    (/root/reference/torchstore/strategy.py:164-188)."""

    def get_volume_id(self) -> str:
        return os.environ.get("RANK", os.environ.get("LOCAL_RANK", "0"))

    def get_client_id(self) -> str:
        return os.environ.get("RANK", os.environ.get("LOCAL_RANK", "0"))


class HostStrategy(StoreStrategy):
    """One volume per host (/root/reference/torchstore/strategy.py:146-161).
    ``TORCHSTORE_TPU_HOSTNAME`` overrides for tests emulating multi-host."""

    def get_volume_id(self) -> str:
        return os.environ.get("TORCHSTORE_TPU_HOSTNAME", get_hostname())

    def get_client_id(self) -> str:
        return os.environ.get("TORCHSTORE_TPU_HOSTNAME", get_hostname())


class SingletonStrategy(StoreStrategy):
    """Single shared volume (the reference's deprecated
    ControllerStorageVolumes, /root/reference/torchstore/strategy.py:191-245,
    kept here as the simple default for one-volume stores)."""

    VOLUME_ID = "0"

    def get_volume_id(self) -> str:
        return self.VOLUME_ID

    def get_client_id(self) -> str:
        return self.VOLUME_ID

    def select_volume_id(self, client_id: str, volume_ids: list[str]) -> str:
        return self.VOLUME_ID

    def num_volumes(self, num_clients: int) -> int:
        return 1
