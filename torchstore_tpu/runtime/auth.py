"""Connection authentication: HMAC challenge-response on every listener.

The actor RPC, rendezvous, bulk-data and peer-read servers all speak
pickle-or-raw protocols that must never process bytes from an unauthorized
peer (server-side ``pickle.loads`` is arbitrary code execution — the
reference delegates this surface to torch TCPStore/Monarch, which at least
do not unpickle client payloads). When ``TORCHSTORE_TPU_AUTH_SECRET`` (or
``StoreConfig.auth_secret``) is set, every accepted connection must complete
a challenge-response BEFORE its first protocol frame is parsed:

    server -> client:  b"TSAU" + 16-byte random nonce      (plain bytes)
    client -> server:  HMAC-SHA256(secret, nonce)           (32 bytes)

No pickling happens pre-auth; a wrong or missing MAC closes the connection.
The nonce makes the exchange non-replayable. With no secret configured the
exchange is skipped entirely (zero overhead, wire-compatible with older
peers) — multi-host deployments without a secret get a prominent warning
from ``spmd.initialize``.
"""

from __future__ import annotations

import asyncio
import hashlib
import hmac
import os
import socket
from typing import Optional

from torchstore_tpu.logging import get_logger

logger = get_logger("torchstore_tpu.auth")

AUTH_MAGIC = b"TSAU"
NONCE_LEN = 16
MAC_LEN = 32  # sha256
AUTH_TIMEOUT_S = 10.0


def get_secret() -> Optional[str]:
    from torchstore_tpu.config import default_config

    return default_config().auth_secret or None


def compute_mac(secret: str, nonce: bytes) -> bytes:
    return hmac.new(secret.encode(), nonce, hashlib.sha256).digest()


# ---- asyncio-streams variants (actor RPC, rendezvous) ---------------------


async def server_authenticate(
    reader: asyncio.StreamReader,
    writer: asyncio.StreamWriter,
    secret: Optional[str] = None,
) -> bool:
    """Run the server side of the challenge. True = proceed; False = the
    peer failed (connection should be closed without parsing anything)."""
    secret = secret if secret is not None else get_secret()
    if not secret:
        return True
    nonce = os.urandom(NONCE_LEN)
    writer.write(AUTH_MAGIC + nonce)
    await writer.drain()
    try:
        mac = await asyncio.wait_for(
            reader.readexactly(MAC_LEN), timeout=AUTH_TIMEOUT_S
        )
    except (asyncio.IncompleteReadError, asyncio.TimeoutError, OSError):
        logger.warning("peer closed or stalled during auth challenge")
        return False
    if not hmac.compare_digest(mac, compute_mac(secret, nonce)):
        peer = writer.get_extra_info("peername")
        logger.warning("rejecting connection from %s: bad auth MAC", peer)
        return False
    return True


async def client_authenticate(
    reader: asyncio.StreamReader,
    writer: asyncio.StreamWriter,
    secret: Optional[str] = None,
) -> None:
    secret = secret if secret is not None else get_secret()
    if not secret:
        return
    hello = await asyncio.wait_for(
        reader.readexactly(AUTH_MAGIC.__len__() + NONCE_LEN),
        timeout=AUTH_TIMEOUT_S,
    )
    if hello[: len(AUTH_MAGIC)] != AUTH_MAGIC:
        raise ConnectionError(
            "auth secret is configured but the server did not issue a "
            "challenge — peer is running without TORCHSTORE_TPU_AUTH_SECRET"
        )
    writer.write(compute_mac(secret, hello[len(AUTH_MAGIC) :]))
    await writer.drain()


# ---- raw-socket variants (bulk transport, peer-read server) ---------------


async def server_authenticate_sock(
    sock: socket.socket, secret: Optional[str] = None
) -> bool:
    secret = secret if secret is not None else get_secret()
    if not secret:
        return True
    loop = asyncio.get_running_loop()
    nonce = os.urandom(NONCE_LEN)
    try:
        await loop.sock_sendall(sock, AUTH_MAGIC + nonce)
        mac = await asyncio.wait_for(
            _recv_exactly(sock, MAC_LEN), timeout=AUTH_TIMEOUT_S
        )
    except (ConnectionError, asyncio.TimeoutError, OSError):
        return False
    if not hmac.compare_digest(mac, compute_mac(secret, nonce)):
        logger.warning("rejecting bulk connection: bad auth MAC")
        return False
    return True


async def client_authenticate_sock(
    sock: socket.socket, secret: Optional[str] = None
) -> None:
    secret = secret if secret is not None else get_secret()
    if not secret:
        return
    loop = asyncio.get_running_loop()
    hello = await asyncio.wait_for(
        _recv_exactly(sock, len(AUTH_MAGIC) + NONCE_LEN), timeout=AUTH_TIMEOUT_S
    )
    if hello[: len(AUTH_MAGIC)] != AUTH_MAGIC:
        raise ConnectionError(
            "auth secret is configured but the server did not issue a "
            "challenge — peer is running without TORCHSTORE_TPU_AUTH_SECRET"
        )
    await loop.sock_sendall(sock, compute_mac(secret, hello[len(AUTH_MAGIC) :]))


async def _recv_exactly(sock: socket.socket, n: int) -> bytes:
    loop = asyncio.get_running_loop()
    buf = bytearray()
    while len(buf) < n:
        chunk = await loop.sock_recv(sock, n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed during auth")
        buf += chunk
    return bytes(buf)
