from torchstore_tpu.runtime.actors import (
    Actor,
    ActorDiedError,
    ActorMesh,
    ActorMeshRef,
    ActorRef,
    ActorTimeoutError,
    RemoteActorError,
    close_all_connections,
    endpoint,
    get_or_spawn_singleton,
    spawn_actors,
    stop_singleton,
)

__all__ = [
    "Actor",
    "ActorDiedError",
    "ActorMesh",
    "ActorMeshRef",
    "ActorRef",
    "ActorTimeoutError",
    "RemoteActorError",
    "close_all_connections",
    "endpoint",
    "get_or_spawn_singleton",
    "spawn_actors",
    "stop_singleton",
]
