"""Process-based actor runtime: the TPU build's Monarch replacement.

The reference runs every component inside Monarch actors (Rust hyperactor:
process spawning, typed async endpoints, actor meshes — SURVEY §2.3 row 1;
/root/reference/torchstore/utils.py:128-139). This module provides the same
contract natively: ``spawn_actors`` forks N OS processes each hosting an
``Actor`` with ``@endpoint`` methods served over an asyncio TCP server;
``ActorRef``/``ActorMesh`` are picklable handles whose ``.method.call()`` /
``.call_one()`` perform multiplexed RPC with zero-copy tensor framing
(see ``serialization.py``). Works intra-host today and across DCN hosts by
binding non-loopback (``TORCHSTORE_TPU_BIND_HOST``).
"""

from __future__ import annotations

import asyncio
import multiprocessing as mp
import os
import socket
import traceback
from typing import Any, Callable, Optional

from torchstore_tpu.logging import get_logger
from torchstore_tpu.observability import context as trace_context
from torchstore_tpu.utils import spawn_logged
from torchstore_tpu.observability.tracing import span
from torchstore_tpu.runtime.serialization import (
    KIND_CONTROL,
    KIND_ERROR,
    KIND_REQUEST,
    KIND_RESPONSE,
    read_message,
    write_message,
)

logger = get_logger("torchstore_tpu.runtime")

_ENDPOINT_ATTR = "_torchstore_tpu_endpoint"

SPAWN_TIMEOUT_S = 120.0
STOP_TIMEOUT_S = 10.0


def endpoint(fn: Callable) -> Callable:
    """Mark a method remotely callable (Monarch ``@endpoint`` analog)."""
    setattr(fn, _ENDPOINT_ATTR, True)
    return fn


class Actor:
    """Base class for actors. Subclasses define ``@endpoint`` methods; each
    instance lives in its own process (one actor per proc, like the
    reference's volume/controller actors)."""


class RemoteActorError(RuntimeError):
    """Raised client-side when the remote endpoint raised; carries the remote
    traceback. The original exception is re-raised when it round-trips pickle,
    with this error attached as ``__cause__``."""


class ActorDiedError(RuntimeError):
    pass


class ActorTimeoutError(ActorDiedError):
    """An RPC exceeded its deadline: the actor is alive-but-unresponsive
    (wedged) or the transfer outlasted the configured timeout. Subclasses
    ActorDiedError so existing died-handling paths also cover wedged actors
    (the supervision role Monarch plays for the reference, SURVEY §2.3)."""


# --------------------------------------------------------------------------
# Client side: connections + refs
# --------------------------------------------------------------------------


class _Connection:
    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self.reader = reader
        self.writer = writer
        self.write_lock = asyncio.Lock()
        self.pending: dict[int, asyncio.Future] = {}
        self.next_id = 0
        self.closed = False
        self._reader_task = asyncio.ensure_future(self._read_loop())

    async def _read_loop(self) -> None:
        try:
            while True:
                kind, msg = await read_message(self.reader)
                fut = self.pending.pop(msg["id"], None)
                if fut is None or fut.done():
                    continue
                if kind == KIND_RESPONSE:
                    fut.set_result(msg["value"])
                elif kind == KIND_ERROR:
                    fut.set_exception(_rebuild_remote_error(msg))
                else:
                    fut.set_exception(RemoteActorError(f"unexpected frame kind {kind}"))
        except (asyncio.IncompleteReadError, ConnectionError, OSError) as exc:
            self._fail_all(ActorDiedError(f"actor connection lost: {exc!r}"))
        except asyncio.CancelledError:
            self._fail_all(ActorDiedError("connection closed"))
            raise
        except Exception as exc:  # pragma: no cover - defensive
            self._fail_all(RemoteActorError(f"connection reader failed: {exc!r}"))

    def _fail_all(self, exc: Exception) -> None:
        self.closed = True
        for fut in self.pending.values():
            if not fut.done():
                fut.set_exception(exc)
        self.pending.clear()

    async def request(
        self, kind: int, body: dict, timeout: Optional[float] = None
    ) -> Any:
        if self.closed:
            raise ActorDiedError("connection already closed")
        req_id = self.next_id
        self.next_id += 1
        body = dict(body, id=req_id)
        # Distributed tracing: the caller's trace context rides the frame so
        # server-side spans stitch into the same trace (client put ->
        # controller notify -> volume put share one trace_id). ~Free when no
        # trace is active (one contextvar read).
        ctx = trace_context.current()
        if ctx is not None:
            body["trace"] = ctx
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self.pending[req_id] = fut
        try:
            return await self._request_inner(fut, req_id, kind, body, timeout)
        except BaseException:
            # The awaiter is gone (cancelled mid-RPC, or the write itself
            # failed): drop the pending slot and mark any late-set
            # exception retrieved — otherwise a dying volume's _fail_all
            # sprays "exception was never retrieved" ActorDiedErrors into
            # whatever event loop hosts this connection.
            self.pending.pop(req_id, None)
            if fut.done() and not fut.cancelled():
                fut.exception()
            else:
                fut.cancel()
            raise

    async def _request_inner(
        self, fut: asyncio.Future, req_id: int, kind: int, body: dict, timeout
    ) -> Any:
        async with self.write_lock:
            await write_message(self.writer, kind, body)
        if timeout is None or timeout <= 0:
            return await fut
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            # asyncio.TimeoutError IS builtin TimeoutError (3.11+), so this
            # clause also catches a REMOTE endpoint's TimeoutError arriving
            # through the future (e.g. wait_for_committed expiry). A done,
            # uncancelled future means the response arrived — propagate the
            # remote exception; only a cancelled future is a local deadline.
            if fut.done() and not fut.cancelled():
                raise
            # A late response finds no pending future and is dropped; the
            # connection itself stays usable (requests are multiplexed).
            self.pending.pop(req_id, None)
            raise ActorTimeoutError(
                f"RPC {body.get('method', body.get('op'))!r} to "
                f"{body.get('actor')!r} timed out after {timeout:.0f}s "
                "(actor wedged, or transfer larger than the timeout allows)"
            ) from None

    async def close(self) -> None:
        self.closed = True
        self._reader_task.cancel()
        try:
            self.writer.close()
            await self.writer.wait_closed()
        except Exception:
            pass


def _rebuild_remote_error(msg: dict) -> Exception:
    remote = RemoteActorError(
        f"remote endpoint raised:\n{msg.get('traceback', '<no traceback>')}"
    )
    exc = msg.get("exception")
    if isinstance(exc, BaseException):
        exc.__cause__ = remote
        return exc
    return remote


# Actors HOSTED IN THIS PROCESS, keyed exactly as their published ActorRefs
# (host, port, name): endpoint calls on such refs bypass the RPC stack
# entirely — direct async method invocation, zero serialization (the
# colocated-volume fast path; remote processes still reach the same actor
# over its real server).
# Safe across forkserver: only the process that HOSTS an actor registers it
# here, and children never inherit a hosting role (each child registers its
# own actor in _child_async).
_inproc_actors: dict[tuple[str, int, str], Actor] = {}  # tslint: disable=fork-safety


def register_inproc(host: str, port: int, name: str, actor: Actor) -> None:
    _inproc_actors[(host, port, name)] = actor


def unregister_inproc(host: str, port: int, name: str) -> None:
    _inproc_actors.pop((host, port, name), None)


# Pools are per (event loop, address): tests run many asyncio.run loops;
# entries of closed loops are pruned so they never accumulate. Children
# fork from the forkserver HELPER, which imports this module but never
# opens a connection — the inherited pool is always empty.
_conn_pools: dict[  # tslint: disable=fork-safety
    tuple[int, str, int], tuple[asyncio.AbstractEventLoop, _Connection]
] = {}


async def get_connection(host: str, port: int) -> _Connection:
    loop = asyncio.get_running_loop()
    # Prune entries whose loop is closed. writer.close() would no-op on a
    # dead loop (transport.close() needs call_soon), and asyncio's
    # TransportSocket forbids close(); shutdown() is allowed and tears the
    # TCP connection down immediately (the server reaps its handler) — the
    # local fd itself is freed when GC collects the orphaned transport.
    for k, (pool_loop, conn) in list(_conn_pools.items()):
        if pool_loop.is_closed():
            conn.closed = True
            sock = conn.writer.get_extra_info("socket")
            if sock is not None:
                try:
                    sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
            _conn_pools.pop(k, None)
    key = (id(loop), host, port)
    entry = _conn_pools.get(key)
    if entry is not None:
        _, conn = entry
        if not conn.closed:
            return conn
    reader, writer = await asyncio.open_connection(host, port, limit=2**20)
    _set_sock_opts(writer)
    from torchstore_tpu.runtime.auth import client_authenticate

    await client_authenticate(reader, writer)
    conn = _Connection(reader, writer)
    _conn_pools[key] = (loop, conn)
    return conn


def _set_sock_opts(writer: asyncio.StreamWriter) -> None:
    sock = writer.get_extra_info("socket")
    if sock is not None:
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass


class ActorEndpointRef:
    def __init__(
        self, ref: "ActorRef", method: str, timeout: Optional[float] = None
    ):
        self._ref = ref
        self._method = method
        self._timeout = timeout

    def with_timeout(self, timeout: Optional[float]) -> "ActorEndpointRef":
        """Copy with an explicit deadline override (<=0 disables). Used for
        size-scaled data-plane timeouts; control RPCs use the ref/config
        default."""
        return ActorEndpointRef(self._ref, self._method, timeout)

    def _effective_timeout(self) -> Optional[float]:
        if self._timeout is not None:
            return self._timeout
        # isinstance guard: a ref unpickled from an older build lacks the
        # attribute and __getattr__ would hand back an endpoint ref instead.
        ref_timeout = self._ref.__dict__.get("rpc_timeout")
        if isinstance(ref_timeout, (int, float)):
            return ref_timeout
        from torchstore_tpu.config import default_config

        return default_config().rpc_timeout

    async def call_one(self, *args, **kwargs) -> Any:
        inproc = _inproc_actors.get(
            (self._ref.host, self._ref.port, self._ref.name)
        )
        if inproc is not None:
            # Same-process actor: direct invocation, no serialization. Note
            # that arguments pass BY REFERENCE — transports relying on this
            # path must copy data they store (the SHM transport does: puts
            # land in segments, never keep caller arrays).
            return await getattr(inproc, self._method)(*args, **kwargs)
        try:
            conn = await get_connection(self._ref.host, self._ref.port)
        except OSError as exc:
            raise ActorDiedError(
                f"cannot connect to actor {self._ref.name!r} at "
                f"{self._ref.host}:{self._ref.port}: {exc!r}"
            ) from exc
        return await conn.request(
            KIND_REQUEST,
            {
                "actor": self._ref.name,
                "method": self._method,
                "args": args,
                "kwargs": kwargs,
            },
            timeout=self._effective_timeout(),
        )

    # On a single ref, call == call_one (parity with Monarch's call on a
    # singleton mesh which returns a one-element result set).
    async def call(self, *args, **kwargs) -> Any:
        return await self.call_one(*args, **kwargs)


class ActorRef:
    """Picklable handle to one actor process."""

    def __init__(self, name: str, host: str, port: int, rank: int = 0):
        self.name = name
        self.host = host
        self.port = port
        self.rank = rank
        # Per-ref RPC deadline override; None defers to config.rpc_timeout.
        # Clients stamp this from their StoreConfig (see LocalClient).
        self.rpc_timeout: Optional[float] = None

    def __getattr__(self, method: str) -> ActorEndpointRef:
        if method.startswith("_"):
            raise AttributeError(method)
        return ActorEndpointRef(self, method)

    def __repr__(self) -> str:
        return f"ActorRef({self.name!r}@{self.host}:{self.port})"

    async def _control(self, op: str) -> Any:
        conn = await get_connection(self.host, self.port)
        return await conn.request(KIND_CONTROL, {"op": op, "actor": self.name})

    async def ping(self) -> bool:
        return await self._control("ping") == "pong"


class MeshEndpointRef:
    def __init__(self, mesh: "ActorMeshRef", method: str):
        self._mesh = mesh
        self._method = method

    async def call(self, *args, **kwargs) -> list[Any]:
        """Fan out to every actor in the mesh; gather results in rank order."""
        return list(
            await asyncio.gather(
                *(
                    getattr(ref, self._method).call_one(*args, **kwargs)
                    for ref in self._mesh.refs
                )
            )
        )

    async def call_one(self, *args, **kwargs) -> Any:
        if len(self._mesh.refs) != 1:
            raise ValueError(
                f"call_one on a mesh of size {len(self._mesh.refs)}; "
                "index the mesh first"
            )
        return await getattr(self._mesh.refs[0], self._method).call_one(
            *args, **kwargs
        )


class ActorMeshRef:
    """Picklable handle to a mesh of actors (rank-ordered)."""

    def __init__(self, refs: list[ActorRef]):
        self.refs = refs

    def __getattr__(self, method: str) -> MeshEndpointRef:
        if method.startswith("_") or method == "refs":
            raise AttributeError(method)
        return MeshEndpointRef(self, method)

    def __getitem__(self, idx) -> "ActorMeshRef":
        if isinstance(idx, int):
            return ActorMeshRef([self.refs[idx]])
        return ActorMeshRef(list(self.refs[idx]))

    def __len__(self) -> int:
        return len(self.refs)


class ActorMesh(ActorMeshRef):
    """Owner-side mesh: also holds the OS process handles for shutdown."""

    def __init__(self, refs: list[ActorRef], processes: list[mp.Process]):
        super().__init__(refs)
        self._processes = processes

    def __getstate__(self):
        return {"refs": self.refs}

    def __setstate__(self, state):
        self.refs = state["refs"]
        self._processes = []

    async def stop(self) -> None:
        for ref in self.refs:
            try:
                await asyncio.wait_for(ref._control("stop"), timeout=STOP_TIMEOUT_S)
            except Exception:
                pass
        loop = asyncio.get_running_loop()
        for proc in self._processes:
            await loop.run_in_executor(None, proc.join, STOP_TIMEOUT_S)
            if proc.is_alive():
                logger.warning("terminating unresponsive actor process %s", proc.pid)
                proc.terminate()
                await loop.run_in_executor(None, proc.join, 5.0)
        self._processes = []


# --------------------------------------------------------------------------
# Server side
# --------------------------------------------------------------------------


class ActorServer:
    def __init__(self) -> None:
        self.actors: dict[str, Actor] = {}
        self.stop_event = asyncio.Event()
        self._server: Optional[asyncio.AbstractServer] = None
        self.port: Optional[int] = None
        self._client_writers: set[asyncio.StreamWriter] = set()

    def register(self, name: str, actor: Actor) -> None:
        self.actors[name] = actor

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        self._server = await asyncio.start_server(
            self._handle_client, host, port, limit=2**20
        )
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def _handle_client(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        from torchstore_tpu.runtime.auth import server_authenticate

        # No frame is parsed (= nothing unpickled) before the peer proves
        # knowledge of the shared secret.
        if not await server_authenticate(reader, writer):
            try:
                writer.close()
            except Exception:
                pass
            return
        _set_sock_opts(writer)
        self._client_writers.add(writer)
        write_lock = asyncio.Lock()
        tasks: set[asyncio.Task] = set()
        try:
            while True:
                kind, msg = await read_message(reader)
                # _dispatch reports endpoint errors to the caller itself;
                # spawn_logged is the belt-and-braces for a failure in that
                # reporting path (and retains the task until done).
                spawn_logged(
                    self._dispatch(kind, msg, writer, write_lock),
                    name="actor.dispatch",
                    tasks=tasks,
                    log=logger,
                )
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            self._client_writers.discard(writer)
            for task in tasks:
                task.cancel()
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    async def _dispatch(
        self,
        kind: int,
        msg: dict,
        writer: asyncio.StreamWriter,
        write_lock: asyncio.Lock,
    ) -> None:
        req_id = msg.get("id")
        try:
            if kind == KIND_CONTROL:
                value = await self._handle_control(msg)
            elif kind == KIND_REQUEST:
                value = await self._handle_request(msg)
            else:
                raise RemoteActorError(f"unknown frame kind {kind}")
            async with write_lock:
                await write_message(writer, KIND_RESPONSE, {"id": req_id, "value": value})
        except asyncio.CancelledError:
            raise
        except BaseException as exc:  # noqa: BLE001 - forwarded to caller
            tb = traceback.format_exc()
            payload: dict[str, Any] = {"id": req_id, "traceback": tb}
            try:
                import pickle

                pickle.dumps(exc)
                payload["exception"] = exc
            except Exception:
                payload["exception"] = None
            try:
                async with write_lock:
                    await write_message(writer, KIND_ERROR, payload)
            except Exception:
                logger.exception("failed to report endpoint error to caller")

    async def _handle_control(self, msg: dict) -> Any:
        op = msg["op"]
        if op == "ping":
            # Faultpoint: arming "actor.ping" in a process makes ITS
            # heartbeat responses raise/stall — the handle the health
            # supervisor's quarantine tests use to simulate a wedged-but-
            # alive volume without blocking its event loop.
            from torchstore_tpu import faults

            await faults.afire("actor.ping")
            return "pong"
        if op == "stop":
            # Respond first; the serve loop exits after this dispatch returns.
            asyncio.get_running_loop().call_soon(self.stop_event.set)
            return "stopping"
        if op == "list":
            return sorted(self.actors)
        raise RemoteActorError(f"unknown control op {op!r}")

    async def _handle_request(self, msg: dict) -> Any:
        actor = self.actors.get(msg["actor"])
        if actor is None:
            raise RemoteActorError(
                f"no actor {msg['actor']!r} in this process "
                f"(have: {sorted(self.actors)})"
            )
        method = getattr(type(actor), msg["method"], None)
        if method is None or not getattr(method, _ENDPOINT_ATTR, False):
            raise RemoteActorError(
                f"{type(actor).__name__}.{msg['method']} is not an @endpoint"
            )
        # Adopt the caller's trace context (if any) for the whole dispatch:
        # the rpc span and everything the endpoint emits (transport spans,
        # nested RPCs to other actors) carry the client's trace_id and hang
        # off the client-side span that issued this request.
        with trace_context.activate(msg.get("trace")):
            with span(f"rpc/{msg['method']}", actor=msg["actor"]):
                result = method(actor, *msg["args"], **msg["kwargs"])
                if asyncio.iscoroutine(result):
                    result = await result
        return result

    async def serve_until_stopped(self) -> None:
        await self.stop_event.wait()
        await self.close()

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
        # Drop live client connections: py3.12's Server.wait_closed() waits
        # for handlers, which would otherwise block forever on open streams.
        for writer in list(self._client_writers):
            try:
                writer.close()
            except Exception:
                pass
        if self._server is not None:
            try:
                await asyncio.wait_for(self._server.wait_closed(), timeout=2.0)
            except asyncio.TimeoutError:
                pass


# --------------------------------------------------------------------------
# Spawning
# --------------------------------------------------------------------------


def _child_main(pipe, actor_cls, name: str, args: tuple, kwargs: dict, env: dict) -> None:
    # ``env`` is the COMPLETE framework environment for this child. The
    # forkserver parent snapshots os.environ at ITS start, so children can
    # inherit stale TORCHSTORE_TPU_* values from whatever test/store first
    # spawned an actor (e.g. an auth secret that was since unset) — remove
    # anything the spawner did not explicitly pass, then apply.
    for key in list(os.environ):
        if key.startswith("TORCHSTORE_TPU_") and key not in env:
            del os.environ[key]
    os.environ.update(env)
    from torchstore_tpu import config as _config_mod

    _config_mod._default_config = None  # re-seed from the corrected env
    # Re-arm env-gated observability against the CORRECTED env: the
    # forkserver's preload imported torchstore with whatever env IT started
    # under, and its dumper/exporter threads did not survive the fork.
    from torchstore_tpu import observability as _obs

    _obs.reinit_after_fork()
    # Landing-copy pool threads do not survive the fork either; drop the
    # inherited (dead) executor so the first landing re-creates a live one.
    from torchstore_tpu.transport import landing as _landing

    _landing.reinit_after_fork()
    # Re-arm faultpoints from the CORRECTED env: the forkserver's module
    # state carries whatever TORCHSTORE_TPU_FAULTPOINTS it imported under,
    # not what this child was spawned with.
    from torchstore_tpu import faults as _faults

    _faults.reinit_after_fork()
    # Re-read the bulk transport's emulated-bandwidth knob (bench/test DCN
    # emulation) from the corrected env for the same reason.
    from torchstore_tpu.transport import bulk as _bulk

    _bulk.reinit_after_fork()
    try:
        asyncio.run(_child_async(pipe, actor_cls, name, args, kwargs))
    except KeyboardInterrupt:
        pass
    finally:
        # Multiprocessing children exit via os._exit, which skips atexit —
        # the trace collector's and metrics dumper's exit hooks would never
        # fire in actor processes. Flush both explicitly so a volume's
        # spans/counters survive a clean stop (crash paths still lose at
        # most the last partial buffer; the streaming trace format and
        # periodic dumps keep earlier data loadable).
        try:
            from torchstore_tpu.observability import metrics as _obs_metrics
            from torchstore_tpu.observability.tracing import flush_trace

            flush_trace()
            _obs_metrics.dump_metrics()
        except Exception:
            pass


async def _child_async(pipe, actor_cls, name: str, args: tuple, kwargs: dict) -> None:
    server = ActorServer()
    try:
        actor = actor_cls(*args, **kwargs)
        server.register(name, actor)
        bind_host = os.environ.get("TORCHSTORE_TPU_BIND_HOST", "127.0.0.1")
        port = await server.start(bind_host)
        # Refs must carry a REACHABLE address: a 0.0.0.0 bind (multi-host
        # DCN) advertises the real hostname/IP instead.
        advertise = os.environ.get("TORCHSTORE_TPU_ADVERTISE_HOST")
        if advertise is None:
            advertise = (
                socket.gethostname() if bind_host in ("0.0.0.0", "::") else bind_host
            )
        pipe.send(("ready", advertise, port))
    except BaseException:
        pipe.send(("error", traceback.format_exc(), None))
        raise
    finally:
        pipe.close()
    await server.serve_until_stopped()


_ctx: Optional[mp.context.BaseContext] = None


def _mp_context() -> mp.context.BaseContext:
    # 'forkserver' keeps children clear of any jax/TPU state in the parent
    # (the fork server is a fresh process, never the jax-holding parent) while
    # amortizing interpreter+numpy startup (~2.5s on this image) across all
    # actor spawns. 'spawn' remains available via TORCHSTORE_TPU_MP_CONTEXT.
    global _ctx
    if _ctx is None:
        method = os.environ.get("TORCHSTORE_TPU_MP_CONTEXT", "forkserver")
        _ctx = mp.get_context(method)
        if method == "forkserver":
            _ctx.set_forkserver_preload(["torchstore_tpu.runtime"])
            # Launch the forkserver NOW with env-gated observability
            # stripped: the preload imports torchstore_tpu in the helper
            # process, which would otherwise start its own metrics dumper /
            # HTTP exporter for an idle registry — and could win the claim
            # on the configured dump path or port. Actor children re-arm
            # from their corrected env in _child_main (reinit_after_fork).
            from torchstore_tpu.observability import (
                ENV_METRICS_DUMP,
                ENV_METRICS_PORT,
                ENV_TRACE,
            )

            saved = {}
            for key in (ENV_METRICS_DUMP, ENV_METRICS_PORT, ENV_TRACE):
                if key in os.environ:
                    saved[key] = os.environ.pop(key)
            try:
                from multiprocessing import forkserver as _forkserver

                _forkserver.ensure_running()
            except Exception:  # noqa: BLE001 - lazy start on first spawn
                pass
            finally:
                os.environ.update(saved)
    return _ctx


async def spawn_actors(
    num_actors: int,
    actor_cls: type,
    name: str,
    *args,
    env_fn: Optional[Callable[[int], dict[str, str]]] = None,
    **kwargs,
) -> ActorMesh:
    """Spawn ``num_actors`` processes each hosting one ``actor_cls`` instance.

    Each child gets rank env vars (``RANK``/``LOCAL_RANK``/``WORLD_SIZE``/
    ``LOCAL_WORLD_SIZE``) so strategies can derive volume ids the way the
    reference does from torchrun env (/root/reference/torchstore/strategy.py:164-188).
    """
    ctx = _mp_context()
    loop = asyncio.get_running_loop()
    procs: list[mp.Process] = []
    pipes = []
    # The whole process tree must share one trace run id BEFORE env capture
    # (see observability/tracing.py: sibling-vs-stale-run arbitration).
    from torchstore_tpu.observability.tracing import ensure_run_id

    ensure_run_id()
    # Forward store handles and config to children explicitly: forkserver
    # children inherit the fork server's env (snapshotted at its start), not
    # the parent's current env.
    inherited = {
        k: v for k, v in os.environ.items() if k.startswith("TORCHSTORE_TPU_")
    }
    for rank in range(num_actors):
        env = dict(inherited)
        env.update(
            {
                "RANK": str(rank),
                "LOCAL_RANK": str(rank),
                "WORLD_SIZE": str(num_actors),
                "LOCAL_WORLD_SIZE": str(num_actors),
            }
        )
        if env_fn is not None:
            env.update(env_fn(rank))
        parent_conn, child_conn = ctx.Pipe()
        proc = ctx.Process(
            target=_child_main,
            args=(child_conn, actor_cls, f"{name}_{rank}", args, kwargs, env),
            daemon=True,
            name=f"ts-{name}-{rank}",
        )
        proc.start()
        child_conn.close()
        procs.append(proc)
        pipes.append(parent_conn)

    refs: list[ActorRef] = []
    try:
        for rank, (proc, pipe) in enumerate(zip(procs, pipes)):
            msg = await loop.run_in_executor(
                None, _pipe_recv, pipe, proc, SPAWN_TIMEOUT_S
            )
            status, a, b = msg
            if status != "ready":
                raise ActorDiedError(
                    f"actor {name}_{rank} failed during spawn:\n{a}"
                )
            refs.append(ActorRef(f"{name}_{rank}", a, b, rank=rank))
    except BaseException:
        for proc in procs:
            if proc.is_alive():
                proc.terminate()
        for proc in procs:
            await loop.run_in_executor(None, proc.join, 5.0)
            if proc.is_alive():  # SIGTERM ignored mid-start: escalate
                proc.kill()
                await loop.run_in_executor(None, proc.join, 2.0)
        raise
    return ActorMesh(refs, procs)


def _pipe_recv(pipe, proc: mp.Process, timeout: float):
    if not pipe.poll(timeout):
        if not proc.is_alive():
            raise ActorDiedError(
                f"actor process exited during spawn (exitcode={proc.exitcode})"
            )
        raise ActorDiedError(f"actor spawn timed out after {timeout}s")
    return pipe.recv()


# --------------------------------------------------------------------------
# Singleton actors (get_or_spawn_controller analog)
# --------------------------------------------------------------------------

# Owner-side registry only: actor children never spawn singletons (the
# spawner owns process handles; children hold plain ActorRefs from env).
_singletons: dict[str, ActorMesh] = {}  # tslint: disable=fork-safety


async def get_or_spawn_singleton(name: str, actor_cls: type, *args, **kwargs) -> ActorRef:
    """Process-local singleton actor registry (Monarch
    ``get_or_spawn_controller`` analog, /root/reference/torchstore/api.py:118-123).
    Cross-rank sharing of the returned (picklable) ref is the SPMD layer's job."""
    mesh = _singletons.get(name)
    if mesh is None:
        mesh = await spawn_actors(1, actor_cls, name, *args, **kwargs)
        _singletons[name] = mesh
    return mesh.refs[0]


async def stop_singleton(name: str) -> None:
    mesh = _singletons.pop(name, None)
    if mesh is not None:
        await mesh.stop()


async def close_all_connections() -> None:
    for _, conn in list(_conn_pools.values()):
        await conn.close()
    _conn_pools.clear()
