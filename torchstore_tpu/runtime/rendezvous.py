"""Rendezvous KV service: the TCPStore replacement.

The reference bootstraps SPMD worlds over torch's C++ ``TCPStore``
(/root/reference/torchstore/spmd.py:310-326, transport/gloo.py:62-92). This
is the native-runtime equivalent: a tiny asyncio KV server with blocking
gets and atomic counters — enough for handle broadcast, barriers, and
connection bootstrap. Rank 0 hosts it on MASTER_ADDR:MASTER_PORT; every rank
connects as a client.

Ops: SET key value | GET key (blocks until set) | ADD key delta (atomic,
returns new value) | CHECK key (non-blocking presence).
"""

from __future__ import annotations

import asyncio
import pickle
from typing import Any, Optional

from torchstore_tpu.logging import get_logger
from torchstore_tpu.utils import spawn_logged
from torchstore_tpu.runtime.serialization import (
    KIND_REQUEST,
    KIND_RESPONSE,
    read_message,
    write_message,
)

logger = get_logger("torchstore_tpu.rendezvous")

DEFAULT_TIMEOUT_S = 300.0


class RendezvousServer:
    def __init__(self) -> None:
        self.kv: dict[str, Any] = {}
        self.counters: dict[str, int] = {}
        self._changed = asyncio.Condition()
        self._server: Optional[asyncio.AbstractServer] = None
        self._writers: set = set()
        self.port: Optional[int] = None

    async def start(self, host: str = "0.0.0.0", port: int = 0) -> int:
        self._server = await asyncio.start_server(self._handle, host, port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def _handle(self, reader, writer) -> None:
        from torchstore_tpu.runtime.auth import server_authenticate

        if not await server_authenticate(reader, writer):
            try:
                writer.close()
            except Exception:
                pass
            return
        self._writers.add(writer)
        write_lock = asyncio.Lock()
        tasks: set[asyncio.Task] = set()
        try:
            while True:
                _, msg = await read_message(reader)
                # _dispatch replies with repr(exc) on op failures itself;
                # spawn_logged retains the task and surfaces failures in
                # that reply path instead of dropping them.
                spawn_logged(
                    self._dispatch(msg, writer, write_lock),
                    name="rendezvous.dispatch",
                    tasks=tasks,
                )
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            self._writers.discard(writer)
            for task in tasks:
                task.cancel()
            try:
                writer.close()
            except Exception:
                pass

    async def _dispatch(self, msg: dict, writer, write_lock) -> None:
        op = msg["op"]
        try:
            from torchstore_tpu import faults

            await faults.afire("rendezvous.dispatch")
            if op == "set":
                async with self._changed:
                    self.kv[msg["key"]] = msg["value"]
                    self._changed.notify_all()
                value = True
            elif op == "get":
                async with self._changed:
                    while msg["key"] not in self.kv:
                        await self._changed.wait()
                    value = self.kv[msg["key"]]
            elif op == "add":
                async with self._changed:
                    self.counters[msg["key"]] = (
                        self.counters.get(msg["key"], 0) + msg["delta"]
                    )
                    value = self.counters[msg["key"]]
                    self._changed.notify_all()
            elif op == "wait_counter":
                async with self._changed:
                    while self.counters.get(msg["key"], 0) < msg["target"]:
                        await self._changed.wait()
                    value = self.counters[msg["key"]]
            elif op == "check":
                value = msg["key"] in self.kv
            else:
                raise ValueError(f"unknown rendezvous op {op!r}")
            ok = True
        except asyncio.CancelledError:
            raise
        except Exception as exc:
            value, ok = repr(exc), False
        async with write_lock:
            await write_message(
                writer, KIND_RESPONSE, {"id": msg["id"], "value": value, "ok": ok}
            )

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            for writer in list(self._writers):
                try:
                    writer.close()
                except Exception:
                    pass
            try:
                await asyncio.wait_for(self._server.wait_closed(), timeout=2.0)
            except asyncio.TimeoutError:
                pass
            self._server = None


class RendezvousClient:
    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self._reader = None
        self._writer = None
        self._lock = asyncio.Lock()
        self._next_id = 0
        self._pending: dict[int, asyncio.Future] = {}
        self._reader_task: Optional[asyncio.Task] = None

    async def connect(self, timeout: float = DEFAULT_TIMEOUT_S) -> None:
        # Rank 0's server may not be up yet: retry under the unified
        # RetryPolicy (caller's timeout = the deadline budget), gentle
        # start + jitter so a whole world connecting at once doesn't
        # hammer the listener in lockstep.
        from torchstore_tpu.config import RetryPolicy

        policy = RetryPolicy(
            base_s=0.2, max_s=1.0, multiplier=1.5, deadline_s=timeout
        )
        deadline = policy.start()
        attempt = 0
        while True:
            try:
                self._reader, self._writer = await asyncio.open_connection(
                    self.host, self.port
                )
                break
            except (ConnectionError, OSError):
                if not policy.should_retry(attempt, deadline):
                    raise
                await asyncio.sleep(policy.backoff(attempt))
                attempt += 1
        from torchstore_tpu.runtime.auth import client_authenticate

        await client_authenticate(self._reader, self._writer)
        self._reader_task = asyncio.ensure_future(self._read_loop())

    async def _read_loop(self) -> None:
        try:
            while True:
                _, msg = await read_message(self._reader)
                fut = self._pending.pop(msg["id"], None)
                if fut is not None and not fut.done():
                    if msg.get("ok", True):
                        fut.set_result(msg["value"])
                    else:
                        fut.set_exception(RuntimeError(msg["value"]))
        except (asyncio.IncompleteReadError, ConnectionError, OSError) as exc:
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionError(f"rendezvous lost: {exc!r}"))
            self._pending.clear()
        except asyncio.CancelledError:
            raise

    async def _request(self, op: str, timeout: float = DEFAULT_TIMEOUT_S, **body):
        req_id = self._next_id
        self._next_id += 1
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        async with self._lock:
            await write_message(
                self._writer, KIND_REQUEST, {"op": op, "id": req_id, **body}
            )
        return await asyncio.wait_for(fut, timeout=timeout)

    async def set(self, key: str, value: Any) -> None:
        await self._request("set", key=key, value=value)

    async def get(self, key: str, timeout: float = DEFAULT_TIMEOUT_S) -> Any:
        return await self._request("get", timeout=timeout, key=key)

    async def add(self, key: str, delta: int = 1) -> int:
        return await self._request("add", key=key, delta=delta)

    async def wait_counter(
        self, key: str, target: int, timeout: float = DEFAULT_TIMEOUT_S
    ) -> int:
        return await self._request(
            "wait_counter", timeout=timeout, key=key, target=target
        )

    async def check(self, key: str) -> bool:
        return await self._request("check", key=key)

    async def barrier(
        self, name: str, world_size: int, timeout: float = DEFAULT_TIMEOUT_S
    ) -> None:
        await self.add(f"barrier/{name}", 1)
        await self.wait_counter(f"barrier/{name}", world_size, timeout=timeout)

    async def close(self) -> None:
        if self._reader_task is not None:
            self._reader_task.cancel()
        if self._writer is not None:
            try:
                self._writer.close()
                await self._writer.wait_closed()
            except Exception:
                pass


def pickle_handle(obj: Any) -> bytes:
    return pickle.dumps(obj)


def unpickle_handle(raw: bytes) -> Any:
    return pickle.loads(raw)
