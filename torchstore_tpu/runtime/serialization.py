"""Wire serialization for the actor runtime.

Replaces Monarch's hyperactor codec (SURVEY §2.3 row 1). Messages are pickled
with protocol 5 and **out-of-band buffers**: large tensor payloads (numpy
arrays riding a ``Request`` or transport buffer) are not copied into the
pickle stream — their memory is framed separately and written directly to the
socket, and reconstructed zero-copy on the receiving side. There is no frame
size limit (the reference had to raise ``HYPERACTOR_CODEC_MAX_FRAME_LENGTH``
for big tensors, /root/reference/torchstore/__init__.py:37-44; this codec
streams arbitrarily large frames in chunks).

Frame layout:
    u32 magic | u8 kind | u64 payload_len | u32 nbufs | u64 buf_len * nbufs
    | payload bytes | buffer bytes...
"""

from __future__ import annotations

import asyncio
import pickle
import struct
from typing import Any

MAGIC = 0x7E5701AB

_HEADER = struct.Struct("<IBQI")
_U64 = struct.Struct("<Q")

# Message kinds.
KIND_REQUEST = 0
KIND_RESPONSE = 1
KIND_ERROR = 2
KIND_CONTROL = 3

# Streaming chunk size for writing very large buffers.
_WRITE_CHUNK = 4 * 1024 * 1024


class SerializationError(RuntimeError):
    pass


def dumps(obj: Any) -> tuple[bytes, list[pickle.PickleBuffer]]:
    buffers: list[pickle.PickleBuffer] = []
    payload = pickle.dumps(obj, protocol=5, buffer_callback=buffers.append)
    return payload, buffers


def loads(payload: bytes, buffers: list[bytes | bytearray | memoryview]) -> Any:
    return pickle.loads(payload, buffers=buffers)


async def write_message(writer: asyncio.StreamWriter, kind: int, obj: Any) -> None:
    payload, buffers = dumps(obj)
    raws = [b.raw() for b in buffers]
    header = bytearray(_HEADER.pack(MAGIC, kind, len(payload), len(raws)))
    for raw in raws:
        header += _U64.pack(raw.nbytes)
    writer.write(bytes(header))
    writer.write(payload)
    for raw in raws:
        flat = raw.cast("B") if raw.ndim != 1 or raw.format != "B" else raw
        if flat.nbytes <= _WRITE_CHUNK:
            writer.write(flat)
        else:
            for off in range(0, flat.nbytes, _WRITE_CHUNK):
                writer.write(flat[off : off + _WRITE_CHUNK])
                await writer.drain()
    await writer.drain()
    for b in buffers:
        b.release()


async def read_message(reader: asyncio.StreamReader) -> tuple[int, Any]:
    header = await reader.readexactly(_HEADER.size)
    magic, kind, payload_len, nbufs = _HEADER.unpack(header)
    if magic != MAGIC:
        raise SerializationError(f"bad frame magic {magic:#x}")
    buf_lens = []
    if nbufs:
        lens_raw = await reader.readexactly(_U64.size * nbufs)
        buf_lens = [
            _U64.unpack_from(lens_raw, i * _U64.size)[0] for i in range(nbufs)
        ]
    payload = await reader.readexactly(payload_len)
    buffers: list[bytearray] = []
    for blen in buf_lens:
        buf = bytearray(blen)
        await _read_into(reader, memoryview(buf))
        buffers.append(buf)
    return kind, loads(payload, buffers)


async def _read_into(reader: asyncio.StreamReader, view: memoryview) -> None:
    # readexactly would allocate+copy; read into the target in chunks instead.
    remaining = view.nbytes
    pos = 0
    while remaining:
        chunk = await reader.read(min(remaining, _WRITE_CHUNK))
        if not chunk:
            raise asyncio.IncompleteReadError(bytes(view[:pos]), view.nbytes)
        view[pos : pos + len(chunk)] = chunk
        pos += len(chunk)
        remaining -= len(chunk)
