"""Ring attention: sequence-parallel attention for long contexts.

The store moves weights; long-context *activations* need the sequence axis
sharded across devices. This op computes exact attention when q/k/v are
sequence-sharded over an ``sp`` mesh axis: each device keeps its query block
resident and rotates k/v blocks around the ring with ``ppermute`` (one hop
per step — the transfers ride ICI neighbor links), accumulating with a
numerically-stable online softmax (blockwise/flash-style). Memory per device
is O(seq/n) instead of O(seq), and the k/v rotation overlaps with block
compute under XLA's latency-hiding scheduler.

Use inside ``shard_map`` (see ``ring_attention_sharded`` for the wrapped
version). Matches dense attention bit-for-block (see
tests/test_ring_attention.py differential tests).
"""

from __future__ import annotations

import math


def ring_attention(q, k, v, axis_name: str, causal: bool = False, impl: str = "auto"):
    """Per-shard attention bodies. Shapes (inside shard_map, per device):
    q: (batch, seq_local, heads, head_dim), k/v: (batch, seq_local,
    kv_heads, head_dim) -> (batch, seq_local, heads, head_dim). GQA is
    handled natively — the ring rotates the UNREPEATED kv blocks, so GQA's
    bandwidth/memory saving survives sequence parallelism.

    ``impl`` selects the per-hop block body:

    - ``"fused"``: the pallas flash kernel (``flash_attention_stats``) —
      scores stream through VMEM tiles, never materializing the
      (sq_local, sk_local) score tensor in HBM; hops merge via the
      standard online-softmax rescale.
    - ``"einsum"``: the reference-free dense block body (materializes
      per-hop scores; any shape).
    - ``"auto"`` (default): fused when the per-device shapes tile
      (``flash_stats_eligible``), einsum otherwise.
    """
    from torchstore_tpu.ops.flash_attention import flash_stats_eligible

    # The fused body's causal hop mask is all-or-nothing per hop, which is
    # exact only when q and kv rings carry EQUAL per-device lengths (the
    # self-attention shape); unequal lengths make some hops partially
    # visible and need the einsum body's global-position mask.
    fused_exact = not causal or q.shape[1] == k.shape[1]
    if impl == "fused":
        if not fused_exact:
            raise ValueError(
                "impl='fused' causal ring attention requires equal q/kv "
                f"sequence lengths per device (got {q.shape[1]} vs "
                f"{k.shape[1]}); use impl='auto' or 'einsum'"
            )
        return _ring_fused(q, k, v, axis_name, causal)
    if (
        impl == "auto"
        and fused_exact
        and flash_stats_eligible(q.shape, k.shape)
    ):
        return _ring_fused(q, k, v, axis_name, causal)
    return _ring_einsum(q, k, v, axis_name, causal)


def _ring_fused(q, k, v, axis_name: str, causal: bool):
    """Ring body with the fused flash kernel per hop: each incoming kv
    block runs ``flash_attention_stats`` (unnormalized accumulator +
    online-softmax stats, computed blockwise in VMEM) and hops merge with
    the flash rescale. Causal hops from ring positions AFTER this device
    are fully masked (zero contribution); the diagonal (own) block applies
    the in-kernel causal mask. Same O(seq/n) memory as the einsum body but
    without ever materializing a (sq, sk) score tensor."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from torchstore_tpu.ops.flash_attention import flash_attention_stats

    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    perm = [(j, (j + 1) % n) for j in range(n)]
    NEG = jnp.float32(-1e30)

    def merge(carry, contrib):
        o, m, l = carry
        acc_j, m_j, l_j = contrib
        m_new = jnp.maximum(m, m_j)
        c1 = jnp.exp(m - m_new)
        c2 = jnp.exp(m_j - m_new)
        return (
            o * c1[..., None] + acc_j * c2[..., None],
            m_new,
            l * c1 + l_j * c2,
        )

    def step(i, carry):
        o, m, l, k_cur, v_cur = carry
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        acc_j, m_j, l_j = flash_attention_stats(
            q, k_cur, v_cur, causal_diag=False
        )
        if causal:
            # k_cur originated on ring position (my_idx - i) mod n; blocks
            # from positions after ours are entirely in the future — mask
            # the whole contribution (same cost profile as the einsum
            # body, which also computes-then-masks; no data-dependent
            # control flow inside the compiled loop).
            valid = ((my_idx - i) % n) < my_idx
            acc_j = jnp.where(valid, acc_j, 0.0)
            m_j = jnp.where(valid, m_j, NEG)
            l_j = jnp.where(valid, l_j, 0.0)
        o, m, l = merge((o, m, l), (acc_j, m_j, l_j))
        return o, m, l, k_cur, v_cur

    # Step 0: the device's own block — in-kernel causal diagonal mask.
    o0, m0, l0 = flash_attention_stats(q, k, v, causal_diag=causal)
    o, m, l, _, _ = lax.fori_loop(1, n, step, (o0, m0, l0, k, v))
    out = o / jnp.maximum(l, 1e-30)[..., None]  # (b, h, sq, d)
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def _ring_einsum(q, k, v, axis_name: str, causal: bool):
    """Dense (einsum) block body: grouped-GQA online softmax materializing
    one (sq, sk) score block per hop. Shape-agnostic fallback for sizes
    the pallas kernel can't tile."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    sk, hk = k.shape[1], k.shape[2]
    if h % hk != 0:
        raise ValueError(f"q heads ({h}) must be a multiple of kv heads ({hk})")
    g = h // hk
    scale = 1.0 / math.sqrt(d)
    perm = [(j, (j + 1) % n) for j in range(n)]

    # Grouped layout: (b, sq, hk, g, d) so kv heads broadcast per group.
    q32 = q.astype(jnp.float32).reshape(b, sq, hk, g, d)
    NEG = jnp.float32(-1e30)

    q_pos = my_idx * sq + jnp.arange(sq)  # global query positions

    def accumulate(carry, k_cur, v_cur, i):
        o, m, l = carry  # o: (b,hk,g,sq,d); m,l: (b,hk,g,sq)
        # k_cur originated on device (my_idx - i) mod n.
        src = (my_idx - i) % n
        s = jnp.einsum(
            "bqhgd,bkhd->bhgqk", q32, k_cur.astype(jnp.float32)
        ) * scale
        if causal:
            k_pos = src * sk + jnp.arange(sk)
            mask = q_pos[:, None] >= k_pos[None, :]  # (sq, sk)
            s = jnp.where(mask[None, None, None], s, NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1)
        o = o * corr[..., None] + jnp.einsum(
            "bhgqk,bkhd->bhgqd", p, v_cur.astype(jnp.float32)
        )
        return o, m_new, l

    def step(i, carry):
        o, m, l, k_cur, v_cur = carry
        # Rotate FIRST (steps 1..n-1): exactly n-1 ppermutes total — the
        # final block's k/v are never rotated into oblivion.
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        o, m, l = accumulate((o, m, l), k_cur, v_cur, i)
        return o, m, l, k_cur, v_cur

    o0 = jnp.zeros((b, hk, g, sq, d), jnp.float32)
    m0 = jnp.full((b, hk, g, sq), NEG)
    l0 = jnp.zeros((b, hk, g, sq), jnp.float32)
    o0, m0, l0 = (_mark_varying(lax, x, axis_name) for x in (o0, m0, l0))
    # Step 0: own (unrotated) block, outside the loop.
    o0, m0, l0 = accumulate((o0, m0, l0), k, v, 0)
    o, m, l, _, _ = lax.fori_loop(1, n, step, (o0, m0, l0, k, v))
    out = o / jnp.maximum(l, 1e-30)[..., None]  # (b,hk,g,sq,d)
    out = jnp.transpose(out, (0, 3, 1, 2, 4)).reshape(b, sq, h, d)
    return out.astype(q.dtype)


def _mark_varying(lax, x, axis_name: str):
    """Newer shard_map tracks device-varying types through scan carries;
    constant initializers must be marked varying over the ring axis."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axis_name, to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, axis_name)
    return x  # older jax: no varying-type tracking


def ring_attention_sharded(
    q, k, v, mesh, axis_name: str = "sp", causal: bool = False, impl: str = "auto"
):
    """jit-compiled ring attention over ``mesh``'s ``axis_name`` ring: global
    (batch, seq, heads, head_dim) arrays sequence-sharded on entry/exit."""
    from torchstore_tpu.ops._sharded import make_sharded_attention

    return make_sharded_attention(
        ring_attention, mesh, axis_name, causal, impl=impl,
        relax_vma=impl != "einsum",
    )(q, k, v)
