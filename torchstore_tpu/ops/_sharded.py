"""Shared shard_map wrapper for the sequence-parallel attention ops."""

from __future__ import annotations

import functools


@functools.cache
def make_sharded_attention(body, mesh, axis_name: str, causal: bool):
    """jit(shard_map(body)) over (q, k, v) sequence-sharded on
    ``axis_name``. Cached per (body, mesh, axis, causal) so repeat calls
    reuse the compiled executable."""
    import jax
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    spec = P(None, axis_name, None, None)
    fn = shard_map(
        functools.partial(body, axis_name=axis_name, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return jax.jit(fn)
