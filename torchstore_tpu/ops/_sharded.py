"""Shared shard_map wrapper for the sequence-parallel attention ops."""

from __future__ import annotations

import functools


@functools.cache
def make_sharded_attention(
    body, mesh, axis_name: str, causal: bool, head_axis: str | None = None
):
    """jit(shard_map(body)) over (q, k, v) sequence-sharded on ``axis_name``
    (and optionally head-sharded on ``head_axis`` — tensor-parallel heads
    compose with both bodies since they only collective over the sequence
    axis). Cached so repeat calls reuse the compiled executable."""
    import jax
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    spec = P(None, axis_name, head_axis, None)
    fn = shard_map(
        functools.partial(body, axis_name=axis_name, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return jax.jit(fn)
