"""Shared shard_map wrapper for the sequence-parallel attention ops."""

from __future__ import annotations

import functools


@functools.cache
def make_sharded_attention(
    body,
    mesh,
    axis_name: str,
    causal: bool,
    head_axis: str | None = None,
    impl: str | None = None,
    relax_vma: bool = False,
):
    """jit(shard_map(body)) over (q, k, v) sequence-sharded on ``axis_name``
    (and optionally head-sharded on ``head_axis`` — tensor-parallel heads
    compose with both bodies since they only collective over the sequence
    axis). ``impl`` forwards a block-body selector to bodies that take one
    (ring attention). ``relax_vma``: set by callers whose body may run a
    pallas kernel — pallas calls inside shard_map trip the vma type checker
    in interpret mode (jax's own error suggests the flag); every other body
    keeps shard_map's varying-type checking (it catches mis-specified
    collectives loudly). Cached so repeat calls reuse the compiled
    executable."""
    import jax
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    kwargs = {"axis_name": axis_name, "causal": causal}
    if impl is not None:
        kwargs["impl"] = impl
    spec = P(None, axis_name, head_axis, None)
    sm_kwargs = dict(
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )
    if relax_vma:
        # The relax knob was renamed across jax versions (check_rep ->
        # check_vma); try the current name first, then the older one. Bodies
        # running pallas kernels need ONE of them off, or shard_map's
        # replication checker rejects pallas_call outright.
        for kw in ("check_vma", "check_rep"):
            try:
                fn = shard_map(
                    functools.partial(body, **kwargs), **{kw: False}, **sm_kwargs
                )
                break
            except TypeError:  # this jax doesn't know the kwarg
                continue
        else:  # neither name exists: run with checking on
            fn = shard_map(functools.partial(body, **kwargs), **sm_kwargs)
    else:
        fn = shard_map(functools.partial(body, **kwargs), **sm_kwargs)
    return jax.jit(fn)
