"""Device-side staging ops: dtype cast for weight transfer.

The transfer-dtype cast (fp32 -> bf16 before shipping weights,
/root/reference/torchstore/state_dict_utils.py:177-189 does it on host with
torch) runs on-device here so the HBM->host copy moves half the bytes. Two
paths:

- ``device_cast``: jitted ``astype`` with buffer donation — XLA emits a
  single fused convert kernel; this is the default (the compiler already
  does the right thing for a pure elementwise op).
- ``pallas_cast``: the same op as an explicit Pallas TPU kernel, tiled to
  the VPU lane layout. Exists as the template for future fused staging
  kernels (cast+pack, cast+reduce) where XLA fusion is not enough; falls
  back to interpret mode off-TPU so it is testable on the CPU mesh.
"""

from __future__ import annotations

import functools


@functools.cache
def _cast_fn(dtype_str: str):
    import jax

    def cast(x):
        return x.astype(dtype_str)

    # No donation: the caller (a training loop publishing weights) still
    # owns and needs the original buffers after staging.
    return jax.jit(cast)


def device_cast(x, dtype):
    """On-device dtype cast (one fused XLA kernel; pallas-tiled on TPU when
    the shape allows). Used by the direct-sync source so the HBM->host copy
    moves the transfer dtype's bytes, not the param dtype's."""
    import jax
    import numpy as np

    dtype_str = str(np.dtype(dtype) if isinstance(dtype, type) else dtype)
    if jax.devices()[0].platform == "tpu":
        try:
            return pallas_cast(x, dtype_str, interpret=False)
        except Exception:  # pragma: no cover - pallas availability varies
            pass
    return _cast_fn(dtype_str)(x)


# Tile shape aligned to the TPU VPU (8 sublanes x 128 lanes).
_TILE = (8, 128)


def pallas_cast(x, dtype, interpret: bool | None = None):
    """Pallas cast kernel for 2D-tileable arrays; falls back to
    ``device_cast`` when the shape doesn't tile cleanly."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    out_dtype = jnp.dtype(dtype)
    flat = x.reshape(-1)
    n = flat.shape[0]
    cols = _TILE[0] * _TILE[1]
    if n % cols != 0:
        # Unaligned shapes take the plain fused-XLA cast (NOT device_cast,
        # which would recurse back here on TPU).
        return _cast_fn(str(out_dtype))(x)
    rows = n // _TILE[1]
    x2d = flat.reshape(rows, _TILE[1])
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"

    def kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...].astype(out_dtype)

    grid = (rows // _TILE[0],)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[pl.BlockSpec(_TILE, lambda i: (i, 0))],
        out_specs=pl.BlockSpec(_TILE, lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, _TILE[1]), out_dtype),
        interpret=interpret,
    )(x2d)
    return out.reshape(x.shape)
