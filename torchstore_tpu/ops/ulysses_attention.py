"""Ulysses-style (all-to-all) sequence-parallel attention.

The complementary long-context pattern to ring attention: instead of
rotating k/v blocks around a ring, an ``all_to_all`` re-partitions the
activations from sequence-sharded to head-sharded, each device runs dense
(flash) attention over the FULL sequence for its subset of heads, and a
second ``all_to_all`` restores sequence sharding. Two collectives per call
(vs n-1 ring hops) at the cost of O(seq) k/v memory per device — the right
trade when heads >= ring size and sequence blocks are small.

Requires num_heads % axis_size == 0.
"""

from __future__ import annotations


def ulysses_attention(q, k, v, axis_name: str, causal: bool = False):
    """Inside shard_map: q (batch, seq_local, heads, head_dim) and k/v
    (batch, seq_local, kv_heads, head_dim) sequence-sharded -> q-shaped
    output. GQA passes through natively (kv heads split over the axis like
    q heads; the inner dense attention handles the grouping)."""
    import jax
    from jax import lax

    n = lax.psum(1, axis_name)
    # seq-sharded -> head-sharded: split heads across the axis, gather seq.
    # all_to_all(x, axis, split_axis=heads, concat_axis=seq).
    def seq_to_heads(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    out = jax.nn.dot_product_attention(qh, kh, vh, is_causal=causal)
    return heads_to_seq(out)


def ulysses_attention_sharded(
    q, k, v, mesh, axis_name: str = "sp", causal: bool = False
):
    """jit-compiled all-to-all attention over ``mesh``'s ``axis_name``:
    global (batch, seq, heads, head_dim) arrays sequence-sharded on entry
    and exit. Every head axis (q AND k/v — GQA included) must be divisible
    by the axis size; repeat kv heads or use ring attention otherwise."""
    from torchstore_tpu.ops._sharded import make_sharded_attention

    axis_size = mesh.shape[axis_name]
    for name, arr in (("q", q), ("k", k), ("v", v)):
        if arr.shape[2] % axis_size != 0:
            raise ValueError(
                f"ulysses attention needs {name} heads ({arr.shape[2]}) "
                f"divisible by the {axis_name!r} axis size ({axis_size}); "
                "use ring attention for head counts below the ring size"
            )
    return make_sharded_attention(ulysses_attention, mesh, axis_name, causal)(q, k, v)
