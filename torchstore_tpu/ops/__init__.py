from torchstore_tpu.ops.staging import device_cast, pallas_cast

__all__ = ["device_cast", "pallas_cast"]
