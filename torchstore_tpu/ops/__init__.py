from torchstore_tpu.ops.flash_attention import flash_attention
from torchstore_tpu.ops.ring_attention import ring_attention, ring_attention_sharded
from torchstore_tpu.ops.staging import device_cast, pallas_cast
from torchstore_tpu.ops.ulysses_attention import (
    ulysses_attention,
    ulysses_attention_sharded,
)

__all__ = [
    "device_cast",
    "flash_attention",
    "pallas_cast",
    "ring_attention",
    "ring_attention_sharded",
    "ulysses_attention",
    "ulysses_attention_sharded",
]
