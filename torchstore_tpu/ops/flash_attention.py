"""Pallas flash attention for TPU.

Blockwise causal/full attention as an explicit Pallas kernel: q/k/v stream
through VMEM in (block_q x d) / (block_k x d) tiles, scores hit the MXU via
``dot_general`` in fp32, and the online-softmax state (running max, running
denominator, fp32 accumulator) lives in VMEM scratch that persists across
the innermost k-block grid dimension (TPU grids execute sequentially, so
the scratch carries between j-steps of the same q block). Causal q-blocks
skip k-blocks entirely above the diagonal and mask only the diagonal block.

This is the single-device inner kernel of the attention stack: the
sequence-parallel layers (``ring_attention`` / ``ulysses_attention``) handle
cross-device movement, and their per-device block math is exactly what this
kernel computes. Off-TPU it runs in interpret mode (tested against dense
attention); on TPU it compiles to a fused VMEM-resident loop.

Layout: (batch, seq, heads, head_dim) in, same out. GQA maps kv heads via
the BlockSpec index maps (no repetition). Block sizes must divide the
sequence lengths (and causal needs sq <= sk); the public wrapper falls back
to dense attention otherwise.
"""

from __future__ import annotations

import functools
import math

NEG_INF = -1e30


def _kernel(
    q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *, scale, causal, block_q, block_k
):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    i = pl.program_id(1)  # q block
    j = pl.program_id(2)  # k block (innermost: scratch carries across j)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    should_run = True if not causal else (j <= i)

    @pl.when(should_run)
    def _block():
        q = q_ref[0].astype(jnp.float32)  # (block_q, d)
        k = k_ref[0].astype(jnp.float32)  # (block_k, d)
        v = v_ref[0].astype(jnp.float32)
        s = (
            jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )
            * scale
        )  # (block_q, block_k)
        if causal:
            # Only the diagonal block needs masking: for j < i every q
            # position is strictly after every k position (block_q ==
            # block_k is enforced by the wrapper).
            rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) + i * block_q
            cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + j * block_k
            s_eff = jnp.where((j < i) | (rows >= cols), s, NEG_INF)
        else:
            s_eff = s
        m_prev = m_ref[:, 0:1]
        l_prev = l_ref[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s_eff, axis=1, keepdims=True))
        p = jnp.exp(s_eff - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_ref[:, 0:1] = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_ref[:, 0:1] = m_new

    last_j = i if causal else pl.num_programs(2) - 1

    @pl.when(j == last_j)
    def _finish():
        denom = jnp.maximum(l_ref[:, 0:1], 1e-30)
        o_ref[0] = (acc_ref[...] / denom).astype(o_ref.dtype)


@functools.cache
def _jitted(causal: bool, block_q: int, block_k: int, interpret: bool):
    import jax

    return jax.jit(
        functools.partial(
            _flash,
            causal=causal,
            block_q=block_q,
            block_k=block_k,
            interpret=interpret,
        )
    )


def _flash(q, k, v, *, causal: bool, block_q: int, block_k: int, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, sq, h, d = q.shape
    sk, hk = k.shape[1], k.shape[2]
    g = h // hk
    scale = 1.0 / math.sqrt(d)

    # (b, s, h, d) -> (b*h, s, d) flattened per-head programs.
    qf = jnp.transpose(q, (0, 2, 1, 3)).reshape(b * h, sq, d)
    kf = jnp.transpose(k, (0, 2, 1, 3)).reshape(b * hk, sk, d)
    vf = jnp.transpose(v, (0, 2, 1, 3)).reshape(b * hk, sk, d)

    def kv_index(bh, i, j):
        # GQA: q program bh = batch*h + head; its kv row is batch*hk + head//g.
        return (bh // h) * hk + (bh % h) // g, j, 0

    grid = (b * h, sq // block_q, sk // block_k)
    out = pl.pallas_call(
        functools.partial(
            _kernel, scale=scale, causal=causal, block_q=block_q, block_k=block_k
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, block_k, d), kv_index),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),   # fp32 accumulator
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max (col 0)
            pltpu.VMEM((block_q, 128), jnp.float32),  # running denom (col 0)
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return jnp.transpose(out.reshape(b, h, sq, d), (0, 2, 1, 3))


def flash_attention(
    q,
    k,
    v,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
):
    """Pallas flash attention; falls back to ``jax.nn.dot_product_attention``
    when shapes don't tile (seq not divisible by blocks, tiny head_dim)."""
    import jax

    b, sq, h, d = q.shape
    sk, hk = k.shape[1], k.shape[2]
    if (
        sq % block_q != 0
        or sk % block_k != 0
        or block_q != block_k
        or h % hk != 0
        or d % 8 != 0
        # Causal with sq > sk would leave q-blocks past the last k-block
        # unwritten (their diagonal lies outside the j grid).
        or (causal and sq > sk)
    ):
        return jax.nn.dot_product_attention(q, k, v, is_causal=causal)
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    return _jitted(causal, block_q, block_k, interpret)(q, k, v)
