"""Pallas blockwise attention for TPU — scope and role:

- **Production role (the reason this kernel exists):
  ``flash_attention_stats``** — the per-hop inner engine of
  ``ring_attention``'s fused body. Sequence-parallel merging needs the
  UNNORMALIZED accumulator plus the online-softmax running max/denominator
  per block; XLA's fused attention cannot emit those, so a bespoke kernel
  is the only way to run ring hops without materializing (sq, sk) score
  tensors in HBM.
- **Explicitly NOT the production dense kernel**: whole-sequence
  ``flash_attention`` measures ~120 TFLOP/s on v5e vs ~290 for XLA's own
  fused attention at the same shapes (BASELINE.md) — the model's dense
  path therefore uses ``jax.nn.dot_product_attention``
  (models/llama.py:_attend), and this module's normalized entry remains as
  the stats kernel's differential-test twin (same block body, one extra
  normalization) and the off-TPU interpret-mode reference.

Mechanics: q/k/v stream through VMEM in (block_q x d) / (block_k x d)
tiles, scores hit the MXU via ``dot_general`` in fp32, and the
online-softmax state (running max, running denominator, fp32 accumulator)
lives in VMEM scratch that persists across the innermost k-block grid
dimension (TPU grids execute sequentially, so the scratch carries between
j-steps of the same q block). Causal q-blocks skip k-blocks entirely above
their row range and mask with global positions.

This module is the single-device inner layer of the attention stack: the
sequence-parallel ops handle cross-device movement and call in here for the
per-device block math. ``ring_attention``'s fused body invokes
``flash_attention_stats`` (the same blockwise kernel, returning the
unnormalized accumulator plus the online-softmax running max/denominator)
once per ring hop and merges the per-block stats across hops;
``ulysses_attention`` runs whole-sequence attention per head shard. Off-TPU
the kernels run in interpret mode (tested against dense attention); on TPU
they compile to fused VMEM-resident loops.

Layout: (batch, seq, heads, head_dim) in, same out. GQA maps kv heads via
the BlockSpec index maps (no repetition). Block sizes must divide the
sequence lengths (and causal needs sq <= sk); the public wrapper falls back
to dense attention otherwise.
"""

from __future__ import annotations

import functools
import math

NEG_INF = -1e30


def _kernel(
    q_ref,
    k_ref,
    v_ref,
    *refs,
    scale,
    causal,
    block_q,
    block_k,
    emit_stats,
):
    """One blockwise online-softmax kernel for both public ops.

    ``emit_stats=False``: refs = (o_ref, acc, m, l scratch); the final
    k-block writes the NORMALIZED output (``flash_attention``).
    ``emit_stats=True``: refs = (acc_out, m_out, l_out, acc, m, l scratch);
    the final k-block writes the raw fp32 accumulator plus the running
    max/denominator so a sequence-parallel caller (ring attention) can
    merge per-device blocks with the standard flash rescale.

    ``causal`` masks with positions i*block_q+row vs j*block_k+col — global
    causal for whole-sequence calls, and exactly the diagonal-block mask
    for the ring's own (offset-aligned) block."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    if emit_stats:
        acc_out, m_out, l_out, acc_ref, m_ref, l_ref = refs
    else:
        o_ref, acc_ref, m_ref, l_ref = refs

    i = pl.program_id(1)  # q block
    j = pl.program_id(2)  # k block (innermost: scratch carries across j)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # Causal: k-blocks entirely above the q block's last row contribute
    # nothing and are skipped outright (block_q == block_k reduces this to
    # the classic j <= i).
    should_run = (
        True if not causal else (j * block_k <= i * block_q + block_q - 1)
    )

    @pl.when(should_run)
    def _block():
        q = q_ref[0].astype(jnp.float32)  # (block_q, d)
        k = k_ref[0].astype(jnp.float32)  # (block_k, d)
        v = v_ref[0].astype(jnp.float32)
        s = (
            jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )
            * scale
        )  # (block_q, block_k)
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) + i * block_q
            cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + j * block_k
            s_eff = jnp.where(rows >= cols, s, NEG_INF)
        else:
            s_eff = s
        m_prev = m_ref[:, 0:1]
        l_prev = l_ref[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s_eff, axis=1, keepdims=True))
        p = jnp.exp(s_eff - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_ref[:, 0:1] = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_ref[:, 0:1] = m_new

    # The last k-block this q block visits (skipped causal blocks excluded).
    if causal:
        last_j = jnp.minimum(
            pl.num_programs(2) - 1, (i * block_q + block_q - 1) // block_k
        )
    else:
        last_j = pl.num_programs(2) - 1

    @pl.when(j == last_j)
    def _finish():
        if emit_stats:
            acc_out[0] = acc_ref[...]
            m_out[0] = m_ref[...]
            l_out[0] = l_ref[...]
        else:
            denom = jnp.maximum(l_ref[:, 0:1], 1e-30)
            o_ref[0] = (acc_ref[...] / denom).astype(o_ref.dtype)


def _flash_call(
    q, k, v, *, causal: bool, block_q: int, block_k: int, interpret: bool,
    emit_stats: bool
):
    """Shared pallas plumbing for both kernel modes: flattened per-head
    programs, GQA kv index maps, vma-annotated out shapes."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, sq, h, d = q.shape
    sk, hk = k.shape[1], k.shape[2]
    g = h // hk
    scale = 1.0 / math.sqrt(d)

    # (b, s, h, d) -> (b*h, s, d) flattened per-head programs.
    qf = jnp.transpose(q, (0, 2, 1, 3)).reshape(b * h, sq, d)
    kf = jnp.transpose(k, (0, 2, 1, 3)).reshape(b * hk, sk, d)
    vf = jnp.transpose(v, (0, 2, 1, 3)).reshape(b * hk, sk, d)

    def kv_index(bh, i, j):
        # GQA: q program bh = batch*h + head; its kv row is batch*hk + head//g.
        return (bh // h) * hk + (bh % h) // g, j, 0

    def out_index(bh, i, j):
        return bh, i, 0

    def out_sds(shape, dtype):
        # Under shard_map with vma checking, pallas out_shapes must declare
        # which mesh axes the output varies over — same set as the inputs.
        try:
            vma = jax.typeof(qf).vma
        except AttributeError:
            vma = None
        if vma:
            try:
                return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
            except TypeError:  # older jax: no vma kwarg
                pass
        return jax.ShapeDtypeStruct(shape, dtype)

    if emit_stats:
        out_specs = [
            pl.BlockSpec((1, block_q, d), out_index),
            # Stats ride full (block_q, 128) lanes (col 0 meaningful) —
            # the natural TPU tile for the VMEM scratch they mirror.
            pl.BlockSpec((1, block_q, 128), out_index),
            pl.BlockSpec((1, block_q, 128), out_index),
        ]
        out_shape = [
            out_sds((b * h, sq, d), jnp.float32),
            out_sds((b * h, sq, 128), jnp.float32),
            out_sds((b * h, sq, 128), jnp.float32),
        ]
    else:
        out_specs = pl.BlockSpec((1, block_q, d), out_index)
        out_shape = out_sds((b * h, sq, d), q.dtype)

    grid = (b * h, sq // block_q, sk // block_k)
    result = pl.pallas_call(
        functools.partial(
            _kernel,
            scale=scale,
            causal=causal,
            block_q=block_q,
            block_k=block_k,
            emit_stats=emit_stats,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), out_index),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, block_k, d), kv_index),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),   # fp32 accumulator
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max (col 0)
            pltpu.VMEM((block_q, 128), jnp.float32),  # running denom (col 0)
        ],
        interpret=interpret,
    )(qf, kf, vf)
    if emit_stats:
        acc, m, l = result
        # (b*h, sq, ...) -> (b, h, sq, ...); stats keep lane col 0 only.
        return (
            acc.reshape(b, h, sq, d),
            m[:, :, 0].reshape(b, h, sq),
            l[:, :, 0].reshape(b, h, sq),
        )
    return jnp.transpose(result.reshape(b, h, sq, d), (0, 2, 1, 3))


@functools.cache
def _jitted(causal: bool, block_q: int, block_k: int, interpret: bool):
    import jax

    return jax.jit(
        functools.partial(
            _flash_call,
            causal=causal,
            block_q=block_q,
            block_k=block_k,
            interpret=interpret,
            emit_stats=False,
        )
    )


def _flash_stats(
    q, k, v, *, causal_diag: bool, block_q: int, block_k: int, interpret: bool
):
    return _flash_call(
        q,
        k,
        v,
        causal=causal_diag,
        block_q=block_q,
        block_k=block_k,
        interpret=interpret,
        emit_stats=True,
    )


def _pick_block(s: int, cap: int = 256) -> "int | None":
    """Largest power-of-two block (>=8, <=cap) dividing ``s``."""
    blk = None
    b = 8
    while b <= cap and s % b == 0:
        blk = b
        b *= 2
    return blk


def flash_stats_eligible(q_shape, k_shape) -> bool:
    """Whether ``flash_attention_stats`` can tile these per-device shapes
    (ring attention's fused-body gate; falls back to its einsum body
    otherwise)."""
    b, sq, h, d = q_shape
    sk, hk = k_shape[1], k_shape[2]
    return (
        _pick_block(sq) is not None
        and _pick_block(sk) is not None
        and h % hk == 0
        and d % 8 == 0
    )


def _stats_ref(q, k, v, causal_diag: bool):
    """Dense jnp twin of the stats kernel (same outputs, same masking
    constants) — the recompute target for the custom VJP: forward runs the
    fused pallas kernel, backward re-derives the block's gradients from
    this reference (flash's standard recompute-in-backward shape, with the
    recompute left to XLA)."""
    import jax.numpy as jnp

    b, sq, h, d = q.shape
    sk, hk = k.shape[1], k.shape[2]
    g = h // hk
    scale = 1.0 / math.sqrt(d)
    qf = jnp.transpose(q, (0, 2, 1, 3)).astype(jnp.float32)  # (b,h,sq,d)
    kf = jnp.repeat(
        jnp.transpose(k, (0, 2, 1, 3)).astype(jnp.float32), g, axis=1
    )
    vf = jnp.repeat(
        jnp.transpose(v, (0, 2, 1, 3)).astype(jnp.float32), g, axis=1
    )
    s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) * scale
    if causal_diag:
        rows = jnp.arange(sq)[:, None]
        cols = jnp.arange(sk)[None, :]
        s = jnp.where(rows >= cols, s, NEG_INF)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhqk,bhkd->bhqd", p, vf)
    return acc, m, l


@functools.cache
def _stats_diff(causal_diag: bool, block_q: int, block_k: int, interpret: bool):
    """Differentiable wrapper: pallas kernel forward, dense-reference
    recompute backward (pallas_call defines no autodiff rule; ring
    attention trains through this op)."""
    import jax

    kernel = functools.partial(
        _flash_stats,
        causal_diag=causal_diag,
        block_q=block_q,
        block_k=block_k,
        interpret=interpret,
    )

    @jax.custom_vjp
    def f(q, k, v):
        return kernel(q, k, v)

    def fwd(q, k, v):
        return kernel(q, k, v), (q, k, v)

    def bwd(res, cts):
        q, k, v = res
        _, vjp = jax.vjp(
            lambda a, b, c: _stats_ref(a, b, c, causal_diag), q, k, v
        )
        return vjp(cts)

    f.defvjp(fwd, bwd)
    return jax.jit(f)


def flash_attention_stats(q, k, v, causal_diag: bool = False, interpret=None):
    """Unnormalized blockwise attention of one kv block: returns
    ``(acc, m, l)`` with ``acc`` (b, h, sq, d) fp32 = sum_k exp(s - m) * v,
    ``m``/``l`` (b, h, sq) the running max / denominator. ``causal_diag``
    applies row>=col masking in block-local coordinates (the ring's
    diagonal block). Merge across blocks with the flash rescale:
    ``m' = max(m1, m2); acc' = acc1*e^(m1-m') + acc2*e^(m2-m')`` etc.
    Differentiable: backward recomputes the block densely (see
    ``_stats_diff``)."""
    import jax

    block_q = _pick_block(q.shape[1])
    block_k = _pick_block(k.shape[1])
    if block_q is None or block_k is None:
        raise ValueError(
            f"sequence lengths {q.shape[1]}/{k.shape[1]} don't tile; gate "
            "with flash_stats_eligible()"
        )
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    return _stats_diff(causal_diag, block_q, block_k, interpret)(q, k, v)


def flash_attention(
    q,
    k,
    v,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
):
    """Pallas flash attention; falls back to ``jax.nn.dot_product_attention``
    when shapes don't tile (seq not divisible by blocks, tiny head_dim)."""
    import jax

    b, sq, h, d = q.shape
    sk, hk = k.shape[1], k.shape[2]
    if (
        sq % block_q != 0
        or sk % block_k != 0
        or block_q != block_k
        or h % hk != 0
        or d % 8 != 0
        # Causal with sq > sk would leave q-blocks past the last k-block
        # unwritten (their diagonal lies outside the j grid).
        or (causal and sq > sk)
    ):
        return jax.nn.dot_product_attention(q, k, v, is_causal=causal)
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    return _jitted(causal, block_q, block_k, interpret)(q, k, v)
