"""Relay-tree topology for one-to-many broadcast weight distribution.

Pure topology math for the controller's broadcast layer (controller.py's
relay engine): given the ORIGIN volume a publisher's layers land on and the
set of member volumes whose hosts subscribed to a channel, compute the tree
each published version flows down — volume-to-volume ``pull_from`` hops, one
copy per host — and re-route it when a relay node dies.

Shape invariants:

- **The root's out-degree is always 1.** Trainer-host egress is the scarce
  resource the whole design exists to bound: however many generator fleets
  subscribe, the origin volume serves exactly ONE relay copy per version
  (O(1) trainer-host egress); interior nodes fan out at
  ``TORCHSTORE_TPU_RELAY_FANOUT``.
- **Deterministic.** Members are ordered by sorted volume id and assigned
  breadth-first, so every controller (and every test) derives the same tree
  from the same membership.
- **Re-parenting never orphans progress.** A dead node's children re-attach
  to its nearest healthy ancestor (ultimately the root); the relay engine
  keeps each child's landed-key set across the move, so a re-parented
  subtree resumes from its last landed watermark and never re-pulls layers
  it already holds.

Everything here is synchronous, side-effect-free, and unit-testable without
a fleet; the asyncio engine that drives pulls lives in controller.py.
"""

from __future__ import annotations

from collections import deque
from typing import Iterable, Optional

# The root (origin volume) forwards to exactly one child regardless of the
# configured interior fanout — see the module docstring.
ROOT_FANOUT = 1


def build_tree(
    root: str,
    members: Iterable[str],
    fanout: int,
    prefer: Optional[Iterable[str]] = None,
) -> dict[str, str]:
    """Parent map ``{child: parent}`` over ``members`` rooted at ``root``.

    ``root`` (the origin volume) is excluded from the member set if listed;
    it takes :data:`ROOT_FANOUT` children, every other node up to
    ``fanout``. Members are attached breadth-first in sorted-id order —
    unless ``prefer`` names members first (the control plane's measured
    edge-proximity order: heaviest consumers attach nearest the root);
    unnamed members follow in sorted-id order, so the tree stays
    deterministic for any (members, prefer) pair. Returns ``{}`` when
    there is nothing to relay to.
    """
    fanout = max(1, int(fanout))
    pool = set(members) - {root}
    order = [v for v in (prefer or ()) if v in pool]
    order += sorted(pool - set(order))
    parents: dict[str, str] = {}
    slots: deque[list] = deque()
    slots.append([root, ROOT_FANOUT])
    for vid in order:
        while slots and slots[0][1] <= 0:
            slots.popleft()
        if not slots:  # unreachable: every attached member adds capacity
            slots.append([root, ROOT_FANOUT])
        node = slots[0]
        node[1] -= 1
        parents[vid] = node[0]
        slots.append([vid, fanout])
    return parents


def healthy_ancestor(
    parents: dict[str, str], root: str, start: str, down: set[str]
) -> str:
    """First ancestor of ``start`` (inclusive) not in ``down``, walking the
    parent chain and bottoming out at ``root`` — the node an orphaned
    subtree re-attaches to. The root is returned even if listed down (a
    dead origin means the publisher is gone; there is nothing better)."""
    node = start
    seen: set[str] = set()
    while node in down and node != root and node not in seen:
        seen.add(node)
        node = parents.get(node, root)
    return node if node not in down or node == root else root


def reparent(
    parents: dict[str, str], root: str, down: set[str]
) -> tuple[dict[str, str], dict[str, tuple[str, str]]]:
    """Drop ``down`` nodes from the tree and re-attach their orphaned
    children to their nearest healthy ancestor. Returns ``(new_parents,
    moved)`` where ``moved`` maps each re-parented child to its
    ``(old_parent, new_parent)`` edge — the engine records one
    flight-recorder decision per entry."""
    new: dict[str, str] = {}
    moved: dict[str, tuple[str, str]] = {}
    for child, parent in parents.items():
        if child in down:
            continue  # dead nodes leave the tree entirely
        if parent in down:
            anc = healthy_ancestor(parents, root, parent, down)
            new[child] = anc
            moved[child] = (parent, anc)
        else:
            new[child] = parent
    return new, moved


def depth_of(
    parents: dict[str, str], root: str, node: str
) -> Optional[int]:
    """Hops from ``root`` to ``node`` (0 for the root itself); None when
    ``node`` is not in the tree or the chain is broken/cyclic."""
    if node == root:
        return 0
    hops = 0
    seen: set[str] = set()
    while node in parents:
        if node in seen:
            return None
        seen.add(node)
        node = parents[node]
        hops += 1
        if node == root:
            return hops
    return None
